CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE second_impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE union_output (
  counter BIGINT,
  source TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO union_output
SELECT counter, 'first' as source FROM impulse_source
UNION ALL SELECT counter, 'second' as source FROM second_impulse_source;
