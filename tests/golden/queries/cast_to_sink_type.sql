CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE cars_output (
  timestamp TIMESTAMP,
  driver_id TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO cars_output SELECT timestamp, driver_id FROM cars;
