"""TLS on the control plane (gRPC) and data plane (Arrow-IPC TCP): a full
embedded cluster runs with mutual TLS, and plaintext clients are refused."""

import asyncio
import datetime
import json

import pytest

from arroyo_tpu.config import update
from arroyo_tpu.controller.controller import ControllerServer, JobState
from arroyo_tpu.controller.scheduler import EmbeddedScheduler


def make_certs(tmp_path):
    """Self-signed CA + one leaf cert (server+client auth, DNS SAN
    arroyo-tpu) written as PEM files."""
    pytest.importorskip(
        "cryptography", reason="cryptography package not installed"
    )
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    now = datetime.datetime.now(datetime.timezone.utc)

    def name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(name("arroyo-tpu-test-ca"))
        .issuer_name(name("arroyo-tpu-test-ca"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    leaf_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    leaf_cert = (
        x509.CertificateBuilder()
        .subject_name(name("arroyo-tpu"))
        .issuer_name(ca_cert.subject)
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("arroyo-tpu")]),
            critical=False,
        )
        .add_extension(
            x509.ExtendedKeyUsage([ExtendedKeyUsageOID.SERVER_AUTH,
                                   ExtendedKeyUsageOID.CLIENT_AUTH]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    paths = {}
    for fname, data in [
        ("ca.pem", ca_cert.public_bytes(serialization.Encoding.PEM)),
        ("cert.pem", leaf_cert.public_bytes(serialization.Encoding.PEM)),
        ("key.pem", leaf_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )),
    ]:
        p = tmp_path / fname
        p.write_bytes(data)
        paths[fname.split(".")[0]] = str(p)
    return paths


def test_cluster_with_mutual_tls(tmp_path):
    """2 embedded workers under mTLS: gRPC control plane AND the
    cross-worker TCP shuffle both ride TLS; exact output proves it."""
    certs = make_certs(tmp_path)
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '2000', start_time = '0'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{tmp_path}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 8 as k, tumble(interval '1 millisecond') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """

    async def go():
        c = await ControllerServer(EmbeddedScheduler()).start()
        await c.submit_job("tls1", sql=sql, n_workers=2, parallelism=2)
        state = await c.wait_for_state(
            "tls1", JobState.FINISHED, JobState.FAILED, timeout=60
        )
        addr = c.addr
        await c.stop()
        return state, addr

    with update(tls={"enabled": True, "cert": certs["cert"],
                     "key": certs["key"], "ca": certs["ca"]}):
        state, addr = asyncio.run(go())
    assert state == JobState.FINISHED
    from collections import Counter

    counts = Counter()
    with open(tmp_path / "out.json") as f:
        for line in f:
            if line.strip():
                r = json.loads(line)
                counts[r["k"]] += r["cnt"]
    assert dict(counts) == {k: 250 for k in range(8)}


def test_plaintext_client_refused_by_tls_server(tmp_path):
    certs = make_certs(tmp_path)
    from arroyo_tpu.engine.rpc import RpcServer, RpcClient

    async def go():
        with update(tls={"enabled": True, "cert": certs["cert"],
                         "key": certs["key"], "ca": certs["ca"]}):
            server = RpcServer()

            async def ping(req):
                return {"pong": True}

            server.add_service("T", {"Ping": ping})
            port = await server.start()
        # plaintext channel against the TLS port must fail
        client = RpcClient(f"127.0.0.1:{port}")
        with pytest.raises(Exception):
            await client.call("T", "Ping", {}, timeout=5.0)
        await client.close()
        # a TLS client with the right material succeeds
        with update(tls={"enabled": True, "cert": certs["cert"],
                         "key": certs["key"], "ca": certs["ca"]}):
            secure = RpcClient(f"127.0.0.1:{port}")
            resp = await secure.call("T", "Ping", {}, timeout=10.0)
            await secure.close()
        await server.stop()
        return resp

    assert asyncio.run(go()) == {"pong": True}


def test_tls_requires_explicit_ca(tmp_path):
    """enabled without a CA must fail fast, not run encrypted-but-
    unauthenticated."""
    certs = make_certs(tmp_path)
    from arroyo_tpu.utils.tls import data_client_context

    with update(tls={"enabled": True, "cert": certs["cert"],
                     "key": certs["key"], "ca": ""}):
        with pytest.raises(ValueError, match="tls.ca"):
            data_client_context()
