"""arroyolint engine tests: per-rule fixture pairs, suppression comments,
baseline round-trips, and the tier-1 gate that keeps the real tree clean.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from arroyo_tpu.analysis import Baseline, all_rules, get_rule, run_lint
from arroyo_tpu.analysis.baseline import DEFAULT_BASELINE
from arroyo_tpu.analysis.engine import collect_files, parse_project
from arroyo_tpu.analysis.rules_jax_config import config_key_table

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "lint_fixtures"

RULE_IDS = [r.id for r in all_rules()]


def run_one(rule_id: str, root: Path):
    return run_lint(root, rules=[get_rule(rule_id)], roots=(".",))


# -- rule fixtures -----------------------------------------------------------


def test_registry_size():
    # ISSUE 3 acceptance: at least 8 registered rules
    assert len(all_rules()) >= 8
    assert len(RULE_IDS) == len(set(RULE_IDS))


def test_race_family_registered():
    # ISSUE 18: the RACE family must stay registered — if its rule-module
    # import were dropped, the parametrized fixture tests would silently
    # shrink instead of failing
    for rid in ("RACE001", "RACE002", "RACE003", "RACE004"):
        assert rid in RULE_IDS, f"{rid} not registered"


def test_every_rule_has_fixture_pair():
    # meta-test: a rule without fixtures is an unproven rule
    for rule in all_rules():
        fire = FIXTURES / rule.id / "fire"
        clean = FIXTURES / rule.id / "clean"
        assert fire.is_dir() and list(fire.rglob("*.py")), (
            f"{rule.id} has no firing fixture"
        )
        assert clean.is_dir() and list(clean.rglob("*.py")), (
            f"{rule.id} has no clean fixture"
        )


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_fixture_fires(rule_id):
    res = run_one(rule_id, FIXTURES / rule_id / "fire")
    assert not res.errors, res.errors
    assert res.findings, f"{rule_id} found nothing in its firing fixture"
    assert all(f.rule == rule_id for f in res.findings)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_fixture_clean(rule_id):
    res = run_one(rule_id, FIXTURES / rule_id / "clean")
    assert not res.errors, res.errors
    assert not res.findings, (
        f"{rule_id} false-positives on its clean fixture: "
        + "; ".join(f"{f.path}:{f.line} {f.message}" for f in res.findings)
    )


def test_rules_have_metadata():
    for rule in all_rules():
        assert rule.name and rule.description, rule.id
        assert rule.scope in ("file", "project"), rule.id


# -- suppressions ------------------------------------------------------------

_DANGLING = (
    "import asyncio\n\n\n"
    "async def go():\n"
    "    asyncio.create_task(go()){comment}\n"
)


def _lint_source(tmp_path, source, rule_id="ASY001"):
    (tmp_path / "mod.py").write_text(source)
    return run_one(rule_id, tmp_path)


def test_finding_without_suppression(tmp_path):
    res = _lint_source(tmp_path, _DANGLING.format(comment=""))
    assert len(res.findings) == 1


def test_line_suppression(tmp_path):
    res = _lint_source(
        tmp_path,
        _DANGLING.format(comment="  # arroyolint: disable=ASY001"),
    )
    assert not res.findings


def test_line_suppression_wrong_rule_does_not_apply(tmp_path):
    res = _lint_source(
        tmp_path,
        _DANGLING.format(comment="  # arroyolint: disable=ASY002"),
    )
    assert len(res.findings) == 1


def test_file_suppression(tmp_path):
    src = "# arroyolint: disable-file=ASY001\n" + _DANGLING.format(comment="")
    res = _lint_source(tmp_path, src)
    assert not res.findings


def test_file_suppression_must_be_near_top(tmp_path):
    src = _DANGLING.format(comment="") + (
        "\n" * 20 + "# arroyolint: disable-file=ASY001\n"
    )
    res = _lint_source(tmp_path, src)
    assert len(res.findings) == 1


# -- baseline ----------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    src = _DANGLING.format(comment="")
    (tmp_path / "mod.py").write_text(src)
    first = run_one("ASY001", tmp_path)
    assert len(first.findings) == 1

    bl_path = tmp_path / "baseline.json"
    bl = Baseline.from_findings(first.findings, justification="known debt")
    bl.save(bl_path)
    bl2 = Baseline.load(bl_path)
    assert bl2.entries == bl.entries

    second = run_lint(
        tmp_path, rules=[get_rule("ASY001")], roots=(".",), baseline=bl2
    )
    assert not second.findings
    assert len(second.grandfathered) == 1
    assert not second.stale_baseline


def test_baseline_stale_detection(tmp_path):
    (tmp_path / "mod.py").write_text(_DANGLING.format(comment=""))
    bl = Baseline(
        [
            {
                "rule": "ASY001",
                "path": "gone.py",
                "message": "result of create_task() discarded",
                "justification": "was real once",
            }
        ]
    )
    res = run_lint(
        tmp_path, rules=[get_rule("ASY001")], roots=(".",), baseline=bl
    )
    assert len(res.findings) == 1  # mod.py finding is NOT matched by gone.py
    assert len(res.stale_baseline) == 1
    assert not res.strict_ok(bl)


def test_baseline_unjustified_blocks_strict(tmp_path):
    (tmp_path / "mod.py").write_text(_DANGLING.format(comment=""))
    first = run_one("ASY001", tmp_path)
    bl = Baseline.from_findings(first.findings)  # default TODO justification
    assert bl.unjustified()
    res = run_lint(
        tmp_path, rules=[get_rule("ASY001")], roots=(".",), baseline=bl
    )
    assert not res.findings
    assert not res.strict_ok(bl)


# -- the real tree (tier-1 gate) --------------------------------------------


def test_full_tree_strict_clean():
    """ISSUE 3 acceptance: the whole package lints clean under every rule,
    modulo a justified (currently empty) committed baseline."""
    baseline = Baseline.load(REPO / DEFAULT_BASELINE)
    res = run_lint(REPO, baseline=baseline)
    assert not res.errors, "\n".join(f"{f.path}: {f.message}" for f in res.errors)
    assert not res.findings, "\n".join(
        f"{f.path}:{f.line} [{f.rule}] {f.message}" for f in res.findings
    )
    assert res.strict_ok(baseline)
    assert res.n_files > 100  # sanity: the walk actually covered the tree


def test_committed_baseline_is_justified():
    bl = Baseline.load(REPO / DEFAULT_BASELINE)
    assert not bl.unjustified(), (
        "baseline entries need a human-written justification"
    )


def test_config_table_matches_declared_tree():
    project = parse_project(REPO, collect_files(REPO))
    table = dict(config_key_table(project))
    assert len(table) >= 50
    # spot checks against known declarations
    assert table["tpu.mesh_devices"] == "0"
    assert table["pipeline.checkpointing.interval"] == "10.0"
    assert table["worker.heartbeat_interval"] == "2.0"
    # every key the engine actually reads resolves (CFG001 enforces this;
    # double-check a few hot ones end-to-end)
    for key in ("tpu.enabled", "controller.scheduler", "chaos.plan"):
        assert key in table


# -- CLI ---------------------------------------------------------------------


def test_cli_strict_and_json(tmp_path):
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), "--strict"],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr

    js = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), "--json"],
        capture_output=True, text=True, timeout=300,
    )
    assert js.returncode == 0, js.stdout + js.stderr
    data = json.loads(js.stdout)
    assert data["summary"]["clean"] is True
    assert data["findings"] == []


def test_cli_list_rules():
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), "--list-rules"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0
    for rule in all_rules():
        assert rule.id in out.stdout
