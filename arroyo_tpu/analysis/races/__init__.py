"""arroyoracer — asyncio race & atomicity analysis (ISSUE 18).

Every past concurrency bug in this tree (PR 2's stranded commits, PR 9's
stop-path holes, PR 10's heartbeat stampede) was an *interleaving* bug:
correct-looking code whose shared state was mutated by another task
between a read and the dependent write. Per-file AST rules cannot see
that — the read, the yield point, and the conflicting writer live in
different functions, files, and task-spawn roots. This package is the
lockset/happens-before answer (Eraser, SOSP'97; FastTrack, PLDI'09)
adapted to asyncio's cooperative model, in two cooperating halves:

static (``callgraph`` + ``rules_races``)
    A project-wide interprocedural engine: a cross-file call graph with
    async-context propagation (which functions run under which
    task-spawn roots — runner loop, control pump, heartbeat, checkpoint
    flush, failover manager, TimerWheel callbacks), locksets propagated
    through call edges, and the RACE00x rule family over fields declared
    with the ``shared_state``/``guarded_by`` annotation DSL
    (``annotations``, runtime no-op like ``@protocol_effect``):

      RACE001  shared field written from >= 2 task roots with no common
               lock and no ``multi_writer`` declaration
      RACE002  atomicity violation: a read of shared state crosses an
               ``await`` before the dependent write, with no
               revalidation (the asyncio TOCTOU)
      RACE003  ``guarded_by`` field accessed without holding its lock
      RACE004  awaiting while holding a ``guarded_by`` lock whose
               fields a concurrent task root mutates

static debugging: ``tools/lint.py --call-graph`` dumps roots ->
reachable functions -> shared-field accesses as JSON.

dynamic (``sanitizer``)
    An opt-in interleaving sanitizer (``ARROYO_RACE_SANITIZER=1``):
    annotated classes get access-recording instrumentation keyed by
    (task root, yield epoch); lost-update windows (read -> another
    root's write -> write-back) and undeclared cross-root write/write
    pairs are flagged live. Wired into the chaos drill runner and the
    ``runner.stall``-driven starvation drill
    (``tools/chaos_drill.py --starvation``).
"""

from .annotations import (  # noqa: F401 - public surface
    GUARDED_BY_ATTR,
    SHARED_STATE_ATTR,
    guarded_by,
    shared_state,
)
