"""MUST fire ASY003: await while holding a sync threading lock."""
import threading

LOCK = threading.Lock()


class Thing:
    def __init__(self):
        self._lock = threading.Lock()

    async def go(self, q):
        with self._lock:
            await q.get()


async def module_level(q):
    with LOCK:
        await q.get()
