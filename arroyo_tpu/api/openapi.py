"""OpenAPI 3.0 spec for the REST API, generated from the route table.

Capability parity with the reference's utoipa-generated spec
(/root/reference/crates/arroyo-api/src/lib.rs ApiDoc + api-types): the same
route table drives BOTH aiohttp router registration (rest.py build_app) and
the spec served at /api/v1/openapi.json, so the document cannot drift from
the actual surface.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# (method, path, handler attr, summary, tag, request schema, response schema)
Route = Tuple[str, str, str, str, str, Optional[str], Optional[str]]

ROUTES: List[Route] = [
    ("get", "/ping", "ping", "Liveness check", "ping", None, None),
    ("post", "/pipelines/validate_query", "validate_query",
     "Validate SQL and return the planned dataflow graph or errors",
     "pipelines", "ValidateQueryPost", "QueryValidationResult"),
    ("post", "/pipelines/preview", "preview_pipeline",
     "Run a bounded preview of a query, buffering sampled output",
     "pipelines", "PipelinePost", "Pipeline"),
    ("get", "/pipelines/preview/{id}/output", "preview_output",
     "Fetch buffered preview output rows", "pipelines", None,
     "OutputData"),
    ("get", "/pipelines/preview/{id}/output/ws", "preview_output_ws",
     "Stream preview output over a websocket", "pipelines", None, None),
    ("post", "/pipelines", "create_pipeline",
     "Create and start a pipeline", "pipelines", "PipelinePost",
     "Pipeline"),
    ("get", "/pipelines", "list_pipelines", "List pipelines",
     "pipelines", None, "PipelineCollection"),
    ("get", "/pipelines/{id}", "get_pipeline", "Get one pipeline",
     "pipelines", None, "Pipeline"),
    ("patch", "/pipelines/{id}", "patch_pipeline",
     "Update stop mode / parallelism / checkpoint interval",
     "pipelines", "PipelinePatch", "Pipeline"),
    ("delete", "/pipelines/{id}", "delete_pipeline",
     "Stop and delete a pipeline", "pipelines", None, None),
    ("post", "/pipelines/{id}/restart", "restart_pipeline",
     "Restart a pipeline (optionally force without checkpoint)",
     "pipelines", "PipelineRestart", "Pipeline"),
    ("get", "/pipelines/{id}/jobs", "pipeline_jobs",
     "Jobs for one pipeline", "jobs", None, "JobCollection"),
    ("get", "/jobs", "all_jobs", "All jobs across pipelines", "jobs",
     None, "JobCollection"),
    ("get", "/jobs/{job_id}/checkpoints", "job_checkpoints",
     "Checkpoints of a job", "jobs", None, "CheckpointCollection"),
    ("get", "/jobs/{job_id}/checkpoints/{epoch}/operator_checkpoint_groups",
     "operator_checkpoint_groups",
     "Per-operator detail of one checkpoint: per-subtask state sizes, "
     "file/row counts and watermarks", "jobs", None,
     "OperatorCheckpointGroupCollection"),
    ("get", "/jobs/{job_id}/errors", "job_errors",
     "Operator error reports of a job", "jobs", None,
     "JobLogMessageCollection"),
    ("get", "/jobs/{job_id}/traces", "job_traces",
     "Flight-recorder spans of a job (checkpoint epochs, lifecycle "
     "events) as Perfetto-loadable Chrome trace-event JSON", "jobs",
     None, "TraceDump"),
    ("get", "/jobs/{job_id}/latency", "job_latency",
     "Latency-marker histograms (per-operator transit + end-to-end at "
     "the sinks) and XLA compile/dispatch telemetry of a job", "jobs",
     None, "LatencyReport"),
    ("get", "/jobs/{job_id}/doctor", "job_doctor",
     "Bottleneck doctor: ranked limiting-factor verdict (host-bound / "
     "device-bound / exchange-bound / starved / noisy-neighbor) naming "
     "the limiting operator — and, for noisy-neighbor, the co-resident "
     "tenant suspected of holding the shared worker", "jobs",
     None, "DoctorReport"),
    ("get", "/jobs/{job_id}/state", "job_state_tables",
     "Queryable state tables of a running job (StateServe): every keyed "
     "operator view with key/value fields and the published epoch reads "
     "serve at", "state", None, "StateTableCollection"),
    ("get", "/jobs/{job_id}/state/{table}", "job_state_get",
     "Point lookup of one key's aggregate at the last published "
     "checkpoint epoch (?key=K; JSON-encoded for non-string keys)",
     "state", None, "StateReadResult"),
    ("post", "/jobs/{job_id}/state/{table}", "job_state_bulk",
     "Bulk multi-key lookup: durable jobs serve follower-first off the "
     "checkpoint stream (staleness-bounded, zero worker RPCs); "
     "remaining keys fan out to their owning workers concurrently and "
     "merge into one epoch-consistent response",
     "state", "StateReadPost", "StateReadResult"),
    ("get", "/jobs/{job_id}/alerts", "job_alerts",
     "Watchtower SLO state of a job: per-rule alert states (ok / "
     "pending / firing / clearing with hysteresis) and the job's slice "
     "of the firing/cleared ledger with cause series attached", "jobs",
     None, "AlertReport"),
    ("get", "/jobs/{job_id}/metrics/history", "job_metrics_history",
     "Retained metric history of a job: windowed samples plus derived "
     "rate / delta / quantiles per series (?series= narrows to one "
     "family, ?window= seconds of lookback)", "jobs", None,
     "MetricHistory"),
    ("get", "/jobs/{job_id}/audit", "job_audit",
     "Conservation ledger of a job: per-edge epoch attestations "
     "(sender/receiver row counts + order-insensitive digests), flow "
     "checks and every recorded exactly-once breach", "jobs", None,
     "AuditReport"),
    ("get", "/jobs/{job_id}/bundles", "job_bundles",
     "Diagnostic bundles captured for the job's SLO breaches (doctor "
     "verdict + flight recording + Perfetto timeline + metric-history "
     "window)", "jobs", None, "BundleCollection"),
    ("get", "/jobs/{job_id}/bundles/{n}", "job_bundle",
     "Download one diagnostic bundle by sequence number", "jobs", None,
     "Bundle"),
    ("get", "/jobs/{job_id}/operator_metric_groups",
     "operator_metric_groups", "Per-operator metric groups", "jobs",
     None, "OperatorMetricGroupCollection"),
    ("get", "/jobs/{job_id}/autoscale", "job_autoscale",
     "Autoscaler decision audit log, pin state and current per-operator "
     "parallelism of a job", "jobs", None, "AutoscaleStatus"),
    ("patch", "/jobs/{job_id}/autoscale", "patch_job_autoscale",
     "Pin or unpin a job against automatic rescaling", "jobs",
     "AutoscalePatch", "AutoscaleStatus"),
    ("get", "/connectors", "list_connectors",
     "Available connector types with config schemas", "connectors",
     None, "ConnectorCollection"),
    ("get", "/connection_profiles", "list_connection_profiles",
     "List stored connection profiles", "connections", None,
     "ConnectionProfileCollection"),
    ("post", "/connection_profiles", "create_connection_profile",
     "Store a connection profile", "connections",
     "ConnectionProfilePost", "ConnectionProfile"),
    ("get", "/connection_tables", "list_connection_tables",
     "List stored connection tables", "connections", None,
     "ConnectionTableCollection"),
    ("post", "/connection_tables", "create_connection_table",
     "Store a connection table", "connections", "ConnectionTablePost",
     "ConnectionTable"),
    ("delete", "/connection_tables/{id}", "delete_connection_table",
     "Delete a connection table", "connections", None, None),
    ("post", "/connection_tables/test", "test_connection_table",
     "Validate a connection table config against its connector",
     "connections", "ConnectionTablePost", "TestSourceMessage"),
    ("post", "/udfs/validate", "validate_udf",
     "Validate a UDF definition", "udfs", "ValidateUdfPost",
     "UdfValidationResult"),
    ("post", "/udfs", "create_udf", "Register a global UDF", "udfs",
     "UdfPost", "GlobalUdf"),
    ("get", "/udfs", "list_udfs", "List global UDFs", "udfs", None,
     "GlobalUdfCollection"),
    ("delete", "/udfs/{id}", "delete_udf", "Delete a global UDF",
     "udfs", None, None),
]


def _obj(props: Dict[str, Any], required: Optional[List[str]] = None):
    out: Dict[str, Any] = {"type": "object", "properties": props}
    if required:
        out["required"] = required
    return out


def _str():
    return {"type": "string"}


def _int():
    return {"type": "integer", "format": "int64"}


def _ref(name: str):
    return {"$ref": f"#/components/schemas/{name}"}


def _collection(item: str):
    return _obj({"data": {"type": "array", "items": _ref(item)},
                 "hasMore": {"type": "boolean"}}, ["data"])


def _schemas() -> Dict[str, Any]:
    s: Dict[str, Any] = {
        "ValidateQueryPost": _obj(
            {"query": _str(), "udfs": {"type": "array", "items": _str()}},
            ["query"],
        ),
        "QueryValidationResult": _obj(
            {"graph": {"type": "object", "nullable": True},
             "errors": {"type": "array", "items": _str()}},
        ),
        "PipelinePost": _obj(
            {"name": _str(), "query": _str(),
             "parallelism": _int(),
             # multi-tenancy: admission quotas + fair slot scheduling
             # apply per tenant (default "default")
             "tenant": _str(),
             "checkpointIntervalMicros": _int(),
             "udfs": {"type": "array", "items": _str()},
             "previewSink": {"type": "boolean"}},
            ["name", "query"],
        ),
        "PipelinePatch": _obj(
            {"stop": {"type": "string",
                      "enum": ["none", "graceful", "immediate",
                               "checkpoint", "force"]},
             "parallelism": _int(),
             "checkpointIntervalMicros": _int()},
        ),
        "PipelineRestart": _obj({"force": {"type": "boolean"}}),
        "Pipeline": _obj(
            {"id": _str(), "name": _str(), "query": _str(),
             "stop": _str(), "createdAt": _int(),
             "graph": {"type": "object"},
             "preview": {"type": "boolean"}},
            ["id", "name", "query"],
        ),
        "Job": _obj(
            {"id": _str(), "pipelineId": _str(), "state": _str(),
             "runId": _int(), "startTime": {**_int(), "nullable": True},
             "finishTime": {**_int(), "nullable": True},
             "tasks": {**_int(), "nullable": True},
             "failureMessage": {**_str(), "nullable": True}},
            ["id", "state"],
        ),
        "Checkpoint": _obj(
            {"epoch": _int(), "backend": _str(),
             "startTime": _int(),
             "finishTime": {**_int(), "nullable": True},
             "spanTypes": {"type": "array", "items": _str()}},
            ["epoch"],
        ),
        "JobLogMessage": _obj(
            {"createdAt": _int(), "operatorId": {**_str(),
                                                 "nullable": True},
             "taskIndex": {**_int(), "nullable": True},
             "level": {"type": "string",
                       "enum": ["info", "warn", "error"]},
             "message": _str(), "details": _str()},
            ["message"],
        ),
        "Metric": _obj({"time": _int(), "value": {"type": "number"}}),
        "SubtaskMetrics": _obj(
            {"index": _int(),
             "metrics": {"type": "array", "items": _ref("Metric")}},
        ),
        "MetricGroup": _obj(
            {"name": _str(),
             "subtasks": {"type": "array",
                          "items": _ref("SubtaskMetrics")}},
        ),
        "OperatorMetricGroup": _obj(
            {"operatorId": _str(),
             "metricGroups": {"type": "array",
                              "items": _ref("MetricGroup")}},
        ),
        "CheckpointTableDetail": _obj(
            {"table": _str(), "kind": _str(), "bytes": _int(),
             "files": _int(), "rows": {**_int(), "nullable": True}},
        ),
        "CheckpointTaskDetail": _obj(
            {"subtask": _int(), "task_id": _str(),
             "watermark": {**_int(), "nullable": True},
             "bytes": _int(), "rows": _int(),
             "tables": {"type": "array",
                        "items": _ref("CheckpointTableDetail")}},
        ),
        "OperatorCheckpointGroup": _obj(
            {"node_id": _int(), "bytes": _int(),
             "tasks": {"type": "array",
                       "items": _ref("CheckpointTaskDetail")}},
        ),
        "Connector": _obj(
            {"id": _str(), "name": _str(), "description": _str(),
             "source": {"type": "boolean"}, "sink": {"type": "boolean"},
             "connectionConfig": {"type": "object"},
             "tableConfig": {"type": "object"}},
            ["id", "name"],
        ),
        "ConnectionProfilePost": _obj(
            {"name": _str(), "connector": _str(),
             "config": {"type": "object"}},
            ["name", "connector", "config"],
        ),
        "ConnectionProfile": _obj(
            {"id": _str(), "name": _str(), "connector": _str(),
             "config": {"type": "object"}},
            ["id", "name", "connector"],
        ),
        "ConnectionSchemaDef": _obj(
            {"fields": {"type": "array", "items": _obj(
                {"name": _str(), "type": _str(),
                 "nullable": {"type": "boolean"}})},
             "format": {**_str(), "nullable": True},
             "badData": {**_str(), "nullable": True}},
        ),
        "ConnectionTablePost": _obj(
            {"name": _str(), "connector": _str(),
             "connectionProfileId": {**_str(), "nullable": True},
             "config": {"type": "object"},
             "schema": {**_ref("ConnectionSchemaDef"),
                        "nullable": True}},
            ["name", "connector", "config"],
        ),
        "ConnectionTable": _obj(
            {"id": _str(), "name": _str(), "connector": _str(),
             "tableType": {"type": "string",
                           "enum": ["source", "sink", "lookup"]},
             "config": {"type": "object"},
             "schema": _ref("ConnectionSchemaDef")},
            ["id", "name", "connector"],
        ),
        "TestSourceMessage": _obj(
            {"error": {"type": "boolean"}, "done": {"type": "boolean"},
             "message": _str()},
            ["error", "done", "message"],
        ),
        "ValidateUdfPost": _obj({"definition": _str()}, ["definition"]),
        "UdfValidationResult": _obj(
            {"udfName": {**_str(), "nullable": True},
             "errors": {"type": "array", "items": _str()}},
        ),
        "UdfPost": _obj(
            {"prefix": {**_str(), "nullable": True},
             "definition": _str(),
             "description": {**_str(), "nullable": True}},
            ["definition"],
        ),
        "GlobalUdf": _obj(
            {"id": _str(), "name": _str(), "definition": _str(),
             "description": {**_str(), "nullable": True},
             "createdAt": _int()},
            ["id", "name", "definition"],
        ),
        "AutoscaleDecision": _obj(
            {"time": {"type": "number"}, "seq": _int(),
             "action": {"type": "string",
                        "enum": ["baseline", "warmup", "cooldown", "hold",
                                 "pinned", "unactuatable", "rescale"]},
             "restarts": _int(), "rescales": _int(),
             "pinned": {"type": "boolean"},
             "current": {"type": "object"},
             "targets": {"type": "object"},
             "reasons": {"type": "object"},
             "signals": {"type": "object"}},
            ["action"],
        ),
        "AutoscaleStatus": _obj(
            {"enabled": {"type": "boolean"}, "policy": _str(),
             "pinned": {"type": "boolean"}, "rescales": _int(),
             "parallelism": {"type": "object"},
             "decisions": {"type": "array",
                           "items": _ref("AutoscaleDecision")}},
            ["enabled", "pinned", "decisions"],
        ),
        "AutoscalePatch": _obj(
            {"pinned": {"type": "boolean"}}, ["pinned"],
        ),
        "TraceDump": _obj(
            {"traceEvents": {"type": "array", "items": {"type": "object"}},
             "displayTimeUnit": _str(),
             "spanCount": _int(),
             # present on ?fmt=perfetto exports: batch-phase ledger
             # events included as named per-(job, phase) tracks
             "phaseCount": {**_int(), "nullable": True}},
            ["traceEvents"],
        ),
        "DoctorCause": _obj(
            {"cause": {"type": "string",
                       "enum": ["host-bound", "device-bound",
                                "exchange-bound", "starved",
                                "noisy-neighbor"]},
             "score": {"type": "number"}},
            ["cause", "score"],
        ),
        "DoctorVerdict": _obj(
            {"cause": _str(), "score": {"type": "number"},
             "operator": {**_str(), "nullable": True},
             "suspect": {**_str(), "nullable": True},
             "confidence": {"type": "number"},
             "detail": _str()},
            ["cause"],
        ),
        "DoctorReport": _obj(
            {"job": _str(),
             "verdict": _ref("DoctorVerdict"),
             "ranked": {"type": "array", "items": _ref("DoctorCause")},
             "signals": {"type": "object"}},
            ["job", "verdict", "ranked"],
        ),
        "LatencySeries": _obj(
            {"job": _str(), "task": _str(), "samples": _int(),
             "mean_ms": {"type": "number"},
             "p50_ms": {"type": "number"},
             "p95_ms": {"type": "number"},
             "p99_ms": {"type": "number"}},
            ["task", "samples"],
        ),
        "LatencyReport": _obj(
            {"operators": {"type": "array", "items": _ref("LatencySeries")},
             "end_to_end": {"type": "array",
                            "items": _ref("LatencySeries")},
             "device": {"type": "object"}},
            ["operators", "end_to_end", "device"],
        ),
        "StateTable": _obj(
            {"table": _str(), "node_id": _int(), "parallelism": _int(),
             "key_fields": {"type": "array", "items": _str()},
             "key_kinds": {"type": "array", "items": _str()},
             "value_fields": {"type": "array", "items": _str()},
             "kind": {"type": "string", "enum": ["window", "updating"]},
             "routable": {"type": "boolean"},
             "live_mode": {"type": "boolean"}},
            ["table", "node_id", "parallelism"],
        ),
        "StateReadPost": _obj(
            {"keys": {"type": "array", "items": {}}}, ["keys"],
        ),
        "StateKeyResult": _obj(
            {"key": {}, "found": {"type": "boolean"},
             "value": {"type": "object", "nullable": True},
             "cached": {"type": "boolean"},
             "error": {**_str(), "nullable": True},
             "retriable": {"type": "boolean"}},
            ["found"],
        ),
        "StateReadResult": _obj(
            {"job": _str(), "table": _str(),
             "epoch": {**_int(), "nullable": True},
             # follower replicas (ISSUE 20): the epoch actually served,
             # its lag behind publication (bounded by
             # replica.max_lag_epochs — one checkpoint interval), and
             # which tier answered
             "served_epoch": {**_int(), "nullable": True},
             "staleness": _int(),
             "source": {**_str(), "enum": ["follower", "worker"]},
             "results": {"type": "array", "items": _ref("StateKeyResult")},
             "cache": {"type": "object"}},
            ["results"],
        ),
        "OutputData": _obj(
            {"rows": {"type": "array", "items": {"type": "object"}},
             "done": {"type": "boolean"},
             "error": {**_str(), "nullable": True}},
            ["rows", "done"],
        ),
        # Watchtower (ISSUE 13): SLO alerts, metric history, bundles
        "AlertEvent": _obj(
            {"ts": {"type": "number"}, "event": _str(), "job": _str(),
             "tenant": _str(), "rule": _str(),
             "value": {"type": "number", "nullable": True},
             "threshold": {"type": "number"}, "unit": _str(),
             "cause": {"type": "array", "items": {"type": "object"}}},
            ["ts", "event", "job", "rule"],
        ),
        "AlertReport": _obj(
            {"job": _str(), "alerts": {"type": "object"},
             "firing": {"type": "array", "items": _str()},
             "ledger": {"type": "array", "items": _ref("AlertEvent")}},
            ["job", "alerts", "firing", "ledger"],
        ),
        "MetricSeries": _obj(
            {"name": _str(), "labels": {"type": "object"},
             "kind": {"type": "string", "enum": ["scalar", "hist"]},
             "samples": {"type": "array",
                         "items": {"type": "array",
                                   "items": {"type": "number"}}},
             "rate": {"type": "number", "nullable": True},
             "delta": {"type": "number", "nullable": True},
             "max": {"type": "number", "nullable": True},
             "quantiles": {"type": "object", "nullable": True}},
            ["name", "labels", "kind", "samples"],
        ),
        "MetricHistory": _obj(
            {"job": _str(), "window": {"type": "number"},
             "series": {"type": "array", "items": _ref("MetricSeries")}},
            ["job", "window", "series"],
        ),
        "BundleMeta": _obj(
            {"n": _int(), "job": _str(), "tenant": _str(),
             "rule": _str(), "captured_at": {"type": "number"},
             "bytes": _int(), "spans": _int()},
            ["n", "job", "rule", "captured_at"],
        ),
        "Bundle": _obj(
            {"n": _int(), "job": _str(), "rule": _str(),
             "captured_at": {"type": "number"},
             "alert": {"type": "object"}, "doctor": {"type": "object"},
             "flight_recorder": {"type": "array",
                                 "items": {"type": "object"}},
             "perfetto": {"type": "object"},
             "history": {"type": "array", "items": _ref("MetricSeries")},
             "ledger": {"type": "array", "items": {"type": "object"}}},
            ["n", "job", "rule"],
        ),
        # Conservation ledger (obs/audit.py)
        "AuditBreach": _obj(
            {"job": _str(), "kind": _str(), "edge": _str(),
             "epoch": _int(), "detail": _str(), "ts": {"type": "number"}},
            ["job", "kind", "edge", "epoch"],
        ),
        "AuditReport": _obj(
            {"job": _str(),
             "incarnation": {**_int(), "nullable": True},
             "epochs_reconciled": _int(), "edges_verified": _int(),
             "rows_attested": _int(),
             "last_epoch": {**_int(), "nullable": True},
             "breach_count": _int(),
             "breaches": {"type": "array", "items": _ref("AuditBreach")},
             "edges": {"type": "object"}},
            ["job"],
        ),
        "ErrorResp": _obj({"error": _str()}, ["error"]),
    }
    for item, name in [
        ("Pipeline", "PipelineCollection"),
        ("Job", "JobCollection"),
        ("Checkpoint", "CheckpointCollection"),
        ("JobLogMessage", "JobLogMessageCollection"),
        ("OperatorMetricGroup", "OperatorMetricGroupCollection"),
        ("OperatorCheckpointGroup", "OperatorCheckpointGroupCollection"),
        ("Connector", "ConnectorCollection"),
        ("ConnectionProfile", "ConnectionProfileCollection"),
        ("ConnectionTable", "ConnectionTableCollection"),
        ("GlobalUdf", "GlobalUdfCollection"),
        ("StateTable", "StateTableCollection"),
        ("BundleMeta", "BundleCollection"),
    ]:
        s[name] = _collection(item)
    return s


def build_spec(prefix: str = "/api/v1") -> Dict[str, Any]:
    """OpenAPI 3.0.3 document covering every registered /api/v1 route."""
    paths: Dict[str, Any] = {}
    for method, path, handler, summary, tag, req, resp in ROUTES:
        op: Dict[str, Any] = {
            "summary": summary,
            "operationId": handler,
            "tags": [tag],
            "responses": {
                "200": {"description": "OK"},
                "400": {"description": "Bad request",
                        "content": {"application/json": {
                            "schema": _ref("ErrorResp")}}},
            },
        }
        if resp:
            op["responses"]["200"]["content"] = {
                "application/json": {"schema": _ref(resp)}
            }
        if req:
            op["requestBody"] = {
                "required": True,
                "content": {"application/json": {"schema": _ref(req)}},
            }
        params = [
            seg[1:-1] for seg in path.split("/")
            if seg.startswith("{") and seg.endswith("}")
        ]
        if params:
            op["parameters"] = [
                {"name": p, "in": "path", "required": True,
                 "schema": _str()} for p in params
            ]
        paths.setdefault(prefix + path, {})[method] = op
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "arroyo-tpu REST API",
            "description": "Pipeline management API "
                           "(reference parity: arroyo-api ApiDoc)",
            "version": "1.0.0",
        },
        "paths": paths,
        "components": {"schemas": _schemas()},
    }
