"""REST API: pipelines lifecycle, preview, connectors, UDFs, connections."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from arroyo_tpu.api.rest import build_app
from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import EmbeddedScheduler

IMPULSE_SQL = """
CREATE TABLE impulse WITH (
  connector = 'impulse', event_rate = '1000000',
  message_count = '1000', start_time = '0'
);
SELECT counter FROM impulse WHERE counter < 5;
"""


def with_client(fn):
    async def run():
        controller = await ControllerServer(EmbeddedScheduler()).start()
        app = build_app(controller, db_path=":memory:")
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, app["api"], controller)
        finally:
            await client.close()
            await controller.stop()

    asyncio.run(run())


def test_ping_and_connectors():
    async def body(client, api, controller):
        r = await client.get("/api/v1/ping")
        assert (await r.json())["pong"] is True
        r = await client.get("/api/v1/connectors")
        names = {c["id"] for c in (await r.json())["data"]}
        assert {"kafka", "impulse", "nexmark", "single_file"} <= names

    with_client(body)


def test_validate_query():
    async def body(client, api, controller):
        r = await client.post(
            "/api/v1/pipelines/validate_query", json={"query": IMPULSE_SQL}
        )
        out = await r.json()
        assert out["errors"] == []
        # compile-time chaining fuses forward runs: count OPERATORS
        # across chains, not nodes
        n_ops = sum(
            len(n["operator"].split(" -> "))
            for n in out["graph"]["nodes"]
        )
        assert n_ops >= 3 and len(out["graph"]["nodes"]) >= 1
        r = await client.post(
            "/api/v1/pipelines/validate_query",
            json={"query": "SELECT x FROM ghost"},
        )
        assert r.status == 400
        assert "unknown table" in (await r.json())["errors"][0]

    with_client(body)


def test_pipeline_lifecycle_with_controller(tmp_path):
    sink = tmp_path / "out.json"
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '2000', start_time = '0'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'single_file', path = '{sink}',
      format = 'json', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse WHERE counter % 2 = 0;
    """

    async def body(client, api, controller):
        r = await client.post(
            "/api/v1/pipelines", json={"name": "p1", "query": sql}
        )
        assert r.status == 200
        pid = (await r.json())["id"]
        # wait for the tracked job to finish
        for _ in range(300):
            r = await client.get(f"/api/v1/pipelines/{pid}")
            state = (await r.json())["state"]
            if state in ("Finished", "Failed"):
                break
            await asyncio.sleep(0.05)
        assert state == "Finished"
        r = await client.get(f"/api/v1/pipelines/{pid}/jobs")
        jobs = (await r.json())["data"]
        assert len(jobs) == 1 and jobs[0]["state"] == "Finished"
        r = await client.get("/api/v1/jobs")
        assert len((await r.json())["data"]) == 1

    with_client(body)
    rows = [json.loads(l) for l in open(sink)]
    assert len(rows) == 1000


def test_preview_returns_rows():
    async def body(client, api, controller):
        r = await client.post(
            "/api/v1/pipelines/preview", json={"query": IMPULSE_SQL}
        )
        pid = (await r.json())["id"]
        for _ in range(200):
            r = await client.get(f"/api/v1/pipelines/preview/{pid}/output")
            out = await r.json()
            if out["done"]:
                break
            await asyncio.sleep(0.05)
        assert out["error"] is None
        assert sorted(row["counter"] for row in out["rows"]) == [0, 1, 2, 3, 4]

    with_client(body)


def test_udf_endpoints():
    udf_src = """
@udf(pa.int64(), [pa.int64()], name="plus_one_api")
def plus_one_api(xs):
    return xs + 1
"""

    async def body(client, api, controller):
        r = await client.post(
            "/api/v1/udfs/validate", json={"definition": udf_src}
        )
        assert (await r.json())["udfs"] == ["plus_one_api"]
        r = await client.post("/api/v1/udfs", json={"definition": udf_src})
        uid = (await r.json())["id"]
        r = await client.get("/api/v1/udfs")
        assert any(u["id"] == uid for u in (await r.json())["data"])
        # the registered udf is usable in queries
        r = await client.post(
            "/api/v1/pipelines/validate_query",
            json={"query": IMPULSE_SQL.replace(
                "SELECT counter", "SELECT plus_one_api(counter)"
            )},
        )
        assert (await r.json())["errors"] == []
        r = await client.post(
            "/api/v1/udfs/validate", json={"definition": "not python ("}
        )
        assert r.status == 400

    with_client(body)


def test_connection_tables():
    async def body(client, api, controller):
        r = await client.post(
            "/api/v1/connection_tables",
            json={
                "name": "t1", "connector": "impulse",
                "config": {"event_rate": "100"}, "table_type": "source",
            },
        )
        assert r.status == 200
        r = await client.get("/api/v1/connection_tables")
        assert len((await r.json())["data"]) == 1
        r = await client.post(
            "/api/v1/connection_tables",
            json={"name": "bad", "connector": "kafka", "config": {}},
        )
        assert r.status == 400
        r = await client.post(
            "/api/v1/connection_tables/test",
            json={"connector": "kafka",
                  "config": {"bootstrap_servers": "x:9092", "topic": "t"}},
        )
        out = await r.json()
        assert out["ok"] is False  # no kafka client in this environment

    with_client(body)


def test_stop_pipeline_via_patch(tmp_path):
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '5000', realtime = 'true',
      start_time = '0'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'single_file', path = '{tmp_path}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def body(client, api, controller):
        r = await client.post(
            "/api/v1/pipelines", json={"name": "p2", "query": sql}
        )
        pid = (await r.json())["id"]
        await asyncio.sleep(0.3)
        r = await client.patch(
            f"/api/v1/pipelines/{pid}", json={"stop": "graceful"}
        )
        assert r.status == 200
        for _ in range(200):
            r = await client.get(f"/api/v1/pipelines/{pid}")
            state = (await r.json())["state"]
            if state in ("Stopped", "Failed", "Finished"):
                break
            await asyncio.sleep(0.05)
        assert state == "Stopped"

    with_client(body)


def test_openapi_spec():
    @with_client
    async def _(client, api, controller):
        resp = await client.get("/api/v1/openapi.json")
        assert resp.status == 200
        spec = await resp.json()
        assert spec["openapi"].startswith("3.0")
        # every ROUTES entry appears in the spec and is actually routed
        from arroyo_tpu.api.openapi import ROUTES

        assert len(ROUTES) == sum(len(ms) for ms in spec["paths"].values())
        for method, path, *_ in ROUTES:
            assert method in spec["paths"]["/api/v1" + path], path
        # all $ref targets resolve against components
        comps = spec["components"]["schemas"]

        def refs(node):
            if isinstance(node, dict):
                for k, v in node.items():
                    if k == "$ref":
                        yield v
                    else:
                        yield from refs(v)
            elif isinstance(node, list):
                for item in node:
                    yield from refs(item)

        for ref in refs(spec):
            assert ref.split("/")[-1] in comps, ref


def test_operator_metric_groups_structured(tmp_path):
    @with_client
    async def _(client, api, controller):
        # run a short pipeline so task-labeled counters exist
        resp = await client.post("/api/v1/pipelines", json={
            "name": "m1", "query": IMPULSE_SQL})
        assert resp.status == 200
        pid = (await resp.json())["id"]
        import asyncio as _a

        for _ in range(100):
            jobs = await (await client.get("/api/v1/jobs")).json()
            if any(j["state"] == "Finished" for j in jobs["data"]):
                break
            await _a.sleep(0.05)
        jobs = await (await client.get("/api/v1/jobs")).json()
        jid = jobs["data"][0]["id"]
        resp = await client.get(
            f"/api/v1/jobs/{jid}/operator_metric_groups")
        body = await resp.json()
        assert body["data"], "no operator groups"
        by_metric = {
            g["name"]: g
            for op in body["data"] for g in op["metricGroups"]
        }
        assert "messages_sent" in by_metric
        sub = by_metric["messages_sent"]["subtasks"][0]
        assert sub["index"] == 0 and sub["metrics"][0]["value"] > 0
        # tx-queue backpressure gauge (reference job_metrics.rs): present
        # per subtask, in [0, 1]
        assert "backpressure" in by_metric
        bp = by_metric["backpressure"]["subtasks"][0]["metrics"][0]["value"]
        assert 0.0 <= bp <= 1.0
        assert "prometheus" in body


def test_admin_server():
    """Per-process admin endpoints: /status, /metrics, /debug/* (reference
    arroyo-server-common start_admin_server)."""
    import aiohttp
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    async def go():
        with update(admin={"http_port": 0}):
            c = await ControllerServer(EmbeddedScheduler()).start()
        port = c.admin_port
        assert port > 0
        async with aiohttp.ClientSession() as s:
            st = await (await s.get(
                f"http://127.0.0.1:{port}/status")).json()
            metrics = await (await s.get(
                f"http://127.0.0.1:{port}/metrics")).text()
            tasks = await (await s.get(
                f"http://127.0.0.1:{port}/debug/tasks")).text()
            stacks = await (await s.get(
                f"http://127.0.0.1:{port}/debug/stacks")).text()
        await c.stop()
        return st, metrics, tasks, stacks

    st, metrics, tasks, stacks = asyncio.run(go())
    assert st["service"] == "arroyo-tpu-controller" and st["status"] == "ok"
    assert "jobs" in st and st["uptime_seconds"] >= 0
    assert "# HELP" in metrics or metrics.strip() == ""
    assert "RUNNING" in tasks
    assert "File" in stacks or "Thread" in stacks


def test_api_db_remote_sync(tmp_path):
    """MaybeLocalDb semantics: the sqlite file syncs through a storage URL
    — a fresh ApiDb pointed at the same remote sees prior state."""
    from arroyo_tpu.api.db import ApiDb

    remote = str(tmp_path / "remote")
    db1 = ApiDb(str(tmp_path / "local1.db"), remote_url=remote)
    p = db1.create_pipeline("synced", "SELECT 1", 1)
    udf = db1.create_udf("f", "def f(): pass")
    # a second instance (different local path) restores from the remote
    db2 = ApiDb(str(tmp_path / "local2.db"), remote_url=remote)
    assert [x["name"] for x in db2.list_pipelines()] == ["synced"]
    assert [x["name"] for x in db2.list_udfs()] == ["f"]
    # mutations through db2 propagate onward
    db2.delete_pipeline(p["id"])
    db3 = ApiDb(str(tmp_path / "local3.db"), remote_url=remote)
    assert db3.list_pipelines() == []
    assert db3.get_pipeline(p["id"]) is None
    assert [x["id"] for x in db3.list_udfs()] == [udf["id"]]


def test_postgres_backend_dialect():
    """The postgres path drives the same query set through the `%s`
    placeholder dialect and dict rows. A fake DBAPI connection asserts
    every statement arrived in Postgres form (no '?' placeholders) and
    executes it against an in-memory store to prove the round trip."""
    import sqlite3

    from arroyo_tpu.api.db import ApiDb, _PgConn

    executed = []

    class FakePgRaw:
        """Quacks like a psycopg connection; backed by sqlite but only
        accepts %s-style statements (as a real PG server would)."""

        def __init__(self):
            self._db = sqlite3.connect(":memory:")
            self._db.row_factory = sqlite3.Row

        def cursor(self):
            db = self._db

            class Cur:
                description = None
                rowcount = 0

                def execute(self, sql, params=()):
                    assert "?" not in sql, f"sqlite placeholder leaked: {sql}"
                    executed.append(sql)
                    self._c = db.execute(sql.replace("%s", "?"), params)
                    self.rowcount = self._c.rowcount
                    self.description = self._c.description

                def fetchone(self):
                    r = self._c.fetchone()
                    return dict(r) if r is not None else None

                def fetchall(self):
                    return [dict(r) for r in self._c.fetchall()]

            return Cur()

        def commit(self):
            self._db.commit()

    db = ApiDb(_pg_conn=_PgConn(FakePgRaw()))
    assert db.backend == "postgres"
    p = db.create_pipeline("pg-test", "SELECT 1;", 2)
    assert db.get_pipeline(p["id"])["name"] == "pg-test"
    db.set_pipeline_state(p["id"], "Running")
    assert db.get_pipeline(p["id"])["state"] == "Running"
    assert len(db.list_pipelines()) == 1
    j = db.create_job(p["id"])
    db.update_job(j["id"], "Running")
    assert db.all_jobs()[0]["state"] == "Running"
    u = db.create_udf("f", "def f(): pass")
    assert db.list_udfs()[0]["name"] == "f"
    db.delete_udf(u["id"])
    assert db.list_udfs() == []
    ct = db.create_connection_table("t", "kafka", {"topic": "x"}, None,
                                    "source", None)
    assert db.list_connection_tables()[0]["config"] == {"topic": "x"}
    db.delete_connection_table(ct["id"])
    db.delete_pipeline(p["id"])
    assert any("%s" in s for s in executed)


def test_console_smoke_and_ui_api_contract():
    """Serve /console and pin the UI-API contract: the SPA loads, and
    every /api/v1 path referenced in app.js resolves to a registered
    route (catches the reference-webui drift class where the UI polls
    endpoints the server renamed)."""
    @with_client
    async def _(client, api, controller):
        import re

        resp = await client.get("/console")
        assert resp.status == 200
        html = await resp.text()
        assert "<html" in html.lower() and "app.js" in html
        resp = await client.get("/console/app.js")
        assert resp.status == 200
        js = await resp.text()
        # the SPA routes every call through api(path) with relative
        # paths: extract the literal arguments of its HTTP helpers
        raw = re.findall(
            r"""(?:GET|POST|PATCH|DELETE|DEL)\(\s*["'`](/[^"'`?]*)""", js
        )
        called = sorted(
            "/api/v1" + re.sub(r"\$\{[^}]*\}", "${p}", p)
            for p in set(raw)
        )
        assert called, "app.js references no API endpoints?"
        # aiohttp canonicals: /api/v1/jobs/{job_id}/checkpoints
        canonicals = set()
        for r in client.app.router.routes():
            info = r.resource.get_info() if r.resource else {}
            canon = info.get("path") or info.get("formatter")
            if canon:
                canonicals.add(canon)

        def matches(js_path: str) -> bool:
            want = js_path.split("/")
            for canon in canonicals:
                have = canon.split("/")
                if len(have) != len(want):
                    continue
                ok = True
                for w, h in zip(want, have):
                    if h.startswith("{") and h.endswith("}"):
                        continue  # path param matches anything non-empty
                    if w.startswith("${"):
                        ok = False  # JS param against static segment
                        break
                    if w != h:
                        ok = False
                        break
                if ok:
                    return True
            return False

        missing = [p for p in called if not matches(p)]
        assert not missing, f"SPA calls unregistered endpoints: {missing}"
        # the SPA must poll the structured metrics endpoint whose shape
        # test_operator_metric_groups_structured pins
        assert any("operator_metric_groups" in p for p in called)


def test_operator_checkpoint_groups_detail(tmp_path):
    """Per-operator checkpoint drill-down (reference CheckpointDetails):
    per-subtask state sizes, file counts and watermarks for one epoch."""
    sink = tmp_path / "out.json"
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '20000', realtime = 'true',
      message_count = '8000'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{sink}',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 4 AS k, tumble(interval '100 millisecond') AS w,
             count(*) AS cnt
      FROM impulse GROUP BY 1, 2
    );
    """

    async def body(client, api, controller):
        from arroyo_tpu.config import update

        with update(pipeline={
            "checkpointing": {"storage_url": str(tmp_path / "ck"),
                              "interval": 0.1},
        }):
            r = await client.post(
                "/api/v1/pipelines", json={"name": "ckd", "query": sql}
            )
            assert r.status == 200
            # wait until at least one checkpoint is listed
            groups = None
            for _ in range(300):
                jobs = (await (await client.get("/api/v1/jobs")).json())[
                    "data"
                ]
                if jobs:
                    jid = jobs[0]["id"]
                    cks = (await (await client.get(
                        f"/api/v1/jobs/{jid}/checkpoints"
                    )).json())["data"]
                    if cks:
                        epoch = cks[-1]["epoch"]
                        d = await (await client.get(
                            f"/api/v1/jobs/{jid}/checkpoints/{epoch}"
                            "/operator_checkpoint_groups"
                        )).json()
                        # early epochs may precede any flushed state;
                        # wait for one that carries bytes
                        if d["data"] and any(
                            t["bytes"] > 0 for g in d["data"]
                            for task in g["tasks"] for t in task["tables"]
                        ):
                            groups = d
                            break
                await asyncio.sleep(0.05)
            assert groups is not None, "no checkpoint detail appeared"
            assert groups["epoch"] == epoch
            # shape: operators -> tasks -> tables, with byte accounting
            g0 = groups["data"][0]
            assert {"node_id", "bytes", "tasks"} <= set(g0)
            t0 = g0["tasks"][0]
            assert {"subtask", "task_id", "watermark", "bytes", "rows",
                    "tables"} <= set(t0)
            # the window operator's state table must appear with bytes
            all_tables = [
                t["table"] for g in groups["data"]
                for task in g["tasks"] for t in task["tables"]
            ]
            assert all_tables, "no state tables in checkpoint detail"
            assert any(
                t["bytes"] > 0 for g in groups["data"]
                for task in g["tasks"] for t in task["tables"]
            )

    with_client(body)


def test_rescale_via_patch_exactly_once(tmp_path):
    """PATCH parallelism on a running pipeline checkpoint-stops the job
    and resubmits at the new parallelism, RESTORING the pipeline's
    checkpoint lineage (storage keyed by pipeline id): across both jobs
    every source row reaches the sink exactly once."""
    sink = tmp_path / "out.json"
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '4000', realtime = 'true',
      message_count = '4000', start_time = '0'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'single_file', path = '{sink}',
      format = 'json', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def body(client, api, controller):
        from arroyo_tpu.config import update

        with update(pipeline={"checkpointing": {
            "storage_url": str(tmp_path / "ck"), "interval": 0.1,
        }}):
            r = await client.post(
                "/api/v1/pipelines", json={"name": "rs", "query": sql}
            )
            pid = (await r.json())["id"]
            await asyncio.sleep(0.4)
            r = await client.patch(
                f"/api/v1/pipelines/{pid}", json={"parallelism": 2}
            )
            assert r.status == 200
            assert (await r.json())["parallelism"] == 2
            # a second job exists and finishes the remaining stream at
            # the new parallelism
            for _ in range(600):
                jobs = (await (await client.get(
                    f"/api/v1/pipelines/{pid}/jobs"
                )).json())["data"]
                if len(jobs) == 2 and all(
                    controller.jobs.get(j["id"]) is not None
                    and controller.jobs[j["id"]].state.is_terminal()
                    for j in jobs
                ):
                    break
                await asyncio.sleep(0.05)
            assert len(jobs) == 2
            assert controller.jobs[jobs[-1]["id"]].parallelism == 2
            # invalid values rejected
            r = await client.patch(
                f"/api/v1/pipelines/{pid}", json={"parallelism": 0}
            )
            assert r.status == 400

    with_client(body)
    rows = sorted(json.loads(l)["counter"] for l in open(sink) if l.strip())
    assert rows == list(range(4000)), (
        f"rescale lost/duplicated rows: {len(rows)} rows"
    )


def test_restart_resumes_from_checkpoint_lineage(tmp_path):
    """POST /pipelines/{id}/restart checkpoint-stops the running job and
    the new job RESUMES the pipeline's checkpoint lineage — every source
    row reaches the sink exactly once across both jobs."""
    sink = tmp_path / "out.json"
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '4000', realtime = 'true',
      message_count = '4000', start_time = '0'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'single_file', path = '{sink}',
      format = 'json', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def body(client, api, controller):
        from arroyo_tpu.config import update

        with update(pipeline={"checkpointing": {
            "storage_url": str(tmp_path / "ck"), "interval": 0.1,
        }}):
            r = await client.post(
                "/api/v1/pipelines", json={"name": "rr", "query": sql}
            )
            pid = (await r.json())["id"]
            await asyncio.sleep(0.4)
            r = await client.post(f"/api/v1/pipelines/{pid}/restart")
            assert r.status == 200
            for _ in range(600):
                jobs = (await (await client.get(
                    f"/api/v1/pipelines/{pid}/jobs"
                )).json())["data"]
                if len(jobs) == 2 and all(
                    controller.jobs.get(j["id"]) is not None
                    and controller.jobs[j["id"]].state.is_terminal()
                    for j in jobs
                ):
                    break
                await asyncio.sleep(0.05)
            assert len(jobs) == 2

    with_client(body)
    rows = sorted(json.loads(l)["counter"] for l in open(sink) if l.strip())
    assert rows == list(range(4000)), (
        f"restart lost/duplicated rows: {len(rows)} rows"
    )


def test_preview_ttl_cleanup():
    """Finished previews older than api.preview_ttl are swept — registry
    entry AND pipeline/job rows (reference: controller update loop
    preview cleanup, arroyo-controller lib.rs:600-706)."""
    async def body(client, api, controller):
        import time as _time

        r = await client.post("/api/v1/pipelines/preview", json={
            "query": (
                "CREATE TABLE impulse (counter BIGINT UNSIGNED NOT NULL, "
                "subtask_index BIGINT UNSIGNED NOT NULL) WITH ("
                "connector='impulse', event_rate='1000', "
                "message_count='50', start_time='0');"
                "SELECT counter FROM impulse;"
            ),
            "timeout": 30,
        })
        assert r.status == 200
        pid = (await r.json())["id"]
        for _ in range(200):
            if api.previews[pid]["done"]:
                break
            await asyncio.sleep(0.05)
        assert api.previews[pid]["done"]
        # young + finished: not swept
        assert api.cleanup_previews() == 0
        assert api.db.get_pipeline(pid) is not None
        # stale + finished: swept from registry and db
        from arroyo_tpu.config import config as config_fn

        future = _time.time() + config_fn().api.preview_ttl + 1
        assert api.cleanup_previews(now=future) == 1
        assert pid not in api.previews
        assert api.db.get_pipeline(pid) is None
        # orphaned DB row (registry lost to cap-eviction or restart):
        # the sweep finds it via its 'Preview' state
        orphan = api.db.create_pipeline("preview", "SELECT 1", 1)
        api.db.set_pipeline_state(orphan["id"], "Preview")
        assert api.cleanup_previews(now=future) == 1
        assert api.db.get_pipeline(orphan["id"]) is None
        # a non-preview pipeline is never touched
        keeper = api.db.create_pipeline("real", "SELECT 1", 1)
        assert api.cleanup_previews(now=future) == 0
        assert api.db.get_pipeline(keeper["id"]) is not None

    with_client(body)


def test_versioned_migrations():
    """schema_version gates ordered DDL: fresh dbs land on the newest
    version; a pre-versioning db (tables, no schema_version) upgrades in
    place; reopening is a no-op."""
    import sqlite3
    import tempfile

    from arroyo_tpu.api.db import MIGRATIONS, ApiDb, apply_migrations

    latest = MIGRATIONS[-1][0]
    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/api.db"
        db = ApiDb(path)
        row = db.conn.execute(
            "SELECT MAX(version) AS v FROM schema_version").fetchone()
        assert row["v"] == latest
        db.create_pipeline("p", "SELECT 1", 1)
        # reopen: no re-application, data intact
        db2 = ApiDb(path)
        assert len(db2.list_pipelines()) == 1
        assert apply_migrations(db2.conn) == latest

        # legacy db: v1 tables only, no schema_version — upgrade applies
        # every version exactly once and the v2 index exists after
        legacy = f"{td}/legacy.db"
        conn = sqlite3.connect(legacy)
        for _, stmts in MIGRATIONS[:1]:
            for s in stmts:
                conn.execute(s)
        conn.commit()
        conn.close()
        db3 = ApiDb(legacy)
        row = db3.conn.execute(
            "SELECT COUNT(*) AS c FROM sqlite_master "
            "WHERE name = 'idx_jobs_pipeline'").fetchone()
        assert row["c"] == 1


def test_admin_debug_profile():
    """/debug/profile captures a windowed CPU profile (reference
    /debug/pprof/profile, arroyo-server-common profile.rs:12-51)."""
    from arroyo_tpu.utils.admin import build_admin_app

    async def run():
        app = build_admin_app("test")
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/debug/profile?seconds=0.2")
            assert r.status == 200
            text = await r.text()
            assert "function calls" in text and "tottime" in text
            r = await client.get("/debug/profile?seconds=abc")
            assert r.status == 400
            r = await client.get("/debug/profile?sort=nope")
            assert r.status == 400
        finally:
            await client.close()

    asyncio.run(run())
