"""Core of the arroyolint rule engine: findings, parsed files, the project
view handed to project-scope rules, and the rule registry.

Design notes: every source file is parsed once into a `FileContext`
(tree + parent links + suppression comments); rules are stateless
singletons registered by id. File-scope rules see one `FileContext` at a
time; project-scope rules (protocol conformance, config drift) see the
whole `Project` and locate their anchor files by path suffix so the same
rule runs unchanged against the real tree and against the miniature trees
under `tests/lint_fixtures/`.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix path relative to the lint root
    line: int
    col: int
    message: str

    def fingerprint(self) -> str:
        """Stable identity for baseline matching: deliberately excludes
        line/col so pure code motion doesn't churn the baseline."""
        raw = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fingerprint": self.fingerprint(),
        }


_LINE_RE = re.compile(r"#\s*arroyolint:\s*disable=([A-Za-z0-9_,\s]+)")
_FILE_RE = re.compile(r"#\s*arroyolint:\s*disable-file=([A-Za-z0-9_,\s]+)")
# file-level suppressions must sit near the top, before any real code
_FILE_SUPPRESS_WINDOW = 10


class FileContext:
    """One parsed source file plus the comment-level metadata rules need."""

    def __init__(self, root: Path, relpath: str, source: str):
        self.root = Path(root)
        self.path = relpath.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.line_suppressions: Dict[int, set] = {}
        self.file_suppressions: set = set()
        for lineno, text in enumerate(self.lines, start=1):
            if "arroyolint" not in text:
                continue
            m = _LINE_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.line_suppressions.setdefault(lineno, set()).update(rules)
            m = _FILE_RE.search(text)
            if m and lineno <= _FILE_SUPPRESS_WINDOW:
                self.file_suppressions.update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions or "all" in self.file_suppressions:
            return True
        on_line = self.line_suppressions.get(line, ())
        return rule in on_line or "all" in on_line

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing (Async)FunctionDef, or None at module scope."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule.id,
            path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


class Project:
    """The set of parsed files a lint run covers, rooted at one directory."""

    def __init__(self, root: Path, files: Dict[str, FileContext],
                 errors: Optional[List[Finding]] = None):
        self.root = Path(root)
        self.files = files  # relpath -> FileContext
        self.errors = errors or []

    def get(self, relpath: str) -> Optional[FileContext]:
        return self.files.get(relpath.replace("\\", "/"))

    def find(self, suffix: str) -> Optional[FileContext]:
        """Locate a file by path suffix ("operators/control.py" matches both
        the real tree and a fixture mini-tree)."""
        suffix = suffix.replace("\\", "/")
        for path, ctx in sorted(self.files.items()):
            if path == suffix or path.endswith("/" + suffix):
                return ctx
        return None

    def __iter__(self):
        return iter(self.files.values())


class Rule:
    """Base class. Subclasses set `id`/`name`/`description` and override one
    of the check hooks. `scope` is "file" or "project"."""

    id: str = ""
    name: str = ""
    description: str = ""
    scope: str = "file"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


_RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register the rule by id."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    return [r for _, r in sorted(_RULES.items())]


def get_rule(rule_id: str) -> Rule:
    return _RULES[rule_id]


# -- shared AST helpers ------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_attr(node: ast.AST) -> Optional[str]:
    """Final component of a Name/Attribute chain ('c' for a.b.c)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_scope(fn: ast.AST, into_nested: bool = False) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested function/class
    definitions (unless `into_nested`), so scope-sensitive rules don't
    attribute an inner def's statements to the outer function."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not into_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def iter_functions(tree: ast.AST) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def sorted_findings(findings: Sequence[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
