"""MUST fire ASY004: cancellation swallowed while more work follows."""
import asyncio


async def drain(tasks):
    for t in tasks:
        try:
            await t
        except (asyncio.CancelledError, Exception):
            pass
    return len(tasks)


async def commit(task):
    try:
        await task
    except BaseException:
        pass
    await task  # more work runs under the swallowed cancellation
