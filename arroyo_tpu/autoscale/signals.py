"""Signal sampling for the autoscaler: metrics registry -> per-operator rates.

The observe step of the control loop (DS2, Kalavri et al. OSDI '18 §3:
"three steps is all you need" — observe true rates, decide by rate ratios,
actuate). Each control period the sampler takes a registry snapshot
(merged across the job's workers over the GetMetrics rpc — identical
snapshots from embedded same-process workers union to one), diffs the
task-labeled counters against the previous period, and aggregates the
deltas into one `OperatorSignals` per logical node:

  observed_rate            rows/s actually processed (recv counters)
  output_rate              rows/s emitted (sent counters)
  busy_ratio               useful-work seconds / (period * parallelism)
  true_rate_per_instance   rows per busy-second — the DS2 true processing
                           rate, independent of how idle/backpressured the
                           operator currently is
  selectivity              output rows per input row (demand propagation)
  backpressure             fullness of the operator's own output queues
                           (an op is the bottleneck when its UPSTREAMs'
                           backpressure is high)
  watermark_lag            seconds the subtask watermark trails wall clock

Counters restart from zero when a worker process is replaced (recovery,
process scheduler); deltas clamp at the observed value so a restart reads
as a small sample, not a negative rate.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

# metric families the sampler consumes (names, not handles: snapshots may
# come over the wire from another process's registry)
_RECV = "arroyo_worker_messages_recv"
_SENT = "arroyo_worker_messages_sent"
_BUSY = "arroyo_worker_busy_seconds"
_BACKPRESSURE = "arroyo_worker_backpressure"
_WM_LAG = "arroyo_worker_watermark_lag_seconds"
_BATCH_HIST = "arroyo_worker_batch_processing_seconds"


@dataclasses.dataclass
class OperatorSignals:
    """One control period's aggregated view of a logical operator."""

    node_id: int
    parallelism: int
    observed_rate: float = 0.0
    output_rate: float = 0.0
    busy_ratio: Optional[float] = None
    true_rate_per_instance: Optional[float] = None
    selectivity: float = 1.0
    backpressure: float = 0.0
    watermark_lag: float = 0.0
    # tail latency of batch processing (estimated from cumulative buckets;
    # metrics.hist_quantiles) — audit-log context, not a decision input
    batch_p95: Optional[float] = None

    def summary(self) -> dict:
        out = dataclasses.asdict(self)
        return {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in out.items() if v is not None
        }


def merge_snapshots(snapshots: List[dict]) -> Dict[str, Dict[tuple, object]]:
    """Union registry snapshots keyed by (metric, sorted label items).
    Embedded workers share one process registry and return identical
    snapshots — the union collapses them instead of double counting."""
    merged: Dict[str, Dict[tuple, object]] = {}
    for snap in snapshots:
        for name, entries in (snap or {}).items():
            dst = merged.setdefault(name, {})
            for labels, value in entries:
                dst[tuple(sorted(dict(labels).items()))] = value
    return merged


def _task_values(merged: Dict[str, Dict[tuple, object]], metric: str,
                 job_id: str) -> Dict[Tuple[int, int], object]:
    """{(node_id, subtask): value} for a job's task-labeled family."""
    out: Dict[Tuple[int, int], object] = {}
    for labels, value in merged.get(metric, {}).items():
        d = dict(labels)
        if d.get("job") != job_id:
            continue
        task = d.get("task") or ""
        node, _, sub = task.rpartition("-")
        try:
            out[(int(node), int(sub))] = value
        except ValueError:
            continue
    return out


class SignalSampler:
    """Stateful per-job sampler: keeps the previous period's counter sums
    per node and turns the current snapshot into OperatorSignals."""

    def __init__(self, job_id: str):
        self.job_id = job_id
        # node_id -> (recv_rows, sent_rows, busy_seconds)
        self._prev: Dict[int, Tuple[float, float, float]] = {}
        self._prev_time: Optional[float] = None

    def reset(self) -> None:
        """Forget history (after a reschedule/rescale the topology and the
        worker set changed; the next sample only re-seeds the baseline)."""
        self._prev.clear()
        self._prev_time = None

    def sample(self, merged: Dict[str, Dict[tuple, object]],
               node_parallelism: Dict[int, int],
               now: Optional[float] = None) -> Optional[Dict[int, OperatorSignals]]:
        """Diff the merged snapshot against the previous period. Returns
        None on the first call (baseline only — rates need two points)."""
        from ..metrics import hist_quantiles

        now = time.monotonic() if now is None else now
        recv = _task_values(merged, _RECV, self.job_id)
        sent = _task_values(merged, _SENT, self.job_id)
        busy = _task_values(merged, _BUSY, self.job_id)
        bp = _task_values(merged, _BACKPRESSURE, self.job_id)
        lag = _task_values(merged, _WM_LAG, self.job_id)
        hist = _task_values(merged, _BATCH_HIST, self.job_id)

        sums: Dict[int, Tuple[float, float, float]] = {}
        nodes = {n for n, _ in (*recv, *sent, *busy)} | set(node_parallelism)
        for nid in nodes:
            sums[nid] = (
                sum(v for (n, _s), v in recv.items() if n == nid),
                sum(v for (n, _s), v in sent.items() if n == nid),
                sum(v for (n, _s), v in busy.items() if n == nid),
            )
        prev, prev_time = self._prev, self._prev_time
        self._prev, self._prev_time = sums, now
        if prev_time is None:
            return None
        dt = max(1e-6, now - prev_time)

        out: Dict[int, OperatorSignals] = {}
        for nid, (r, s, b) in sums.items():
            pr, ps, pb = prev.get(nid, (0.0, 0.0, 0.0))
            # counter restarts (replaced worker process) read as the raw
            # value, never a negative delta
            dr = r - pr if r >= pr else r
            ds = s - ps if s >= ps else s
            db = b - pb if b >= pb else b
            par = max(1, node_parallelism.get(nid, 1))
            sig = OperatorSignals(node_id=nid, parallelism=par)
            sig.observed_rate = dr / dt
            sig.output_rate = ds / dt
            if db > 0:
                sig.busy_ratio = min(1.0, db / (dt * par))
                if dr > 0:
                    sig.true_rate_per_instance = dr / db
            sig.selectivity = (ds / dr) if dr > 0 else 1.0
            sig.backpressure = max(
                (float(v) for (n, _s), v in bp.items() if n == nid),
                default=0.0,
            )
            sig.watermark_lag = max(
                (float(v) for (n, _s), v in lag.items() if n == nid),
                default=0.0,
            )
            node_hists = [v for (n, _s), v in hist.items()
                          if n == nid and isinstance(v, dict)]
            if node_hists:
                p95s = [hist_quantiles(h, (0.95,)).get("p95")
                        for h in node_hists]
                p95s = [p for p in p95s if p is not None]
                if p95s:
                    sig.batch_p95 = max(p95s)
            out[nid] = sig
        return out
