"""Shared deferred-jax bootstrap.

jax is imported lazily so host-only deployments can import the module
tree without pulling in the accelerator stack; every device-path module
must see the same config (x64 enabled — the engine's timestamps, keys
and integer accumulators are 64-bit)."""

from __future__ import annotations

_jax = None
_accel: bool | None = None


def get_jax():
    global _jax
    if _jax is None:
        import jax

        jax.config.update("jax_enable_x64", True)
        # persistent XLA compilation cache: compiled programs survive
        # process exit, so repeat pipeline runs (bench medians, worker
        # restarts, the probe daemon's grant children) skip compilation.
        # Pays off hugely through the TPU relay (~20-40s per program)
        # and measurably on CPU-jax (mesh bench: ~1.7s of compiles per
        # fresh process). Config tpu.compilation_cache_dir; empty = off.
        from ..config import config

        cache_dir = config().tpu.compilation_cache_dir
        if cache_dir:
            import os

            try:
                cache_dir = os.path.expanduser(cache_dir)
                os.makedirs(cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", cache_dir)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", 0)
            except Exception:  # cache is an optimization, never fatal
                pass
        _jax = jax
    return _jax


def accelerator_present() -> bool:
    """True when jax's default backend is a real accelerator (TPU/GPU).
    The device execution tiers engage on this by default: jitted kernels
    on CPU-jax LOSE to the numpy/arrow host paths (measured: forced
    device join q7 322k -> 92k ev/s; assign bench device tier 15ms vs
    native C++ 0.24ms per batch), so a production run on a host without
    an accelerator must not pay XLA compiles for negative throughput."""
    global _accel
    if _accel is None:
        import os

        if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            # explicit CPU pin: answer without importing jax at all (a
            # default-config host-only deployment shouldn't pay jax
            # import + backend discovery just to learn "use numpy")
            _accel = False
            return _accel
        try:
            _accel = get_jax().default_backend() not in ("cpu",)
        except Exception:  # jax absent/broken: host paths only
            _accel = False
    return _accel


def device_tier_active() -> bool:
    """tpu.enabled AND (an accelerator exists OR the config explicitly
    waives the requirement — tests and CPU-jax measurement runs)."""
    from ..config import config

    cfg = config().tpu
    if not cfg.enabled:
        return False
    return accelerator_present() if cfg.require_accelerator else True


def device_join_active() -> bool:
    """Gate for the merge-join probe, shared by the instant/expiring and
    updating join operators: the device tier (or the force flag for
    off-TPU cost-model measurement) plus the join-specific switch."""
    from ..config import config

    cfg = config().tpu
    return cfg.device_join and (device_tier_active()
                                or cfg.device_join_force)


def safe_donate(*argnums) -> tuple:
    """donate_argnums gated on the jax generation: on the 0.4.x line
    (shard_map still experimental) consuming donated buffers across
    repeated runs intermittently corrupts the allocator (observed as
    glibc "corrupted double-linked list"/segfaults on 0.4.37-cpu, both
    for mesh-sharded state and the single-device accumulators);
    donation re-engages where shard_map has moved into core jax."""
    try:
        from jax import shard_map  # noqa: F401

        return tuple(argnums)
    except ImportError:
        return ()
