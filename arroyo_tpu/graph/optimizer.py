"""Graph optimizers — operator chaining.

Capability parity with the reference's ChainingOptimizer
(/root/reference/crates/arroyo-datastream/src/optimizers.rs:6-18): fuse
Forward-connected, same-parallelism operator pairs into one node so a chain
executes in a single subtask with direct calls (Flink-style chaining).
Sources with multiple outputs, shuffle edges, and fan-in nodes break chains.
"""

from __future__ import annotations

from .logical import EdgeType, LogicalGraph, LogicalNode


class ChainingOptimizer:
    def optimize(self, graph: LogicalGraph) -> LogicalGraph:
        changed = True
        while changed:
            changed = False
            for edge in list(graph.edges):
                if edge.edge_type != EdgeType.FORWARD:
                    continue
                src = graph.nodes[edge.src]
                dst = graph.nodes[edge.dst]
                if src.parallelism != dst.parallelism:
                    continue
                # only fuse linear connections: src has exactly one out edge,
                # dst exactly one in edge
                if len(graph.out_edges(src.node_id)) != 1:
                    continue
                if len(graph.in_edges(dst.node_id)) != 1:
                    continue
                # async UDFs need the select loop's operator-future polling
                # (completions + held-watermark release); source-led chains
                # run the source loop instead, so never fuse one into them
                from .logical import OperatorName

                if src.chain[0].operator == OperatorName.CONNECTOR_SOURCE and any(
                    op.operator == OperatorName.ASYNC_UDF for op in dst.chain
                ):
                    continue
                # never fuse sinks: checkpoint/commit control (2PC
                # prepare/commit, offset truncation) targets sink TASKS —
                # a sink folded into an upstream chain breaks that
                # routing. The valuable fusion is the stateless
                # source->watermark->projection prefix anyway.
                if any(
                    op.operator == OperatorName.CONNECTOR_SINK
                    for op in dst.chain
                ):
                    continue
                self._fuse(graph, src, dst, edge)
                changed = True
                break
        return graph

    @staticmethod
    def _fuse(graph: LogicalGraph, src: LogicalNode, dst: LogicalNode, edge):
        src.chain.extend(dst.chain)
        src.description = f"{src.description} -> {dst.description}"
        graph.edges.remove(edge)
        for e in list(graph.edges):
            if e.src == dst.node_id:
                e.src = src.node_id
        del graph.nodes[dst.node_id]
