"""AWS Kinesis connector (reference: crates/arroyo-connectors/src/kinesis/,
955 LoC). Shard iterators checkpoint by sequence number. Client gated on
boto3/aioboto3."""

from __future__ import annotations

import asyncio
from typing import Dict

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector


class KinesisSource(SourceOperator):
    def __init__(self, stream: str, region: str, init_position: str,
                 schema, format, bad_data):
        super().__init__("kinesis_source")
        self.stream = stream
        self.region = region
        self.init_position = init_position  # latest | earliest
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.positions: Dict[str, str] = {}  # shard id -> sequence number

    def tables(self):
        from ..state.table_config import global_table

        return {"kin": global_table("kin")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("kin")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.positions = dict(stored)

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("kin")
            table.put(ctx.task_info.task_index, dict(self.positions))

    async def run(self, ctx, collector) -> SourceFinishType:
        boto3 = require_client("boto3")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        client = boto3.client("kinesis", region_name=self.region)
        shards = client.list_shards(StreamName=self.stream)["Shards"]
        mine = [
            s["ShardId"] for i, s in enumerate(shards)
            if i % ctx.task_info.parallelism == ctx.task_info.task_index
        ]
        iterators = {}
        for sid in mine:
            if sid in self.positions:
                it = client.get_shard_iterator(
                    StreamName=self.stream, ShardId=sid,
                    ShardIteratorType="AFTER_SEQUENCE_NUMBER",
                    StartingSequenceNumber=self.positions[sid],
                )
            else:
                it = client.get_shard_iterator(
                    StreamName=self.stream, ShardId=sid,
                    ShardIteratorType=(
                        "TRIM_HORIZON" if self.init_position == "earliest"
                        else "LATEST"
                    ),
                )
            iterators[sid] = it["ShardIterator"]
        while iterators:
            finish = await ctx.check_control(collector)
            if finish is not None:
                return finish
            for sid, it in list(iterators.items()):
                resp = client.get_records(ShardIterator=it, Limit=1000)
                for rec in resp["Records"]:
                    ts = int(rec["ApproximateArrivalTimestamp"].timestamp()
                             * 1e9)
                    for row in deser.deserialize_slice(
                        rec["Data"], timestamp=ts,
                        error_reporter=ctx.error_reporter,
                    ):
                        ctx.buffer_row(row)
                    self.positions[sid] = rec["SequenceNumber"]
                nxt = resp.get("NextShardIterator")
                if nxt is None:
                    del iterators[sid]
                else:
                    iterators[sid] = nxt
            await self.flush_buffer(ctx, collector)
            await asyncio.sleep(0.2)
        return SourceFinishType.FINAL


class KinesisSink(Operator):
    def __init__(self, stream: str, region: str, format):
        super().__init__("kinesis_sink")
        self.stream = stream
        self.region = region
        self.serializer = Serializer(format=format or "json")
        self.client = None

    async def on_start(self, ctx):
        boto3 = require_client("boto3")
        self.client = boto3.client("kinesis", region_name=self.region)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        records = [
            {"Data": rec, "PartitionKey": str(i)}
            for i, rec in enumerate(self.serializer.serialize(batch))
        ]
        for lo in range(0, len(records), 500):  # API limit per call
            self.client.put_records(
                StreamName=self.stream, Records=records[lo: lo + 500]
            )


@register_connector
class KinesisConnector(Connector):
    name = "kinesis"
    description = "AWS Kinesis source and sink"
    source = True
    sink = True
    config_schema = {
        "stream_name": {"type": "string", "required": True},
        "aws_region": {"type": "string"},
        "source.init_position": {"type": "string"},
    }

    def validate_options(self, options, schema):
        if "stream_name" not in options:
            raise ValueError("kinesis requires stream_name")
        return {
            "stream": options["stream_name"],
            "region": options.get("aws_region", "us-east-1"),
            "init_position": options.get("source.init_position", "latest"),
        }

    def make_source(self, config, schema: ConnectionSchema):
        return KinesisSource(config["stream"], config["region"],
                             config.get("init_position", "latest"),
                             config.get("schema"), config.get("format"),
                             config.get("bad_data", "fail"))

    def make_sink(self, config, schema: ConnectionSchema):
        return KinesisSink(config["stream"], config["region"],
                           config.get("format"))
