"""Placeholder: mqtt connector lands with the connector milestone."""
