from .table_config import TableConfig, global_table, time_key_table  # noqa: F401
