#!/usr/bin/env python
"""Mesh hot-path stage budget: profile q5 on the N-virtual-device CPU
mesh and split wall time into XLA dispatch vs host packing vs directory
work (VERDICT round-5 item 4: "no profile says how much of the remaining
gap is XLA-CPU dispatch floor vs removable host work").

The measurement drives the existing `/debug/profile` admin endpoint
(arroyo_tpu/utils/admin.py): the child process runs the same q5 mesh
workload as `bench.py --mesh N` with the admin server on an ephemeral
port; the parent captures a windowed cProfile over the steady state
(after a warmup run has paid all XLA compiles) and buckets the pstats
rows into stages. Output is one JSON line plus an optional markdown
table for BASELINE.md.

Usage:
    python tools/mesh_profile.py [--events 2000000] [--mesh 8]
                                 [--seconds 10] [--markdown]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# -------------------------------------------------------------- child

def child(events: int, mesh: int, linger: float) -> None:
    """Run q5 on the mesh with the admin server up. Protocol on stdout:
    ADMIN <port>, MEASURING (engine started, steady state), then the
    bench-compatible MESHSTATS / RESULT lines."""
    import asyncio
    import time

    sys.path.insert(0, REPO)
    import bench
    from arroyo_tpu.config import config
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.utils.admin import serve_admin

    # mirror bench.py's mesh child settings exactly: the budget must
    # describe the same configuration the benchmark measures
    config().tpu.enabled = True
    config().pipeline.source_batch_size = 8192
    config().tpu.mesh_devices = mesh
    config().tpu.shape_buckets = (8192, 65536)
    config().tpu.initial_capacity = 1 << 18
    config().tpu.use_32bit_accumulators = True

    def plan(n_events: int):
        rate = max(n_events // 60, 1)
        results: list = []
        p = plan_query(
            bench.QUERIES["q5"].format(rate=rate, events=n_events),
            preview_results=results,
        )
        bench.force_backend(p, "jax")
        return p

    # warmup: pay every XLA compile (programs persist in-process) so the
    # profiled window sees steady-state dispatch, not compilation
    warm = plan(max(events // 10, 20_000))

    async def run_warm():
        eng = Engine(warm.graph).start()
        await eng.join(600)

    asyncio.run(run_warm())
    print("WARMED", flush=True)

    measured = plan(events)

    async def run_measured():
        runner, port = await serve_admin("mesh-profile", port=0)
        print(f"ADMIN {port}", flush=True)
        t0 = time.monotonic()
        eng = Engine(measured.graph).start()
        print("MEASURING", flush=True)
        await eng.join(600)
        dt = time.monotonic() - t0
        from arroyo_tpu.parallel.sharded_state import MESH_STATS

        print(f"MESHSTATS {MESH_STATS['rows_sent']} "
              f"{MESH_STATS['rows_padded']} "
              f"{MESH_STATS['dispatches']} "
              f"{MESH_STATS['updates']} "
              f"{MESH_STATS['flushes_elided']} "
              f"{MESH_STATS['rows_combined']}", flush=True)
        # device-tier observatory (ISSUE 6): per-program dispatch-time
        # quantiles + per-rung padding waste, folded into the stage
        # budget so the mesh refactor has a before/after ledger
        from arroyo_tpu.obs import device as obs_device

        summ = obs_device.summary()
        print("DEVICE " + json.dumps({
            "programs": summ["programs"],
            "padding_waste": summ["padding_waste"],
            # fused segment runtime (ISSUE 14): per-segment dispatch
            # stats by tier + fused-op counts, so the BASELINE ledger
            # carries a per-segment row set next to the device programs
            "segments": summ["segments"],
        }), flush=True)
        print(f"RESULT {events / dt:.1f} 0 {dt:.2f}", flush=True)
        if linger > 0:
            # keep the loop (and the in-flight /debug/profile capture)
            # alive if the run finished before the window closed
            await asyncio.sleep(linger)
        if runner is not None:
            await runner.cleanup()

    asyncio.run(run_measured())


# -------------------------------------------------------------- parse

# sharded_state.py hosts both the directory facade and the accumulator;
# split its rows by function name so "directory work" and "host packing"
# stay separate stages
_DIR_FUNCS = {
    "assign", "owners_for", "take_bin", "_take_bin_arrays",
    "take_bin_arrays", "bin_entries", "_bin_entries_multi",
    "bin_entries_multi", "items", "keys_for_slots", "slots_for_keys",
    "remove", "peek_bin", "bins_up_to", "live_bins", "alloc_slot",
    "alloc_slots", "free_slot", "free_slots", "required_capacity",
    "entries_arrays", "n_live", "by_bin", "swap_to_native",
}

_ROW_RE = re.compile(
    r"^\s*(\S+)\s+([\d.]+)\s+[\d.]+\s+([\d.]+)\s+[\d.]+\s+(.+)$"
)


def classify(loc: str) -> str:
    l = loc.strip()
    if ("method 'poll'" in l or "method 'select'" in l or "epoll" in l
            or "_run_once" in l or "Event.wait" in l
            or "method 'acquire' of '_thread.lock'" in l):
        return "idle"
    if "directory.py" in l or "ops/native.py" in l or "arroyo_native" in l:
        return "directory"
    if "sharded_state.py" in l:
        fn = l.rsplit("(", 1)[-1].rstrip(")")
        return "directory" if fn in _DIR_FUNCS else "host_packing"
    if "jax" in l or "jaxlib" in l or "xla" in l:
        return "xla_dispatch"
    if "aggregates.py" in l:
        return "host_packing"
    if "numpy" in l or l.startswith("{method") and (
            "of 'numpy" in l or "ndarray" in l):
        return "numpy_kernels"
    if ("windows.py" in l or "updating.py" in l or "joins.py" in l
            or "operators/" in l):
        return "operator_host"
    if "pyarrow" in l or "expressions.py" in l or "schema.py" in l:
        return "sql_arrow"
    return "other"


def parse_profile(text: str) -> dict:
    """pstats table -> {stage: tottime seconds}."""
    stages: dict = {}
    for line in text.splitlines():
        m = _ROW_RE.match(line)
        if not m or m.group(4).startswith("filename:"):
            continue
        tottime = float(m.group(2))
        if tottime <= 0:
            continue
        stage = classify(m.group(4))
        stages[stage] = stages.get(stage, 0.0) + tottime
    return stages


def budget_from_stages(stages: dict) -> dict:
    """Normalize to a stage budget over the ACTIVE profiled time (idle —
    the event loop waiting with no work — is excluded and reported)."""
    idle = stages.pop("idle", 0.0)
    active = sum(stages.values())
    budget = {
        k: {"seconds": round(v, 3),
            "pct": round(100.0 * v / active, 1) if active else 0.0}
        for k, v in sorted(stages.items(), key=lambda kv: -kv[1])
    }
    return {"active_seconds": round(active, 3),
            "idle_seconds": round(idle, 3), "stages": budget}


# -------------------------------------------------------------- parent

def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", type=int, default=2_000_000)
    ap.add_argument("--mesh", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=10.0,
                    help="profile capture window")
    ap.add_argument("--markdown", action="store_true",
                    help="print a BASELINE.md-ready table")
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--linger", type=float, default=0.0)
    args = ap.parse_args()
    if args.child:
        child(args.events, args.mesh, args.linger)
        return 0

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY"):
        env.pop(var, None)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", "")).strip()
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.mesh}"
    ).strip()

    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--events", str(args.events), "--mesh", str(args.mesh),
           "--linger", str(args.seconds + 3.0)]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            stderr=subprocess.PIPE, cwd=REPO, env=env)

    port = None
    profile_text: list = []
    capture: list = [None]

    def grab(p: int):
        import urllib.request

        url = (f"http://127.0.0.1:{p}/debug/profile"
               f"?seconds={args.seconds}&limit=800")
        try:
            with urllib.request.urlopen(url, timeout=args.seconds + 60) as r:
                capture[0] = r.read().decode()
        except Exception as e:  # noqa: BLE001 - reported below
            capture[0] = None
            sys.stderr.write(f"profile capture failed: {e}\n")

    t = None
    result = None
    stats = None
    device = None
    assert proc.stdout is not None
    for line in proc.stdout:
        line = line.strip()
        if line.startswith("ADMIN "):
            port = int(line.split()[1])
        elif line == "MEASURING" and port is not None:
            t = threading.Thread(target=grab, args=(port,), daemon=True)
            t.start()
        elif line.startswith("RESULT "):
            parts = line.split()
            result = {"eps": float(parts[1]), "secs": float(parts[3])}
        elif line.startswith("DEVICE "):
            try:
                device = json.loads(line[len("DEVICE "):])
            except json.JSONDecodeError:
                device = None
        elif line.startswith("MESHSTATS "):
            parts = [int(x) for x in line.split()[1:]]
            shipped = parts[0] + parts[1]
            stats = {
                "rows_sent": parts[0], "rows_padded": parts[1],
                "padding_ratio": round(parts[1] / max(1, shipped), 3),
                "dispatches": parts[2], "updates": parts[3],
                "flushes_elided": parts[4] if len(parts) > 4 else 0,
                "rows_combined": parts[5] if len(parts) > 5 else 0,
            }
    if t is not None:
        t.join(args.seconds + 90)
    proc.wait(120)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.read()[-3000:] + "\n")
        return 1
    if capture[0] is None:
        sys.stderr.write("no profile captured (run too short for the "
                         "window? raise --events or lower --seconds)\n")
        return 1
    budget = budget_from_stages(parse_profile(capture[0]))
    out = {
        "metric": "q5_mesh_stage_budget",
        "mesh_devices": args.mesh,
        "events": args.events,
        "profile_seconds": args.seconds,
        **({"q5_mesh_eps": round(result["eps"], 1),
            "run_seconds": result["secs"]} if result else {}),
        **({"mesh_stats": stats} if stats else {}),
        **({"device_telemetry": device} if device else {}),
        **budget,
    }
    print(json.dumps(out))
    if args.markdown:
        print()
        print("| stage | seconds | % of active |")
        print("|---|---|---|")
        for k, v in budget["stages"].items():
            print(f"| {k} | {v['seconds']} | {v['pct']}% |")
        print(f"\nActive profiled time {budget['active_seconds']}s over a "
              f"{args.seconds}s window (idle {budget['idle_seconds']}s); "
              f"q5_mesh{args.mesh} "
              f"{out.get('q5_mesh_eps', 'n/a')} ev/s.")
        if device:
            # the observatory's per-program ledger: dispatch floor +
            # padding waste per rung beside the host-stage budget. The
            # exchange column is arroyo_device_exchange_seconds — the
            # keyed-shuffle collective's own time, which REPLACES the
            # old host-exchange stage rows of earlier BASELINE rounds
            # (those costs now live in the route/step programs)
            print("\n| program | compiles | compile s | dispatches "
                  "| dispatch p50/p95 | exchange s (n) | cache h/m |")
            print("|---|---|---|---|---|---|---|")
            for name, p in sorted(device.get("programs", {}).items()):
                dq = p.get("dispatch_quantiles", {})
                ex = (f"{p.get('exchange_s_total', 0)} "
                      f"({p.get('exchange_dispatches', 0)})"
                      if p.get("exchange_dispatches") else "-")
                print(f"| {name} | {p.get('compiles', 0)} "
                      f"| {p.get('compile_s_total', 0)} "
                      f"| {p.get('dispatches', 0)} "
                      f"| {dq.get('p50', 'n/a')}/{dq.get('p95', 'n/a')} s "
                      f"| {ex} "
                      f"| {p.get('cache_hit', 0)}/"
                      f"{p.get('cache_miss', 0)} |")
            waste = [w for w in device.get("padding_waste", [])
                     if w.get("waste")]
            if waste:
                print("\n| program | rung | padding waste |")
                print("|---|---|---|")
                for w in waste:
                    print(f"| {w['program']} | {w['rung']} "
                          f"| {100.0 * w['waste']:.1f}% |")
            segs = device.get("segments", {})
            if segs:
                # per-segment ledger (ISSUE 14): one row per fused
                # segment program — how many operator dispatches each
                # batch no longer pays, and what the single dispatch
                # costs per tier
                print("\n| segment | fused ops | tier | dispatches "
                      "| total s | p50/p95 |")
                print("|---|---|---|---|---|---|")
                for name, s in sorted(segs.items()):
                    for tier in ("host", "jax"):
                        n = s.get(f"{tier}_dispatches")
                        if not n:
                            continue
                        q = s.get(f"{tier}_quantiles", {})
                        print(f"| {name} | {s.get('fused_ops', '?')} "
                              f"| {tier} | {n} "
                              f"| {s.get(f'{tier}_s_total', 0)} "
                              f"| {q.get('p50', 'n/a')}/"
                              f"{q.get('p95', 'n/a')} s |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
