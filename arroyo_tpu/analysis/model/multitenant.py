"""Two jobs x shared multiplexed workers: per-job recovery independence.

The multiplexed worker (ISSUE 10) introduces a failure mode the
single-job model (spec.py) cannot see: ONE worker process hosts subtasks
of MANY jobs, so a worker death fails them all at once (shared fate).
The safety property the control plane owes tenants is **per-job recovery
independence**:

  * job A's kill/recovery never moves job B's state machine illegally
    (every move of EITHER job still goes through the extracted
    TRANSITIONS table — spec's conformance check, lifted to the product);
  * worker-side namespaces are job-scoped — a barrier fanned out by job
    A lands only in job A's namespace on the shared worker (V_LEAK), and
    job A's per-job teardown (StopJob) clears only job A's namespace
    (V_TEARDOWN);
  * a shared-worker death is observed and recovered by EACH hosted job
    independently; one job's recovery heals the pool (the scheduler's
    ensure-pool pass) without erasing the other's obligation to recover.

Model shape: two reduced job machines (CREATED -> SCHEDULING -> RUNNING
-> {RECOVERING -> SCHEDULING | STOPPING -> STOPPED | FAILED}, `epochs`
cadence barriers each) over `workers` SHARED worker slots. Each worker
slot holds one namespace per job (highest barrier epoch captured + live
flag). The one fault is the shared-worker kill: the slot dies, BOTH
jobs' namespaces on it vanish, and BOTH jobs' controllers are owed a
death observation (`pending_death`).

Mutants:

  * `leak_barrier_across_jobs` — the bug the job-scoped data-plane route
    namespaces prevent: a barrier fanned out by job A also lands in job
    B's namespace on the shared worker (an un-namespaced quad route
    match). Job B's namespace then carries an epoch B's machine never
    issued, flagged the moment B's capture bookkeeping reads it.
  * `teardown_clears_both_jobs` — job A's recovery teardown clears job
    B's live namespace too (StopJob scoping broken). The invariant
    observes the damage from B's side: RUNNING with no death owed, a
    live worker slot, and a destroyed namespace.

Explored exhaustively by `check_multitenant`; wired into
tools/model_check.py (--multi, corpus) and tests/test_model_check.py.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from .extract import job_state_machine_from_root


class MTConfig(NamedTuple):
    workers: int = 2          # shared pool slots
    epochs: int = 2           # cadence barriers per job per incarnation
    kills: int = 1            # shared-worker kill budget
    restarts: int = 2         # per-job recovery budget
    mutant: str = ""          # "" | a MT_MUTANTS key


class JobNS(NamedTuple):
    """One job's namespace on one shared worker slot."""

    seen: int = 0             # highest barrier epoch captured
    live: bool = False        # namespace built (job scheduled here)


class JobM(NamedTuple):
    """One job's controller-side machine (reduced)."""

    js: str = "CREATED"
    epoch: int = 0            # last ISSUED barrier epoch
    budget: int = 0
    reports: Tuple = ()       # ((epoch, widx), ...) credited completions
    restarts: int = 0
    stop: bool = False
    pending_death: bool = False  # a hosting worker died; recovery owed


class MTSys(NamedTuple):
    jobs: Tuple[JobM, ...]
    # ns[j][w]: job j's namespace on worker slot w
    ns: Tuple[Tuple[JobNS, ...], ...]
    alive: Tuple[bool, ...]   # shared worker slot liveness
    kills: int = 0


class MTStep(NamedTuple):
    label: str
    arg: Tuple
    nxt: Optional[MTSys]
    violation: str = ""


class MTTrace(NamedTuple):
    violation: str
    events: List[Tuple[str, Tuple]]
    config: dict


class MTResult(NamedTuple):
    states: int
    transitions: int
    violations: List[MTTrace]
    exhaustive: bool

    @property
    def clean(self) -> bool:
        return not self.violations


V_ILLEGAL = "illegal-jobstate-move"
V_LEAK = "cross-job-barrier-leak"
V_TEARDOWN = "cross-job-teardown"
V_DEADLOCK = "deadlock"


def _initial(cfg: MTConfig) -> MTSys:
    return MTSys(
        jobs=tuple(JobM(budget=cfg.epochs) for _ in range(2)),
        ns=tuple(
            tuple(JobNS() for _ in range(cfg.workers)) for _ in range(2)
        ),
        alive=tuple(True for _ in range(cfg.workers)),
    )


class MTModel:
    """Enabled-transition enumerator over the 2-job product. JobState
    moves go through the SAME extracted table as the single-job model."""

    def __init__(self, cfg: MTConfig,
                 transitions: Optional[Dict[str, Set[str]]] = None,
                 terminals: Optional[Set[str]] = None):
        if transitions is None or terminals is None:
            import os

            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
            _members, ext_terminals, ext_transitions = (
                job_state_machine_from_root(root)
            )
            transitions = (ext_transitions if transitions is None
                           else transitions)
            terminals = ext_terminals if terminals is None else terminals
        self.transitions = transitions
        self.terminals = terminals
        self.cfg = cfg

    # -- helpers -------------------------------------------------------------

    def _move(self, s: MTSys, j: int, label: str, nxt_js: str,
              **updates) -> MTStep:
        cur = s.jobs[j].js
        if nxt_js not in self.transitions.get(cur, set()):
            return MTStep(label, (j, cur, nxt_js), None,
                          f"{V_ILLEGAL}: job {j} {cur} -> {nxt_js}")
        jobs = list(s.jobs)
        jobs[j] = jobs[j]._replace(js=nxt_js, **updates)
        return MTStep(label, (j, cur, nxt_js),
                      s._replace(jobs=tuple(jobs)))

    @staticmethod
    def _set_ns(s: MTSys, j: int, w: int, ns: JobNS) -> MTSys:
        rows = [list(r) for r in s.ns]
        rows[j][w] = ns
        return s._replace(ns=tuple(tuple(r) for r in rows))

    def done(self, s: MTSys) -> bool:
        return all(jm.js in self.terminals for jm in s.jobs)

    # -- enumeration ---------------------------------------------------------

    def enabled(self, s: MTSys) -> List[MTStep]:
        cfg = self.cfg
        out: List[MTStep] = []
        for j, jm in enumerate(s.jobs):
            if jm.js in self.terminals:
                continue
            if jm.js == "CREATED":
                out.append(self._move(s, j, "mt.schedule_init",
                                      "SCHEDULING"))
            elif jm.js == "SCHEDULING":
                out.append(self._schedule(s, j))
            elif jm.js == "RECOVERING":
                out.append(self._recover(s, j))
            elif jm.js == "RUNNING":
                if jm.pending_death:
                    # shared fate: each hosted job observes the shared
                    # worker's death via ITS heartbeat view and recovers
                    # independently of its co-tenant
                    out.append(self._move(
                        s, j, "mt.detect_death", "RECOVERING",
                    ))
                if jm.budget > 0 and not jm.stop and not jm.pending_death:
                    out.append(self._barrier(s, j))
                out.extend(self._capture_steps(s, j))
                if not jm.stop and not jm.pending_death:
                    jobs = list(s.jobs)
                    jobs[j] = jm._replace(stop=True)
                    out.append(MTStep("mt.stop_request", (j,),
                                      s._replace(jobs=tuple(jobs))))
                if jm.stop and not jm.pending_death:
                    out.append(self._finish(s, j))
        if s.kills < cfg.kills:
            for w in range(cfg.workers):
                if s.alive[w]:
                    alive = list(s.alive)
                    alive[w] = False
                    # the worker process dies: every job's namespace on
                    # it vanishes at once, and every RUNNING job is owed
                    # a death observation
                    jobs = tuple(
                        jm._replace(pending_death=True)
                        if jm.js in ("RUNNING", "SCHEDULING") else jm
                        for jm in s.jobs
                    )
                    nxt = s._replace(alive=tuple(alive), jobs=jobs,
                                     kills=s.kills + 1)
                    for j in range(2):
                        nxt = self._set_ns(nxt, j, w, JobNS())
                    out.append(MTStep("mt.kill_worker", (w,), nxt))
        return out

    def _schedule(self, s: MTSys, j: int) -> MTStep:
        # the scheduler's ensure-pool pass replaces dead slots for
        # EVERYONE, then ONLY job j's namespaces are (re)built — the
        # co-tenant's pending death observation survives the heal
        nxt = s._replace(alive=tuple(True for _ in s.alive))
        for w in range(self.cfg.workers):
            nxt = self._set_ns(nxt, j, w, JobNS(live=True))
        return self._move(nxt, j, "mt.schedule", "RUNNING",
                          epoch=0, budget=self.cfg.epochs, reports=(),
                          pending_death=False)

    def _recover(self, s: MTSys, j: int) -> MTStep:
        jm = s.jobs[j]
        if jm.restarts >= self.cfg.restarts:
            return self._move(s, j, "mt.fail", "FAILED")
        # per-job teardown: ONLY job j's namespaces are cleared; the
        # teardown mutant wipes the co-tenant's too (StopJob unscoped)
        nxt = s
        for w in range(self.cfg.workers):
            nxt = self._set_ns(nxt, j, w, JobNS())
            if self.cfg.mutant == "teardown_clears_both_jobs":
                nxt = self._set_ns(nxt, 1 - j, w, JobNS())
        return self._move(nxt, j, "mt.recover", "SCHEDULING",
                          restarts=jm.restarts + 1, reports=())

    def _barrier(self, s: MTSys, j: int) -> MTStep:
        jm = s.jobs[j]
        epoch = jm.epoch + 1
        jobs = list(s.jobs)
        jobs[j] = jm._replace(epoch=epoch, budget=jm.budget - 1)
        nxt = s._replace(jobs=tuple(jobs))
        if self.cfg.mutant == "leak_barrier_across_jobs":
            # the bug the job-scoped route namespaces prevent: the
            # barrier frame matches the OTHER job's identical quad on
            # the shared worker and lands in its namespace too
            other = 1 - j
            for w in range(self.cfg.workers):
                if nxt.alive[w] and nxt.ns[other][w].live:
                    leaked = nxt.ns[other][w]
                    if epoch > leaked.seen:
                        nxt = self._set_ns(
                            nxt, other, w, leaked._replace(seen=epoch)
                        )
        return MTStep("mt.barrier", (j, epoch), nxt)

    def _capture_steps(self, s: MTSys, j: int) -> List[MTStep]:
        out: List[MTStep] = []
        jm = s.jobs[j]
        for w in range(self.cfg.workers):
            nsw = s.ns[j][w]
            if not s.alive[w] or not nsw.live:
                continue
            if nsw.seen > jm.epoch:
                # the namespace carries an epoch this job's machine
                # NEVER issued — a barrier leaked across job namespaces
                out.append(MTStep(
                    "mt.capture", (j, w, nsw.seen), None,
                    f"{V_LEAK}: job {j} namespace on worker {w} holds "
                    f"epoch {nsw.seen} but the job only issued {jm.epoch}",
                ))
                continue
            if nsw.seen < jm.epoch:
                e = nsw.seen + 1
                nxt = self._set_ns(s, j, w, nsw._replace(seen=e))
                if (e, w) not in jm.reports:
                    jobs = list(nxt.jobs)
                    jobs[j] = jobs[j]._replace(
                        reports=tuple(sorted(jm.reports + ((e, w),)))
                    )
                    nxt = nxt._replace(jobs=tuple(jobs))
                out.append(MTStep("mt.capture", (j, w, e), nxt))
        return out

    def _finish(self, s: MTSys, j: int) -> MTStep:
        # reduced stop path: RUNNING -> STOPPING -> STOPPED must BOTH be
        # legal per the extracted table
        st = self._move(s, j, "mt.stop_begin", "STOPPING")
        if st.nxt is None:
            return st
        st2 = self._move(st.nxt, j, "mt.stop_finish", "STOPPED",
                         stop=False)
        return MTStep("mt.stop", (j,), st2.nxt, st2.violation)

    def check_state(self, s: MTSys,
                    enabled: List[MTStep]) -> Optional[str]:
        # per-job recovery independence: a RUNNING job owed no death
        # observation must still have every namespace it was scheduled
        # with — a destroyed namespace on a LIVE slot means someone
        # else's teardown reached across job boundaries
        for j, jm in enumerate(s.jobs):
            if jm.js != "RUNNING" or jm.pending_death:
                continue
            for w in range(len(s.alive)):
                if s.alive[w] and not s.ns[j][w].live:
                    return (f"{V_TEARDOWN}: job {j} lost its namespace "
                            f"on live worker {w} without a death to "
                            f"observe (cross-job teardown)")
        if not self.done(s) and not enabled:
            return (f"{V_DEADLOCK}: jobs "
                    f"{tuple(jm.js for jm in s.jobs)}")
        return None


def check_multitenant(cfg: MTConfig, budget: int = 500_000,
                      transitions=None, terminals=None) -> MTResult:
    """BFS the 2-job product; violations carry replayable event paths."""
    model = MTModel(cfg, transitions=transitions, terminals=terminals)
    init = _initial(cfg)
    parent: Dict[MTSys, Optional[Tuple[MTSys, Tuple[str, Tuple]]]] = {
        init: None
    }
    frontier = deque([init])
    violations: List[MTTrace] = []
    seen_kinds: Set[str] = set()
    n_trans = 0
    exhaustive = True

    def record(state: MTSys, ev, violation: str):
        kind = violation.split(":", 1)[0]
        if kind in seen_kinds:
            return
        seen_kinds.add(kind)
        events: List[Tuple[str, Tuple]] = [ev] if ev else []
        cur = state
        while parent[cur] is not None:
            prev, e = parent[cur]
            events.append(e)
            cur = prev
        events.reverse()
        violations.append(MTTrace(violation, events, cfg._asdict()))

    while frontier:
        if len(parent) > budget:
            exhaustive = False
            break
        state = frontier.popleft()
        steps = model.enabled(state)
        inv = model.check_state(state, steps)
        if inv is not None:
            record(state, None, inv)
            continue
        if model.done(state):
            continue
        for st in steps:
            n_trans += 1
            if st.violation:
                record(state, (st.label, st.arg), st.violation)
                continue
            if st.nxt is None or st.nxt in parent:
                continue
            parent[st.nxt] = (state, (st.label, st.arg))
            frontier.append(st.nxt)

    return MTResult(states=len(parent), transitions=n_trans,
                    violations=violations, exhaustive=exhaustive)


class MTMutant(NamedTuple):
    name: str
    description: str
    expect_violation: str
    config: MTConfig


MT_MUTANTS: Dict[str, MTMutant] = {
    m.name: m
    for m in [
        MTMutant(
            name="leak_barrier_across_jobs",
            description=(
                "a barrier fanned out by job A is also delivered into "
                "job B's namespace on the shared worker (the bug the "
                "job-scoped data-plane route namespaces prevent): job "
                "B's namespace carries an epoch B's machine never issued"
            ),
            expect_violation=V_LEAK,
            config=MTConfig(mutant="leak_barrier_across_jobs"),
        ),
        MTMutant(
            name="teardown_clears_both_jobs",
            description=(
                "job A's recovery teardown clears job B's live "
                "namespace on the shared worker (per-job StopJob "
                "scoping broken): co-resident jobs are not independent"
            ),
            expect_violation=V_TEARDOWN,
            config=MTConfig(mutant="teardown_clears_both_jobs"),
        ),
    ]
}
