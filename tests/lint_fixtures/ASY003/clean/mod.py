"""Must NOT fire ASY003: async lock, or await-free critical section."""
import asyncio
import threading

ALOCK = asyncio.Lock()
LOCK = threading.Lock()


async def go(q):
    async with ALOCK:
        await q.get()
    with LOCK:
        n = 1 + 1  # no suspension point under the sync lock
    return n
