"""Collectors: route operator output onto downstream edge queues.

Capability parity with the reference's ArrowCollector + repartition
(/root/reference/crates/arroyo-operator/src/context.rs:506-610): keyed
shuffle edges hash the routing-key columns and slice one sub-batch per
destination partition; unkeyed shuffle edges rotate whole batches
round-robin (the reference slices round-robin with a random rotation — we
keep a deterministic per-subtask rotation so tests are reproducible);
forward edges are 1-1. Signals broadcast to every destination queue.
"""

from __future__ import annotations

import time
import weakref
from typing import List, Optional

import pyarrow as pa

from .. import chaos
from ..metrics import BACKPRESSURE, BATCHES_SENT, BYTES_SENT, MESSAGES_SENT
from ..obs import timeline
from ..schema import StreamSchema
from ..types import SignalKind, SignalMessage
from .queues import BatchQueue, batch_bytes


class EdgeSender:
    def __init__(
        self,
        edge_type,
        schema: StreamSchema,
        queues: List[BatchQueue],
        src_subtask: int = 0,
    ):
        from ..graph.logical import EdgeType  # avoid import cycle

        self.edge_type = edge_type
        self.schema = schema
        self.queues = queues
        self.src_subtask = src_subtask
        self._rr = src_subtask  # round-robin cursor for unkeyed shuffles
        self._marker_rr = src_subtask  # separate cursor for latency markers
        self._is_forward = edge_type == EdgeType.FORWARD
        # conservation ledger (obs/audit.py): one sender-side attestation
        # tap per destination queue, built lazily on the first send so
        # config is resolved once. None entries = auditing off or a queue
        # the wiring didn't stamp (engine-internal previews).
        self._audit_taps: Optional[list] = None

    def _taps(self) -> list:
        if self._audit_taps is None:
            from ..obs import audit

            if audit.enabled():
                self._audit_taps = [
                    audit.EdgeTap(q.audit_edge)
                    if getattr(q, "audit_edge", None) else None
                    for q in self.queues
                ]
            else:
                self._audit_taps = [None] * len(self.queues)
        return self._audit_taps

    async def _send_data(self, idx: int, batch: pa.RecordBatch):
        """All data batches leave through here: attest to the queue's tap
        FIRST (the attestation states what the operator chain emitted),
        then pass the chaos dropped-flush seam — a fired drop means rows
        the sender attested never reach the receiver, which is exactly
        the lost-delivery shape the reconciler must flag."""
        tap = self._taps()[idx]
        if tap is not None:
            tap.observe(batch)
            if chaos.fire("audit.drop_batch", edge=tap.edge):
                return
        await self.queues[idx].send(batch)

    async def send_batch(self, batch: pa.RecordBatch):
        n = len(self.queues)
        if self._is_forward or n == 1:
            idx = self.src_subtask % n if self._is_forward else 0
            await self._send_data(idx, batch)
            return
        if self.schema.key_indices:
            parts = self.schema.partition(batch, n)
            for i, part in enumerate(parts):
                if part is not None and part.num_rows:
                    await self._send_data(i, part)
        else:
            self._rr = (self._rr + 1) % n
            await self._send_data(self._rr, batch)

    def seal_audit(self, epoch: int) -> None:
        """Seal every destination tap's running attestation at this
        epoch's barrier broadcast (the sender-side epoch cut)."""
        for tap in self._taps():
            if tap is not None:
                tap.seal(epoch)

    def drain_audit(self, epoch: int, out: dict) -> None:
        """Move this sender's sealed epoch attestations into `out`
        (edge -> [rows, digest]) for the checkpoint report."""
        for tap in self._taps():
            if tap is not None:
                v = tap.drain(epoch)
                if v is not None:
                    out[tap.edge] = [v[0], v[1]]

    async def broadcast(self, signal: SignalMessage):
        if signal.kind == SignalKind.BARRIER:
            self.seal_audit(signal.barrier.epoch)
        if self._is_forward:
            await self.queues[self.src_subtask % len(self.queues)].send(signal)
        else:
            for q in self.queues:
                await q.send(signal)

    async def send_marker(self, signal: SignalMessage):
        """Forward a latency marker to exactly ONE destination (Flink's
        latency-marker rule: broadcasting across every shuffle hop would
        multiply markers combinatorially along the depth of the graph).
        Rotates a dedicated cursor so all destination subtasks get
        sampled over time — deliberately separate from the unkeyed-data
        round-robin cursor, which must keep routing the exact same
        batches to the exact same queues (chaos drills compare output
        byte-identically with obs on and off)."""
        if self._is_forward:
            await self.queues[self.src_subtask % len(self.queues)].send(signal)
            return
        self._marker_rr = (self._marker_rr + 1) % len(self.queues)
        await self.queues[self._marker_rr].send(signal)


class Collector:
    """The tail collector of a subtask: fans output to all out edges and
    maintains tx counters."""

    def __init__(self, edges: List[EdgeSender], task_id: str = "",
                 job_id: str = ""):
        self.edges = edges
        self.task_id = task_id
        self._batch_counter = BATCHES_SENT.labels(job=job_id, task=task_id)
        self._msg_counter = MESSAGES_SENT.labels(job=job_id, task=task_id)
        self._bytes_counter = BYTES_SENT.labels(job=job_id, task=task_id)
        self._bp_gauge = BACKPRESSURE.labels(job=job_id, task=task_id)
        self._bp_tick = 0
        # the sampled update in collect() goes stale the moment a stream
        # quiesces (no more collect() calls ever re-sample it — ADVICE
        # r5), so the gauge also refreshes at scrape time: a weakly-bound
        # refresher recomputes occupancy on expose/snapshot and
        # unregisters itself once this collector is garbage-collected
        ref = weakref.ref(self)

        def _bp_now():
            c = ref()
            if c is None:
                return None
            return max(
                (q.fullness() for e in c.edges for q in e.queues),
                default=0.0,
            )

        self._bp_gauge.set_refresher(_bp_now)
        # sink-side hook: engine-level capture of terminal output (preview)
        self.collected: Optional[list] = None

    # backpressure needs sampling granularity, not per-batch accuracy:
    # recomputing the max over every out-queue on every collect() added a
    # python generator walk to the hottest path (ADVICE r4)
    _BP_SAMPLE_EVERY = 16

    async def collect(self, batch: pa.RecordBatch):
        if batch.num_rows == 0:
            return
        self._batch_counter.inc()
        self._msg_counter.inc(batch.num_rows)
        self._bytes_counter.inc(batch_bytes(batch))
        # fleet observatory: emit time (partitioning + queue sends,
        # INCLUDING any backpressure wait) is its own timeline phase —
        # a batch stuck here points downstream, not at this operator
        t0 = time.perf_counter()
        for edge in self.edges:
            await edge.send_batch(batch)
        timeline.note("emit", time.perf_counter() - t0, task=self.task_id)
        self._bp_tick += 1
        if self._bp_tick == 1 or self._bp_tick % self._BP_SAMPLE_EVERY == 0:
            # post-send occupancy of the most-loaded out queue: 1.0 means
            # the next send blocks (downstream is the bottleneck)
            self._bp_gauge.set(max(
                (q.fullness() for e in self.edges for q in e.queues),
                default=0.0,
            ))

    async def broadcast(self, signal: SignalMessage):
        for edge in self.edges:
            await edge.broadcast(signal)

    @property
    def is_terminal(self) -> bool:
        """No out edges: this subtask ends the pipeline (sink / preview
        tail) — latency markers arriving here measure end-to-end."""
        return not self.edges

    async def forward_marker(self, signal: SignalMessage):
        """Latency markers go to one destination per out edge (see
        EdgeSender.send_marker)."""
        for edge in self.edges:
            await edge.send_marker(signal)
