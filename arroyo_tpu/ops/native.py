"""Loader + wrapper for the native (C++) slot directory.

The native path handles the common single-int64-key case; everything else
falls back to the python SlotDirectory. Build happens lazily on first use
(g++ is in the image); failures degrade silently to the python
implementation.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

import numpy as np

_native = None
_tried = False


def load_native():
    global _native, _tried
    if _tried:
        return _native
    _tried = True
    if os.environ.get("ARROYO_DISABLE_NATIVE"):
        return None
    try:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        native_dir = os.path.join(repo_root, "native")
        sys.path.insert(0, native_dir)
        try:
            try:
                import arroyo_native  # noqa: F401
            except ImportError:
                from importlib import invalidate_caches

                build_py = os.path.join(native_dir, "build.py")
                import importlib.util

                spec = importlib.util.spec_from_file_location("_anb", build_py)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                mod.build()
                invalidate_caches()
                import arroyo_native  # noqa: F401
        finally:
            # the extension stays imported; nothing else should resolve
            # through native/ (it contains a generic build.py)
            try:
                sys.path.remove(native_dir)
            except ValueError:
                pass
        _native = arroyo_native
    except Exception:  # noqa: BLE001 - silent fallback to python impl
        _native = None
    return _native


class NativeSlotDirectory:
    """Single-int64-key directory over the C++ open-addressing table,
    API-compatible with ops.directory.SlotDirectory for the paths the
    window operators use. Keys surface as 1-tuples like the python impl."""

    def __init__(self, native_mod, n_keys: int = 1):
        self._d = native_mod.SlotDir()
        self.n_keys = n_keys  # 0 = unkeyed (synthetic zero key, empty tuples)
        self.free: list = []  # parity attribute; slot reuse lives natively

    @property
    def n_live(self) -> int:
        return self._d.n_live()

    def required_capacity(self) -> int:
        return self._d.required_capacity()

    def assign(self, bins: np.ndarray, key_cols: List[np.ndarray]) -> np.ndarray:
        key = key_cols[0] if key_cols else np.zeros(len(bins), dtype=np.int64)
        if key.dtype == np.uint64:
            key = key.view(np.int64)
        out = self._d.assign(
            np.ascontiguousarray(bins, dtype=np.int64),
            np.ascontiguousarray(key, dtype=np.int64),
        )
        return np.frombuffer(out, dtype=np.int64)

    def take_bin(self, b: int) -> Tuple[List[tuple], np.ndarray]:
        keys_raw, slots_raw = self._d.take_bin(int(b))
        keys = np.frombuffer(keys_raw, dtype=np.int64)
        slots = np.frombuffer(slots_raw, dtype=np.int64).copy()
        if self.n_keys == 0:
            return [() for _ in keys], slots
        return [(int(k),) for k in keys], slots

    def bin_entries(self, b: int) -> Tuple[np.ndarray, np.ndarray]:
        """(keys int64, slots int64) of a live bin, without removal."""
        keys_raw, slots_raw = self._d.get_bin(int(b))
        return (
            np.frombuffer(keys_raw, dtype=np.int64),
            np.frombuffer(slots_raw, dtype=np.int64),
        )

    @property
    def by_bin(self):
        # truthiness probe used by the sliding operator ("anything live?")
        return {b: True for b in self._d.live_bins()}

    def peek_bin(self, b: int):
        keys, _ = self.bin_entries(b)
        if not len(keys):
            return None
        if self.n_keys == 0:
            return {(): None}
        return {(int(k),): None for k in keys}

    def live_bins(self) -> List[int]:
        return sorted(self._d.live_bins())

    def bins_up_to(self, limit: int) -> List[int]:
        return sorted(b for b in self._d.live_bins() if b < limit)

    def items(self):
        bins_raw, keys_raw, slots_raw = self._d.entries()
        bins = np.frombuffer(bins_raw, dtype=np.int64)
        keys = np.frombuffer(keys_raw, dtype=np.int64)
        slots = np.frombuffer(slots_raw, dtype=np.int64)
        for b, k, s in zip(bins, keys, slots):
            yield int(b), (() if self.n_keys == 0 else (int(k),)), int(s)


def supports_native(key_types) -> bool:
    """Native fast path: zero or one key column of integer/timestamp type."""
    if load_native() is None:
        return False
    if len(key_types) > 1:
        return False
    if not key_types:
        return True
    import pyarrow as pa

    t = key_types[0]
    # bool keys stay on the python path: native returns python ints and
    # pa.array(ints, type=bool_) is rejected at emission
    return pa.types.is_integer(t) or pa.types.is_timestamp(t)
