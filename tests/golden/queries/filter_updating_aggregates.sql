--pk=subtasks
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE filter_updating_aggregates (
  subtasks BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO filter_updating_aggregates
SELECT * FROM (
  SELECT count(DISTINCT subtask_index) as subtasks FROM impulse_source
)
WHERE subtasks >= 1;
