"""MUST fire PRO002: illegal transition target + direct .state assignment
(plus the STALLED state in state_machine.py with no outgoing entry)."""
from .state_machine import JobState, TRANSITIONS  # noqa: F401


class Job:
    def __init__(self):
        self.state = JobState.CREATED  # allowed: state-machine owner init

    def transition(self, nxt):
        self.state = nxt  # allowed: the checked setter itself


def drive(job):
    job.transition(JobState.CREATED)  # CREATED is never a declared target
    job.state = JobState.FAILED  # bypasses check_transition
