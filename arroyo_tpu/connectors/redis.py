"""Placeholder: redis connector lands with the connector milestone."""
