"""TPU compute kernels (jax/XLA/pallas) — the hot data path.

All device code lives here. Everything is shape-bucketed: variable-length
batches are padded to the next bucket size so XLA compiles a bounded set of
programs. jax is imported lazily (aggregates._get_jax) so host-only
deployments can run numpy-backend pipelines without it; the first device
use enables x64 (SQL semantics: COUNT/SUM(int) are 64-bit; the
bit-identical-aggregates target requires exact integer arithmetic).
"""

from .aggregates import (  # noqa: F401
    AggSpec,
    Accumulator,
    make_accumulator,
)
