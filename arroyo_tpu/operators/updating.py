"""Placeholder: updating aggregates / retractions (reference
incremental_aggregator.rs) land with the updating milestone."""
