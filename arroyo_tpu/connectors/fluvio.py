"""Fluvio connector (reference: crates/arroyo-connectors/src/fluvio/,
541 LoC). Client gated on the fluvio python client."""

from __future__ import annotations

import asyncio
from typing import Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector


class FluvioSource(SourceOperator):
    def __init__(self, endpoint: Optional[str], topic: str, schema, format,
                 bad_data):
        super().__init__("fluvio_source")
        self.endpoint = endpoint
        self.topic = topic
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.offset = 0

    def tables(self):
        from ..state.table_config import global_table

        return {"flv": global_table("flv")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("flv")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.offset = stored

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("flv")
            table.put(ctx.task_info.task_index, self.offset)

    async def run(self, ctx, collector) -> SourceFinishType:
        fluvio = require_client("fluvio")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        client = fluvio.Fluvio.connect()
        consumer = client.partition_consumer(
            self.topic, ctx.task_info.task_index
        )
        # the fluvio client is synchronous: a daemon pump thread iterates
        # the blocking stream into a bounded queue, so an idle partition
        # never blocks the event loop, and a stop can't hang interpreter
        # shutdown on a parked non-daemon executor thread
        import queue as _queue
        import threading

        it = iter(consumer.stream(fluvio.Offset.absolute(self.offset)))
        sentinel = object()
        q: _queue.Queue = _queue.Queue(maxsize=4096)
        pump_error: list = []

        def pump():
            try:
                for record in it:
                    q.put(record)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                # surface broker failures on the consumer side — a
                # swallowed exception would end the stream "cleanly" and
                # mark the job Finished with silent data loss
                pump_error.append(e)
            finally:
                q.put(sentinel)

        threading.Thread(
            target=pump, daemon=True, name="fluvio-pump"
        ).start()
        while True:
            finish = await ctx.check_control(collector)
            if finish is not None:
                return finish
            try:
                record = q.get_nowait()
            except _queue.Empty:
                await self.flush_buffer(ctx, collector)
                await asyncio.sleep(0.02)
                continue
            if record is sentinel:
                if pump_error:
                    raise pump_error[0]
                break
            for row in deser.deserialize_slice(
                bytes(record.value()), error_reporter=ctx.error_reporter
            ):
                ctx.buffer_row(row)
            self.offset = record.offset() + 1
            if ctx.should_flush():
                await self.flush_buffer(ctx, collector)
        return SourceFinishType.FINAL


class FluvioSink(Operator):
    def __init__(self, endpoint: Optional[str], topic: str, format):
        super().__init__("fluvio_sink")
        self.endpoint = endpoint
        self.topic = topic
        self.serializer = Serializer(format=format or "json")
        self.producer = None

    async def on_start(self, ctx):
        fluvio = require_client("fluvio")
        self.producer = fluvio.Fluvio.connect().topic_producer(self.topic)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        for rec in self.serializer.serialize(batch):
            self.producer.send(b"", rec)


@register_connector
class FluvioConnector(Connector):
    name = "fluvio"
    description = "Fluvio source and sink"
    source = True
    sink = True
    config_schema = {
        "endpoint": {"type": "string"},
        "topic": {"type": "string", "required": True},
    }

    def validate_options(self, options, schema):
        if "topic" not in options:
            raise ValueError("fluvio requires a topic option")
        return {"endpoint": options.get("endpoint"), "topic": options["topic"]}

    def make_source(self, config, schema: ConnectionSchema):
        return FluvioSource(config.get("endpoint"), config["topic"],
                            config.get("schema"), config.get("format"),
                            config.get("bad_data", "fail"))

    def make_sink(self, config, schema: ConnectionSchema):
        return FluvioSink(config.get("endpoint"), config["topic"],
                          config.get("format"))
