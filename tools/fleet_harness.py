#!/usr/bin/env python3
"""Multi-tenant fleet churn harness (ISSUE 10, ROADMAP item 3).

Drives create/preview/run/stop churn of hundreds of TINY pipelines
through the REAL REST API against one controller + one shared
multiplexed worker pool (the "millions of users" proxy), and reports the
control-plane scaling metrics the bench gate pins:

  fleet_jobs_per_controller   max concurrently RUNNING jobs one
                              controller held (higher is better);
  fleet_idle_cpu_ms           process CPU milliseconds per PARKED job
                              per second at full scale (lower is better
                              — the event-driven controller makes idle
                              cost ~O(changed jobs), not O(jobs)·50 Hz);
  fleet_api_p99_ms            REST p99 latency under churn (lower);
  fleet_idle_cpu_flatness     total idle CPU at full scale over total at
                              quarter scale (diagnostic: ~1 means idle
                              cost is flat in job count; the old poll
                              loops measured ~4, i.e. linear);
  fleet_wakeups_per_job_s     controller driver wakeups per parked
                              job-second (diagnostic; poll loops burned
                              50/s);
  fleet_exactly_once_ok       1 iff every sampled bounded job's output
                              was byte-identical to its solo run.

Fleet observatory (ISSUE 11): unless --no-doctor, the harness also
(a) audits per-job cost attribution — attributed busy seconds summed
across tenants must cover >= 95% of the pool's measured busy time
(fleet_attr_coverage_pct) — and (b) runs the noisy-neighbor scenario:
one deliberately hot "hog" tenant floods the shared pool while the
parked fleet idles, and the bottleneck doctor, asked about a parked
victim job, must name the cause noisy-neighbor AND the hog job as the
suspect (fleet_doctor_ok; exercised through the real REST
/jobs/{id}/doctor route). Either failing exits 1, like an exactly-once
mismatch.

Exactly-once under churn: a sample of bounded deterministic impulse
pipelines runs INSIDE the churning fleet; each output is compared
byte-for-byte (canonical sorted JSON rows) against a solo run of the
same SQL on a fresh single-job cluster. `--kill` additionally SIGKILLs
one pool worker mid-churn, so the sampled jobs prove recovery-under-
multiplexing (the fast-tier smoke test always does).

StateServe read load (ISSUE 12): `--serve` switches to the queryable-
state scenario — continuous keyed windowed-agg tenant pipelines + parked
jobs on the shared pool, thousands of lookups/s through the REAL REST
state routes (point GETs + bulk POSTs), measuring achieved lookups/s,
read p50/p99, cache hit ratio, value LEGALITY (deterministic replay
pacing makes every full window's per-key count exact) and per-key
window-end monotonicity (a backwards window = a stale/torn read), plus
the q5-shaped bounded pipeline's throughput solo vs under load
(serve_pipeline_eps — the zero-impact gate key; on this 1-core host the
solo-vs-loaded delta is bounded below by raw CPU sharing, so the GATE is
the pinned loaded number, not the ratio). `--serve-kill` SIGKILLs a pool
worker mid-load: reads must degrade to retriable errors — a wrong value
or non-retriable error exits 1. serve_* keys gate against
BENCH_BASELINE.json via tools/bench_compare.py in the nightly serve lane.

Follower read replicas (ISSUE 20): `--serve --followers N` adds the
controller-hosted follower tier tailing the serving jobs' checkpoint
stream. The load only starts once every serving job answers reads with
source == "follower", then gates: worker QueryState RPC count over the
serving jobs stays EXACTLY zero (serve_follower_worker_rpcs — followers
serve off published state, never off workers), every read's staleness
(published epoch minus served epoch) is bounded at one checkpoint
interval, and serve_follower_lookup_eps pins follower-leg throughput.
`--serve-kill-follower` kills follower 0 mid-load: reads must fail over
worker-ward (staleness 0) with zero wrong values, and the follower must
reattach from latest.json within the controller's cadence.

Watchtower SLO drill (ISSUE 13): `--watch` runs the alerting scenario —
one victim tenant is stalled (chaos `runner.stall` on its job id +
storage latency on its checkpoint data files + a sub-timeout heartbeat
blackout) among `--watch-healthy` co-tenants; the watchtower must fire
the freshness alert naming exactly the victim, capture a diagnostic
bundle whose flight recording covers the breach window, and CLEAR after
recovery, with watch_false_positive_count == 0 (any firing event naming
a healthy tenant fails the run). Committed as WATCH_r01.json; the
nightly `watch` CI lane gates it via bench_compare's exact-zero class.

Shared-plan fleet A/B (ISSUE 16): `--shared-fleet` runs the SAME
`--jobs` tenants twice — identical deterministic source scan with
per-tenant tails, once all mounted on one hidden `__shared/<fp>` host
(sharing on) and once each owning its full data plane (sharing off) —
and gates: aggregate source events/s with sharing must exceed 5x the
unshared run (fleet_shared_agg_eps / fleet_unshared_agg_eps, both
pinned in BENCH_BASELINE.json), every tenant's output byte-identical
across the passes, the mount actually engaged (refcount peak == jobs),
and the cost apportioner keeping the >= 95% attributed-coverage gate
over the shared fleet with no `__shared/*` bucket left behind.

Usage:
  python tools/fleet_harness.py --jobs 100 --pool 2 --sample 8 \
      [--churn 30] [--idle-seconds 10] [--kill] [--out fleet.json]
  python tools/fleet_harness.py --serve [--serve-kill] \
      [--serve-duration 10] [--serve-clients 6] [--out serve.json]
  python tools/fleet_harness.py --serve --followers 1 \
      [--serve-kill-follower] [--out serve_follower.json]
  python tools/fleet_harness.py --shared-fleet --jobs 100 \
      [--shared-events 50000] [--out shared_fleet.json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from bench import PIN_ERA  # noqa: E402 - era-stamps every harness report


def sample_sql(outdir: str, tag: str, j: int, events: int) -> str:
    """Bounded deterministic pipeline: byte-identical across runs."""
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '{events}', start_time = '0'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{outdir}/{tag}-{j}.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 8 as k, tumble(interval '1 millisecond') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


def parked_sql(outdir: str, j: int) -> str:
    """A realtime trickle source (one event per 20 s): RUNNING but idle —
    the parked-job shape whose control-plane cost the harness measures."""
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '0.05',
      message_count = '1000000', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{outdir}/parked-{j}.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 4 as k, tumble(interval '1 second') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


def canonical_rows(path: str) -> list:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return sorted(
            json.dumps(json.loads(line), sort_keys=True)
            for line in f if line.strip()
        )


def pct(samples: list, q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


class _Api:
    """Timed aiohttp client against the harness's REST server: every call
    lands in the latency sample set the p99 gate reads."""

    def __init__(self, session, base: str, latencies: list):
        self.session = session
        self.base = base
        self.latencies = latencies

    async def call(self, method: str, path: str, **kw):
        t0 = time.monotonic()
        async with self.session.request(
            method, self.base + path, **kw
        ) as resp:
            body = await resp.json()
        self.latencies.append((time.monotonic() - t0) * 1e3)
        return resp.status, body


async def _measure_idle(controller, n_jobs: int, seconds: float) -> dict:
    """Park and measure: process CPU + controller driver wakeups over a
    window with every fleet job RUNNING-idle."""
    w0 = sum(j.wakeups for j in controller.jobs.values())
    c0 = time.process_time()
    t0 = time.monotonic()
    await asyncio.sleep(seconds)
    wall = time.monotonic() - t0
    cpu = time.process_time() - c0
    wakeups = sum(j.wakeups for j in controller.jobs.values()) - w0
    return {
        "cpu_s": cpu,
        "wall_s": wall,
        "cpu_ms_per_job_s": 1e3 * cpu / wall / max(n_jobs, 1),
        "wakeups_per_job_s": wakeups / wall / max(n_jobs, 1),
    }


async def run_fleet(jobs: int = 100, pool: int = 2, sample: int = 8,
                    churn: int = 30, previews: int = 5,
                    idle_seconds: float = 10.0, kill: bool = False,
                    doctor: bool = True, doctor_events: int = 1_500_000,
                    workdir: str | None = None) -> dict:
    from aiohttp import ClientSession, web

    from arroyo_tpu.api.rest import build_app
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    workdir = workdir or tempfile.mkdtemp(prefix="arroyo-fleet-")
    os.makedirs(workdir, exist_ok=True)
    report: dict = {"jobs": jobs, "pool": pool, "sample": sample,
                    "churn": churn, "workdir": workdir}
    latencies: list = []

    # fleet jobs are tiny + stateless: no checkpoint storage (the chaos
    # drills own durable exactly-once; the sampled jobs prove exactly-
    # once of the MULTIPLEXED data plane under churn + kill)
    with update(
        pipeline={"checkpointing": {"storage_url": ""}},
        cluster={"worker_pool_size": pool, "metrics_ttl": 1.0},
        controller={"heartbeat_timeout": 10.0},
        # slots sized for tiny-job density: N one-slot parked jobs (plus
        # in-flight churn) must all be admitted concurrently — slot count
        # is the admission currency, not a thread count
        worker={"task_slots": max(4, (jobs + sample + churn) // pool + 4)},
        obs={"latency_marker_interval": 0.0, "enabled": False},
    ):
        sched = EmbeddedScheduler()
        controller = await ControllerServer(sched).start()
        app = build_app(controller,
                        db_path=os.path.join(workdir, "fleet.db"))
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        base = f"http://127.0.0.1:{port}/api/v1"

        async with ClientSession() as session:
            api = _Api(session, base, latencies)

            # -- phase 1: churn — create/finish/stop/delete bounded jobs
            churn_pids = []
            for j in range(churn):
                _, body = await api.call("post", "/pipelines", json={
                    "name": f"churn-{j}", "tenant": f"t{j % 4}",
                    "query": sample_sql(workdir, "churn", j,
                                        500 + 100 * (j % 5)),
                })
                churn_pids.append(body["id"])
                if j % 3 == 2:  # stop every third one mid-run
                    await api.call("patch", f"/pipelines/{churn_pids[-1]}",
                                   json={"stop": "immediate"})
            for j in range(previews):
                await api.call("post", "/pipelines/preview", json={
                    "name": f"pv-{j}",
                    "query": (
                        "CREATE TABLE impulse WITH (connector='impulse', "
                        "event_rate='100000', message_count='200', "
                        "start_time='0'); "
                        "SELECT counter % 3 AS k FROM impulse;"
                    ),
                    "timeout": 20,
                })

            # -- phase 2: sampled exactly-once jobs run inside the churn
            sample_pids = []
            for j in range(sample):
                _, body = await api.call("post", "/pipelines", json={
                    "name": f"sample-{j}", "tenant": "golden",
                    "query": sample_sql(workdir, "fleet", j,
                                        1000 + 200 * j),
                })
                sample_pids.append(body["id"])

            if kill:
                # SIGKILL-equivalent on one pool worker mid-churn: every
                # job with subtasks there fails and must recover
                # independently (shared-fate, per-job recovery)
                await asyncio.sleep(0.5)
                live = [w for w, _t in sched.pool
                        if not getattr(w, "_shutdown_started", False)]
                if live:
                    report["killed_worker"] = live[0].worker_id
                    await live[0].shutdown()

            # -- phase 3: ramp parked jobs to quarter scale, measure idle
            q_scale = max(jobs // 4, 1)
            parked_pids = []

            async def ramp_to(n):
                while len(parked_pids) < n:
                    j = len(parked_pids)
                    _, body = await api.call("post", "/pipelines", json={
                        "name": f"parked-{j}", "tenant": f"t{j % 4}",
                        "query": parked_sql(workdir, j),
                    })
                    parked_pids.append(body["id"])
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    running = sum(
                        1 for job in controller.jobs.values()
                        if job.state == JobState.RUNNING
                    )
                    if running >= n:
                        return running
                    await asyncio.sleep(0.25)
                return sum(1 for job in controller.jobs.values()
                           if job.state == JobState.RUNNING)

            await ramp_to(q_scale)
            idle_q = await _measure_idle(controller, q_scale,
                                         idle_seconds / 2)

            # -- phase 4: full scale
            running = await ramp_to(jobs)
            jobs_per_controller = max(
                running,
                sum(1 for job in controller.jobs.values()
                    if job.state == JobState.RUNNING),
            )
            idle_full = await _measure_idle(controller, jobs, idle_seconds)

            # -- phase 4b: fleet observatory — attribution audit + the
            # noisy-neighbor doctor scenario (ISSUE 11)
            if doctor:
                from arroyo_tpu.metrics import REGISTRY
                from arroyo_tpu.obs import attribution

                # one deliberately hot tenant: a bounded impulse burst
                # that runs flat-out on the shared pool while every
                # parked job idles — the canonical noisy neighbor
                _, body = await api.call("post", "/pipelines", json={
                    "name": "hog", "tenant": "hog",
                    "query": sample_sql(workdir, "hog", 0, doctor_events),
                })
                hog_pid = body["id"]
                deadline = time.monotonic() + 60
                hog_jid = None
                while time.monotonic() < deadline and hog_jid is None:
                    hog_jid = next(
                        (j.job_id for j in controller.jobs.values()
                         if j.tenant == "hog"), None,
                    )
                    if hog_jid is None:
                        await asyncio.sleep(0.05)
                # let the hog burn shared CPU while the fleet idles, then
                # diagnose a parked victim mid-contention (through the
                # real REST doctor route)
                await asyncio.sleep(2.0)
                victim = next(
                    (j.job_id for j in controller.jobs.values()
                     if j.tenant.startswith("t")
                     and j.state == JobState.RUNNING), None,
                )
                verdict = {}
                if victim is not None:
                    _, verdict = await api.call(
                        "get", f"/jobs/{victim}/doctor"
                    )
                v = verdict.get("verdict") or {}
                report["fleet_doctor_victim"] = victim
                report["fleet_doctor_verdict"] = v.get("cause")
                report["fleet_doctor_suspect"] = v.get("suspect")
                report["fleet_doctor_ok"] = int(
                    v.get("cause") == "noisy-neighbor"
                    and v.get("suspect") == hog_jid
                )
                # attribution audit: attributed busy summed across
                # tenants vs the pool's measured busy time (the same
                # per-subtask arroyo_worker_busy_seconds instrument the
                # autoscaler trusts) — >= 95% means no shared-worker
                # cost escapes the job dimension
                attribution.ACCOUNTING.flush()
                summary = attribution.ACCOUNTING.summary()
                worker_busy = sum(
                    v for _l, v in REGISTRY.snapshot().get(
                        "arroyo_worker_busy_seconds", [])
                )
                report["fleet_attr_coverage_pct"] = round(
                    100.0 * summary["attributed_busy_s"]
                    / max(worker_busy, 1e-9), 2,
                )
                report["fleet_attr_jobs"] = len(summary["jobs"])
                report["fleet_loop_lag_ms_p99"] = summary.get(
                    "loop_lag_ms", {}).get("p99", 0.0)
                # artifacts for the nightly lane: the doctor report and a
                # Perfetto trace (phase ledger + any spans) land in the
                # workdir so a red run ships its own diagnosis
                from arroyo_tpu import obs as _obs

                with open(os.path.join(workdir, "doctor_report.json"),
                          "w") as f:
                    json.dump(verdict, f, indent=2)
                with open(os.path.join(workdir, "fleet_trace.json"),
                          "w") as f:
                    json.dump(
                        _obs.perfetto_trace(_obs.recorder().snapshot()), f
                    )
                # stop the hog via the controller directly (non-blocking):
                # the REST stop waits for the terminal state, and a hog
                # that already ran to FINISHED would sit out that wait —
                # a 60s outlier that belongs to the scenario, not to the
                # API-latency sample the p99 gate reads
                if (hog_jid in controller.jobs
                        and not controller.jobs[hog_jid].state.is_terminal()):
                    await controller.stop_job(hog_jid, "immediate")
                report["fleet_hog_pid"] = hog_pid

            # -- phase 5: wait the sampled jobs out, then stop the fleet
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                states = [
                    controller.jobs[j.job_id].state
                    for j in controller.jobs.values()
                    if j.tenant == "golden"
                ]
                if states and all(s.is_terminal() for s in states):
                    break
                await asyncio.sleep(0.25)
            for pid in parked_pids:
                await api.call("patch", f"/pipelines/{pid}",
                               json={"stop": "immediate"})
            for pid in parked_pids[: len(parked_pids) // 2]:
                await api.call("delete", f"/pipelines/{pid}")

            admission = controller.admission.status()
        await runner.cleanup()
        await controller.stop()

    # -- solo goldens: the same sampled SQL, one job per fresh cluster
    async def solo_runs():
        with update(
            pipeline={"checkpointing": {"storage_url": ""}},
            obs={"latency_marker_interval": 0.0, "enabled": False},
        ):
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                for j in range(sample):
                    await c.submit_job(
                        f"solo-{j}",
                        sql=sample_sql(workdir, "solo", j, 1000 + 200 * j),
                        n_workers=2, parallelism=1,
                    )
                    await c.wait_for_state(
                        f"solo-{j}", JobState.FINISHED, JobState.FAILED,
                        timeout=60,
                    )
            finally:
                await c.stop()

    await solo_runs()
    mismatches = []
    for j in range(sample):
        fleet_rows = canonical_rows(os.path.join(workdir,
                                                 f"fleet-{j}.json"))
        solo_rows = canonical_rows(os.path.join(workdir, f"solo-{j}.json"))
        if not fleet_rows or fleet_rows != solo_rows:
            mismatches.append(j)

    report.update({
        "fleet_jobs_per_controller": jobs_per_controller,
        "fleet_idle_cpu_ms": round(idle_full["cpu_ms_per_job_s"], 3),
        "fleet_api_p99_ms": round(pct(latencies, 0.99), 2),
        "fleet_api_p50_ms": round(pct(latencies, 0.50), 2),
        "fleet_api_calls": len(latencies),
        "fleet_idle_cpu_flatness": round(
            idle_full["cpu_s"] / idle_full["wall_s"]
            / max(idle_q["cpu_s"] / idle_q["wall_s"], 1e-9), 2,
        ),
        "fleet_wakeups_per_job_s": round(
            idle_full["wakeups_per_job_s"], 3
        ),
        "fleet_idle_quarter_cpu_ms": round(
            idle_q["cpu_ms_per_job_s"], 3
        ),
        "fleet_exactly_once_ok": 0 if mismatches else 1,
        "fleet_sample_mismatches": mismatches,
        "fleet_admission": admission,
    })
    return report


def shared_fleet_sql(outdir: str, tag: str, j: int, events: int) -> str:
    """One fleet tenant: every tenant's SCAN is config-identical (the
    shared-plan fingerprint matches), the tail differs per tenant. The
    tail is deliberately thin (a residue filter) — the scenario measures
    what sharing amortizes, the per-row source scan."""
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '{events}', start_time = '0'
    );
    CREATE TABLE out (c BIGINT UNSIGNED) WITH (
      connector = 'single_file', path = '{outdir}/{tag}-{j}.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out SELECT counter as c FROM impulse
    WHERE counter % 997 = {j % 997};
    """


async def run_shared_fleet(jobs: int = 100, events: int = 50000,
                           pool: int = 2,
                           workdir: str | None = None) -> dict:
    """Shared-plan A/B (ISSUE 16): the SAME `jobs` tenants — identical
    source scan, per-tenant tails — run once with sharing ON (all mount
    one `__shared/<fp>` host scan) and once unshared (each job owns its
    data plane). Reports aggregate source events/s for both
    (fleet_shared_agg_eps / fleet_unshared_agg_eps — the pinned bench
    keys), requires byte-identical per-tenant output across the two
    passes, the mount to actually reach refcount == jobs, and the
    attribution apportioner to keep the >= 95% attributed-coverage gate
    over the shared fleet (the host's cost must land on tenants, not in
    a `__shared/*` escape bucket)."""
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState
    from arroyo_tpu.metrics import REGISTRY
    from arroyo_tpu.obs import attribution

    workdir = workdir or tempfile.mkdtemp(prefix="arroyo-shared-fleet-")
    os.makedirs(workdir, exist_ok=True)
    report: dict = {"jobs": jobs, "events": events, "pool": pool,
                    "workdir": workdir}

    async def one_pass(shared: bool, tag: str,
                       busy_baseline: float = 0.0) -> dict:
        out: dict = {"refcount_peak": 0}
        # big source batches: the per-tenant tail cost is per-BATCH
        # (vectorized), the scan cost is per-ROW — the fleet bench runs
        # both passes on the same batching so the A/B isolates sharing
        with update(
            sharing={"enabled": shared},
            pipeline={"checkpointing": {"storage_url": ""},
                      "source_batch_size": 8192},
            # long metrics_ttl: the attribution audit reads per-job
            # totals after ALL tenants finish; the default churn GC
            # would drop early finishers' totals mid-pass
            cluster={"worker_pool_size": pool, "metrics_ttl": 600.0},
            controller={"heartbeat_timeout": 10.0},
            worker={"task_slots": max(4, (jobs + 8) // pool + 4)},
            obs={"latency_marker_interval": 0.0, "enabled": False},
            # a 100-job burst on a small pool trivially breaches the
            # loop-lag SLO; the watchtower is not under test here
            watch={"enabled": False},
        ):
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                t0 = time.monotonic()
                for j in range(jobs):
                    await c.submit_job(
                        f"t{j}",
                        sql=shared_fleet_sql(workdir, tag, j, events),
                        n_workers=1, parallelism=1,
                    )
                pending = set(range(jobs))
                deadline = time.monotonic() + 600
                while pending and time.monotonic() < deadline:
                    if shared:
                        for st in c.sharing.status().values():
                            out["refcount_peak"] = max(
                                out["refcount_peak"], st["refcount"]
                            )
                    for j in list(pending):
                        state = c.jobs[f"t{j}"].state
                        if state == JobState.FAILED:
                            raise RuntimeError(
                                f"t{j}: {c.jobs[f't{j}'].failure}"
                            )
                        if state.is_terminal():
                            pending.discard(j)
                    await asyncio.sleep(0.05)
                if pending:
                    raise RuntimeError(
                        f"shared-fleet pass {tag}: {len(pending)} jobs "
                        "never finished"
                    )
                out["wall_s"] = time.monotonic() - t0
                if shared:
                    # audit BEFORE teardown: metrics_ttl GC drops
                    # per-job attribution totals once jobs expunge.
                    # Host cost must be apportioned onto tenants
                    # (>= 95% of measured pool busy time), and no
                    # __shared/* bucket may be left in the summary —
                    # that would mean cost escaped the apportioner.
                    summary = attribution.ACCOUNTING.summary()
                    worker_busy = sum(
                        v for _l, v in REGISTRY.snapshot().get(
                            "arroyo_worker_busy_seconds", [])
                    ) - busy_baseline
                    out["attr_coverage_pct"] = round(
                        100.0 * summary["attributed_busy_s"]
                        / max(worker_busy, 1e-9), 2,
                    )
                    out["attr_shared_bucket"] = [
                        j for j in summary["jobs"]
                        if j.startswith("__shared/")
                    ]
            finally:
                await c.stop()
        return out

    # shared pass FIRST: the coverage audit reads process-cumulative
    # busy counters, so it must run before the unshared pass adds 100
    # unattributed-scan-free jobs worth of busy time
    attribution.ACCOUNTING.reset()
    busy0 = sum(v for _l, v in REGISTRY.snapshot().get(
        "arroyo_worker_busy_seconds", []))
    shared_pass = await one_pass(True, "shr", busy_baseline=busy0)
    unshared_pass = await one_pass(False, "uns")

    mismatches = []
    for j in range(jobs):
        a = canonical_rows(os.path.join(workdir, f"shr-{j}.json"))
        b = canonical_rows(os.path.join(workdir, f"uns-{j}.json"))
        if not a or a != b:
            mismatches.append(j)

    shared_eps = jobs * events / shared_pass["wall_s"]
    unshared_eps = jobs * events / unshared_pass["wall_s"]
    report.update({
        "fleet_shared_agg_eps": round(shared_eps, 1),
        "fleet_unshared_agg_eps": round(unshared_eps, 1),
        "fleet_shared_speedup": round(shared_eps / unshared_eps, 2),
        "fleet_shared_wall_s": round(shared_pass["wall_s"], 2),
        "fleet_unshared_wall_s": round(unshared_pass["wall_s"], 2),
        "fleet_shared_refcount_peak": shared_pass["refcount_peak"],
        "fleet_shared_outputs_ok": 0 if mismatches else 1,
        "fleet_shared_mismatches": mismatches,
        "fleet_shared_attr_coverage_pct":
            shared_pass["attr_coverage_pct"],
        "fleet_shared_attr_bucket": shared_pass.get(
            "attr_shared_bucket", []),
    })
    return report


def serve_sql(outdir: str, tenant: int, keys: int, rate: int) -> str:
    """Continuous keyed windowed aggregation (deterministic replay
    pacing): every FULL 100ms window holds exactly rate/10 events, so a
    key's count is floor/ceil of rate/10/keys — any other served value
    is WRONG (torn, stale-generation, or mis-keyed), which is what the
    kill variant asserts never happens."""
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '{rate}',
      message_count = '1000000000', start_time = '0',
      realtime = 'true', replay = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{outdir}/serve-t{tenant}.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % {keys} as k,
             tumble(interval '100 millisecond') as w, count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


async def run_serve(tenants: int = 4, keys: int = 64, rate: int = 10000,
                    duration: float = 10.0, clients: int = 6,
                    bulk: int = 16, parked: int = 8, kill: bool = False,
                    pool: int = 2, pipeline_events: int = 400_000,
                    followers: int = 0, kill_follower: bool = False,
                    workdir: str | None = None) -> dict:
    """StateServe read-load scenario (ISSUE 12): thousands of lookups/s
    through the REAL REST state routes against a running multi-tenant
    fleet, measuring read p50/p99, cache hit ratio, achieved lookups/s,
    per-key value LEGALITY (full windows hold exactly rate/10 events)
    and window-end MONOTONICITY per key (published epochs never move
    backwards, so neither may served window results — a violation means
    a stale-generation or torn read). A bounded windowed-agg pipeline
    (the q5-shaped proxy) runs to completion twice — solo, then under
    full read load — pinning the zero-impact requirement as
    serve_pipeline_eps. `kill=True` SIGKILLs one pool worker mid-load:
    reads must degrade to retriable errors, never wrong values.

    `followers=N` (ISSUE 20) brings up the follower replica tier off the
    checkpoint stream: the load waits until every serving job routes
    follower-first, then measures serve_follower_lookup_eps, per-read
    staleness (published minus served epoch, hard-bounded at one
    checkpoint interval), and the worker QueryState RPC count over the
    serving jobs, which MUST stay zero — follower reads never touch
    workers. `kill_follower=True` kills follower 0 mid-load: reads must
    fail over to workers (staleness 0) and the follower must reattach."""
    from aiohttp import ClientSession, web

    from arroyo_tpu import obs
    from arroyo_tpu.api.rest import build_app
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState
    from arroyo_tpu.metrics import REGISTRY

    workdir = workdir or tempfile.mkdtemp(prefix="arroyo-serve-")
    os.makedirs(workdir, exist_ok=True)
    full = rate // 10  # events per full 100 ms window
    legal = {full // keys, -(-full // keys)}  # floor/ceil per key
    report: dict = {"tenants": tenants, "keys": keys, "rate": rate,
                    "duration": duration, "clients": clients,
                    "bulk": bulk, "kill": int(kill),
                    "followers": followers,
                    "kill_follower": int(kill_follower),
                    "workdir": workdir}

    with update(
        pipeline={"checkpointing": {"interval": 0.5,
                                    "storage_url": f"{workdir}/ck"}},
        cluster={"worker_pool_size": pool, "metrics_ttl": 1.0},
        controller={"heartbeat_timeout": 8.0},
        worker={"task_slots": max(8, (tenants + parked + 4) * 2)},
        replica={"followers": followers, "reattach_backoff": 1.0},
        obs={"latency_marker_interval": 0.0, "enabled": False},
    ):
        sched = EmbeddedScheduler()
        controller = await ControllerServer(sched).start()
        app = build_app(controller,
                        db_path=os.path.join(workdir, "serve.db"))
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        base = f"http://127.0.0.1:{port}/api/v1"

        async with ClientSession() as session:
            # -- the serving fleet: continuous tenant pipelines + parked
            for t in range(tenants):
                async with session.post(f"{base}/pipelines", json={
                    "name": f"serve-{t}", "tenant": f"serve{t}",
                    "query": serve_sql(workdir, t, keys, rate),
                }) as resp:
                    assert resp.status == 200, await resp.text()
            for j in range(parked):
                async with session.post(f"{base}/pipelines", json={
                    "name": f"parked-{j}", "tenant": f"parked{j % 4}",
                    "query": parked_sql(workdir, j),
                }) as resp:
                    assert resp.status == 200, await resp.text()
            serve_jobs: list = []
            deadline = time.monotonic() + 90
            while len(serve_jobs) < tenants:
                serve_jobs = sorted(
                    j.job_id for j in controller.jobs.values()
                    if j.tenant.startswith("serve")
                    and j.state == JobState.RUNNING
                )
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"serve fleet never came up: {len(serve_jobs)}"
                    )
                await asyncio.sleep(0.25)
            # wait until every serving job lists its table and serves a key
            tables: dict = {}
            for jid in serve_jobs:
                got = None
                deadline = time.monotonic() + 60
                while got is None:
                    async with session.get(
                        f"{base}/jobs/{jid}/state"
                    ) as resp:
                        doc = await resp.json() if resp.status == 200 else {}
                    for d in doc.get("data", []):
                        if d["kind"] == "window":
                            got = d["table"]
                    if got is None:
                        if time.monotonic() > deadline:
                            raise RuntimeError(f"{jid}: no serve table")
                        await asyncio.sleep(0.25)
                tables[jid] = got
                deadline = time.monotonic() + 60
                while True:
                    async with session.get(
                        f"{base}/jobs/{jid}/state/{got}?key=0"
                    ) as resp:
                        doc = await resp.json()
                    if resp.status == 200 and doc.get("results", [{}])[0].get(
                            "found"):
                        break
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"{jid}: key 0 never served")
                    await asyncio.sleep(0.25)

            def serve_worker_rpcs() -> float:
                """Worker QueryState RPCs issued on behalf of the serving
                jobs — with followers mounted this must not move."""
                snap = REGISTRY.snapshot().get(
                    "arroyo_serve_worker_rpcs_total", [])
                jids = set(serve_jobs)
                return sum(v for labels, v in snap
                           if dict(labels).get("job") in jids)

            if followers:
                # a follower mounts only after the job's first published
                # checkpoint is tailed; wait until EVERY serving job's
                # reads actually route follower-first before measuring
                for jid in serve_jobs:
                    deadline = time.monotonic() + 90
                    while True:
                        async with session.get(
                            f"{base}/jobs/{jid}/state/{tables[jid]}?key=0"
                        ) as resp:
                            doc = await resp.json()
                        if resp.status == 200 \
                                and doc.get("source") == "follower":
                            break
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                f"{jid}: reads never went follower-"
                                f"routed: {controller.replicas.status()}")
                        await asyncio.sleep(0.25)

            # -- solo pipeline baseline (no read load)
            async def run_bounded(tag: str) -> float:
                t0 = time.monotonic()
                async with session.post(f"{base}/pipelines", json={
                    "name": tag, "tenant": "bench",
                    "query": sample_sql(workdir, tag, 0, pipeline_events),
                }) as resp:
                    assert resp.status == 200
                jid = None
                while jid is None:
                    jid = next((j.job_id for j in controller.jobs.values()
                                if j.tenant == "bench"
                                and not j.state.is_terminal()), None)
                    await asyncio.sleep(0.05)
                deadline = time.monotonic() + 300
                while not controller.jobs[jid].state.is_terminal():
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"{tag} never finished")
                    await asyncio.sleep(0.1)
                dt = time.monotonic() - t0
                return pipeline_events / dt

            report["serve_pipeline_solo_eps"] = round(
                await run_bounded("solo"), 1)

            # -- the read load
            lat_ms: list = []
            outcomes = {"ok": 0, "miss": 0, "retriable": 0, "fatal": 0}
            fatal_sample: list = []
            wrong: list = []
            high_water: dict = {}  # (jid, key) -> window end served
            lookups = 0
            sources = {"follower": 0, "worker": 0}  # keyed lookups by leg
            staleness: list = []  # published minus served epoch, per read
            rpcs0 = serve_worker_rpcs()
            stop_load = time.monotonic() + duration
            rng_state = [12345]

            def rng(n):
                rng_state[0] = (rng_state[0] * 1103515245 + 12345) % (1 << 31)
                return rng_state[0] % n

            def check_value(jid, key, val):
                nonlocal wrong
                w = val.get("w") or {}
                cnt = next((v for f, v in val.items()
                            if f.startswith("__agg_out")
                            or f == "cnt"), None)
                end = w.get("end") if isinstance(w, dict) else None
                if cnt is not None and cnt > max(legal):
                    wrong.append({"job": jid, "key": key, "cnt": cnt,
                                  "why": f"count above full window "
                                         f"{max(legal)}"})
                if end is not None:
                    hw = high_water.get((jid, key))
                    if hw is not None and end < hw:
                        wrong.append({"job": jid, "key": key,
                                      "end": end, "prev": hw,
                                      "why": "window end went backwards "
                                             "(stale read)"})
                    else:
                        high_water[(jid, key)] = end

            async def reader(ci: int):
                nonlocal lookups
                while time.monotonic() < stop_load:
                    jid = serve_jobs[rng(len(serve_jobs))]
                    table = tables[jid]
                    t0 = time.perf_counter()
                    try:
                        if ci % 3 == 0:  # point GET
                            k = rng(keys)
                            async with session.get(
                                f"{base}/jobs/{jid}/state/{table}"
                                f"?key={k}"
                            ) as resp:
                                doc = await resp.json()
                                status = resp.status
                            n = 1
                        else:  # bulk POST
                            ks = [rng(keys) for _ in range(bulk)]
                            async with session.post(
                                f"{base}/jobs/{jid}/state/{table}",
                                json={"keys": ks},
                            ) as resp:
                                doc = await resp.json()
                                status = resp.status
                            n = len(ks)
                    except Exception:  # noqa: BLE001 - conn reset midkill
                        outcomes["retriable"] += 1
                        continue
                    lat_ms.append((time.perf_counter() - t0) * 1e3)
                    lookups += n
                    if status != 200:
                        if doc.get("retriable"):
                            outcomes["retriable"] += 1
                        else:
                            outcomes["fatal"] += 1
                            if len(fatal_sample) < 5:
                                fatal_sample.append(doc)
                        continue
                    src = doc.get("source")
                    if src in sources:
                        sources[src] += n
                    stal = doc.get("staleness")
                    if isinstance(stal, int):
                        staleness.append(stal)
                    for r in doc.get("results", []):
                        if r.get("found"):
                            outcomes["ok"] += 1
                            check_value(jid, r.get("key"), r.get("value")
                                        or {})
                        elif r.get("error"):
                            if r.get("retriable", True):
                                outcomes["retriable"] += 1
                            else:
                                outcomes["fatal"] += 1
                                if len(fatal_sample) < 5:
                                    fatal_sample.append(r)
                        else:
                            outcomes["miss"] += 1

            async def killer():
                if kill_follower:
                    await asyncio.sleep(duration / 3)
                    controller.replicas.kill(0)
                    report["serve_killed_follower"] = 0
                if not kill:
                    return
                await asyncio.sleep(duration / 3)
                live = [w for w, _t in sched.pool
                        if not getattr(w, "_shutdown_started", False)]
                if live:
                    report["serve_killed_worker"] = live[0].worker_id
                    await live[0].shutdown()

            load_t0 = time.monotonic()
            bounded_task = asyncio.ensure_future(run_bounded("loaded"))
            await asyncio.gather(killer(),
                                 *(reader(i) for i in range(clients)))
            load_wall = time.monotonic() - load_t0
            try:
                loaded_eps = await bounded_task
            except Exception as e:  # noqa: BLE001
                # the kill variant can take the bounded job's worker too
                loaded_eps = 0.0 if kill else (_ for _ in ()).throw(e)

            hits = sum(v for _l, v in REGISTRY.snapshot().get(
                "arroyo_serve_cache_hits_total", []))
            misses = sum(v for _l, v in REGISTRY.snapshot().get(
                "arroyo_serve_cache_misses_total", []))
            async with session.get(f"{base}/jobs/{serve_jobs[0]}/state") \
                    as resp:
                final_doc = await resp.json()
            report.update({
                "serve_lookup_eps": round(lookups / load_wall, 1),
                "serve_read_p50_ms": round(pct(lat_ms, 0.50), 3),
                "serve_read_p99_ms": round(pct(lat_ms, 0.99), 3),
                "serve_reads": len(lat_ms),
                "serve_lookups": lookups,
                "serve_cache_hit_pct": round(
                    100.0 * hits / max(hits + misses, 1), 2),
                "serve_outcomes": outcomes,
                "serve_fatal_sample": fatal_sample,
                "serve_wrong_values": len(wrong),
                "serve_wrong_sample": wrong[:5],
                "serve_pipeline_eps": round(loaded_eps, 1),
                "serve_published_epoch": final_doc.get("publishedEpoch"),
                "serve_gateway": controller.serve.status(),
            })
            if report.get("serve_pipeline_solo_eps"):
                report["serve_pipeline_impact_pct"] = round(
                    100.0 * (1 - loaded_eps
                             / report["serve_pipeline_solo_eps"]), 1)

            if followers:
                report.update({
                    "serve_follower_lookup_eps": round(
                        sources["follower"] / load_wall, 1),
                    "serve_follower_reads": sources["follower"],
                    "serve_worker_reads": sources["worker"],
                    "serve_staleness_p50": round(
                        pct(staleness, 0.50), 2),
                    "serve_staleness_p99": round(
                        pct(staleness, 0.99), 2),
                    "serve_staleness_max": max(staleness, default=0),
                    "serve_follower_worker_rpcs":
                        serve_worker_rpcs() - rpcs0,
                    "serve_replica": controller.replicas.status(),
                })
                if kill_follower:
                    # the killed follower must reattach (the controller
                    # re-resolves latest.json on its next cadence wake)
                    reattached = 0
                    deadline = time.monotonic() + 30
                    while time.monotonic() < deadline and not reattached:
                        async with session.get(
                            f"{base}/jobs/{serve_jobs[0]}/state/"
                            f"{tables[serve_jobs[0]]}?key=0"
                        ) as resp:
                            doc = await resp.json()
                        if resp.status == 200 \
                                and doc.get("source") == "follower":
                            reattached = 1
                        else:
                            await asyncio.sleep(0.5)
                    report["serve_follower_reattached"] = reattached

            # artifacts: the serve report's Perfetto trace (the serve
            # phase ledger rides the timeline) + slowest-read pointer —
            # the CI lane uploads both when the gate goes red
            with open(os.path.join(workdir, "serve_trace.json"),
                      "w") as f:
                json.dump(obs.perfetto_trace(obs.recorder().snapshot()),
                          f)
            with open(os.path.join(workdir, "serve_slow_read.json"),
                      "w") as f:
                json.dump({"slowest_read":
                           controller.serve.status()["slowest_read"],
                           "p99_ms": report["serve_read_p99_ms"]}, f,
                          indent=2)

            for j in list(controller.jobs.values()):
                if not j.state.is_terminal():
                    await controller.stop_job(j.job_id, "immediate")
        await runner.cleanup()
        await controller.stop()
    return report


def watch_sql(outdir: str, tag: str, rate: int, keys: int) -> str:
    """Continuous keyed windowed aggregation with WALL-CLOCK event time
    (plain realtime, no replay): the watermark tracks the wall clock, so
    the freshness SLO's watermark-lag signal sits near zero while the
    tenant is healthy and grows unboundedly the moment its pipeline
    stalls — exactly the signal the drill injects a stall into."""
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '{rate}',
      message_count = '1000000000', realtime = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{outdir}/watch-{tag}.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % {keys} as k,
             tumble(interval '100 millisecond') as w, count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


async def run_watch(healthy: int = 10, rate: int = 2000, keys: int = 32,
                    pool: int = 2, stall_hold: float = 2.0,
                    fire_timeout: float = 45.0,
                    clear_timeout: float = 60.0,
                    workdir: str | None = None) -> dict:
    """Watchtower SLO drill (ISSUE 13): one victim tenant + `healthy`
    co-tenants run continuous keyed pipelines on a shared pool; a stall
    is injected into the VICTIM ONLY (chaos `storage.latency` matched on
    the victim's checkpoint keys — its flushes back up, barriers block
    the runner, the source stalls and watermark lag grows — plus a
    sub-timeout `worker.heartbeat_blackout` liveness wobble on the
    shared pool that must NOT page anyone). The watchtower must fire
    the freshness alert naming exactly the victim, capture a diagnostic
    bundle whose flight recording covers the breach window, and CLEAR
    after chaos lifts — with ZERO firing events on the healthy
    co-tenants (`watch_false_positive_count == 0` gates the run)."""
    from aiohttp import ClientSession, web

    from arroyo_tpu import chaos
    from arroyo_tpu.api.rest import build_app
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    workdir = workdir or tempfile.mkdtemp(prefix="arroyo-watch-")
    os.makedirs(workdir, exist_ok=True)
    bundles_dir = os.path.join(workdir, "bundles")
    report: dict = {"healthy": healthy, "rate": rate, "keys": keys,
                    "pool": pool, "workdir": workdir}

    with update(
        pipeline={"checkpointing": {"interval": 0.5,
                                    "storage_url": f"{workdir}/ck"}},
        cluster={"worker_pool_size": pool, "metrics_ttl": 1.0},
        controller={"heartbeat_timeout": 8.0},
        worker={"task_slots": max(8, (healthy + 4) * 2)},
        # fast cadence + tight thresholds so the drill runs in tens of
        # seconds; loop_lag is raised far above the 1-core CI host's
        # ambient scheduling jitter — loop pressure there is the host,
        # not a tenant signal
        watch={"sample_interval": 0.25, "eval_interval": 0.25,
               "window": 10.0, "sustain": 1.0, "clear_sustain": 1.5,
               "freshness_lag_s": 3.0, "checkpoint_age_s": 8.0,
               "loop_lag_s": 30.0, "trace_drop_rate": 1e9,
               "spool_dir": bundles_dir},
        obs={"latency_marker_interval": 0.0},
    ):
        sched = EmbeddedScheduler()
        controller = await ControllerServer(sched).start()
        wt = controller.watchtower
        app = build_app(controller,
                        db_path=os.path.join(workdir, "watch.db"))
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        base = f"http://127.0.0.1:{port}/api/v1"

        async with ClientSession() as session:
            async def submit(name: str, tenant: str, tag: str):
                async with session.post(f"{base}/pipelines", json={
                    "name": name, "tenant": tenant,
                    "query": watch_sql(workdir, tag, rate, keys),
                }) as resp:
                    assert resp.status == 200, await resp.text()

            await submit("victim", "victim", "victim")
            for t in range(healthy):
                await submit(f"healthy-{t}", f"t{t}", f"h{t}")

            # wait for the fleet to run AND every job's watermark-lag
            # series to appear in the history (the freshness signal
            # abstains until a watermark flows)
            deadline = time.monotonic() + 120
            victim_jid = None
            while time.monotonic() < deadline:
                running = [j for j in controller.jobs.values()
                           if j.state == JobState.RUNNING]
                victim_jid = next((j.job_id for j in running
                                   if j.tenant == "victim"), None)
                lags = {
                    j.job_id: wt.history.get(
                        "arroyo_worker_watermark_lag_seconds",
                        job=j.job_id)
                    for j in running
                }
                if (len(running) == healthy + 1 and victim_jid
                        and all(lags.values())):
                    break
                await asyncio.sleep(0.25)
            else:
                raise RuntimeError(
                    f"watch fleet never became observable: "
                    f"{len([j for j in controller.jobs.values()])} jobs"
                )
            report["watch_victim"] = victim_jid
            await asyncio.sleep(2.0)  # clean baseline window

            # -- inject the stall: storage latency on the VICTIM's
            # checkpoint keys only (keys are '{job_id}/...'-prefixed),
            # plus one short heartbeat blackout (< heartbeat_timeout) on
            # the shared pool — a liveness wobble, not an outage
            # three faults, one tenant:
            # * runner.stall matched on the victim's job id wedges its
            #   operators (async sleep per input item — co-residents
            #   keep their turns on the shared loop): the watermark
            #   falls behind the wall clock and the freshness SLO sees
            #   a REAL data-plane stall. (Storage latency alone cannot
            #   produce one: the controller backpressures the
            #   checkpoint CADENCE, never the data plane.)
            # * storage.latency on the victim's checkpoint DATA files
            #   only ('{jid}/checkpoints' + op=put — those run in
            #   to_thread flushes; the controller's sync manifest ops
            #   on the shared loop stay fast) stalls epoch publication
            #   for the checkpoint-age SLO.
            # * one sub-timeout heartbeat blackout on the shared pool —
            #   a liveness wobble that must NOT page anyone.
            plan = chaos.FaultPlan(seed=1313)
            plan.add("runner.stall", at_hits=list(range(1, 100000)),
                     match={"job": victim_jid}, params={"delay": 0.5},
                     max_fires=100000)
            plan.add("storage.latency",
                     at_hits=list(range(1, 400)),
                     match={"key": f"{victim_jid}/checkpoints",
                            "op": "put"},
                     params={"delay": 6.0}, max_fires=400)
            plan.add("worker.heartbeat_blackout", at_hits=(2,),
                     params={"duration": 2.0}, max_fires=1)
            chaos.install(plan)
            stall_t0 = time.monotonic()
            stall_wall_us = time.time() * 1e6
            report["watch_stall_injected"] = True

            fired_at = None
            deadline = time.monotonic() + fire_timeout
            while time.monotonic() < deadline:
                async with session.get(
                        f"{base}/jobs/{victim_jid}/alerts") as resp:
                    doc = await resp.json()
                if "freshness" in doc.get("firing", []):
                    fired_at = time.monotonic()
                    break
                await asyncio.sleep(0.25)
            report["watch_fired"] = int(fired_at is not None)
            report["watch_fire_s"] = round(
                (fired_at - stall_t0), 2) if fired_at else None
            report["watch_victim_rules"] = (doc or {}).get("firing", [])
            if fired_at:
                await asyncio.sleep(stall_hold)

            # -- lift the fault; the victim's flushes drain, the source
            # resumes wall-clock stamping and lag collapses
            chaos.clear()
            fired_log = plan.comparable_log()
            report["watch_faults_fired"] = len(fired_log)

            cleared = False
            deadline = time.monotonic() + clear_timeout
            while time.monotonic() < deadline:
                async with session.get(
                        f"{base}/jobs/{victim_jid}/alerts") as resp:
                    doc = await resp.json()
                st = (doc.get("alerts") or {}).get("freshness", {})
                if fired_at and st.get("state") == "ok":
                    cleared = True
                    break
                await asyncio.sleep(0.25)
            report["watch_cleared_ok"] = int(cleared)

            # -- bundle: present for the victim, flight recording covers
            # the breach window, history shows the lag above threshold
            async with session.get(
                    f"{base}/jobs/{victim_jid}/bundles") as resp:
                idx = (await resp.json()).get("data", [])
            report["watch_bundle_count"] = len(idx)
            bundle_ok = 0
            if idx:
                # the throughput rule may fire first on the same backlog
                # — judge the FRESHNESS bundle
                meta = next((m for m in idx
                             if m["rule"] == "freshness"), idx[0])
                async with session.get(
                        f"{base}/jobs/{victim_jid}/bundles/"
                        f"{meta['n']}") as resp:
                    bundle = await resp.json()
                spans = bundle.get("flight_recorder", [])
                in_window = [
                    s for s in spans
                    if stall_wall_us <= s.get("ts", 0)
                    <= bundle.get("captured_at", 0) * 1e6
                ]
                lag_series = [
                    s for s in bundle.get("history", [])
                    if s["name"] == "arroyo_worker_watermark_lag_seconds"
                ]
                lag_max = max(
                    (s.get("max", 0.0) or 0.0 for s in lag_series),
                    default=0.0,
                )
                bundle_ok = int(
                    bool(in_window)
                    and bool(bundle.get("perfetto", {}).get(
                        "traceEvents"))
                    and bundle.get("doctor") is not None
                    and lag_max >= 3.0
                )
                report["watch_bundle_spans_in_window"] = len(in_window)
                report["watch_bundle_lag_max_s"] = round(lag_max, 2)
                report["watch_bundle_file"] = idx[0].get("path")
            report["watch_bundle_ok"] = bundle_ok

            # -- zero false positives: no firing event may name a
            # healthy co-tenant, across the whole run
            false_pos = [
                {k: v for k, v in e.items() if k != "cause"}
                for e in wt.ledger
                if e["event"] == "firing" and e["job"] != victim_jid
            ]
            report["watch_false_positive_count"] = len(false_pos)
            report["watch_false_positives"] = false_pos[:10]
            report["watch_ledger"] = [
                {k: v for k, v in e.items() if k != "cause"}
                for e in wt.ledger
            ]
            report["watch_healthy_observed"] = healthy

            for j in list(controller.jobs.values()):
                if not j.state.is_terminal():
                    await controller.stop_job(j.job_id, "immediate")
        await runner.cleanup()
        await controller.stop()
        chaos.clear()
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", type=int, default=100,
                    help="parked pipelines at full scale")
    ap.add_argument("--pool", type=int, default=2,
                    help="shared worker pool size")
    ap.add_argument("--sample", type=int, default=8,
                    help="bounded exactly-once sample jobs")
    ap.add_argument("--churn", type=int, default=30,
                    help="create/stop churn pipelines")
    ap.add_argument("--previews", type=int, default=5)
    ap.add_argument("--idle-seconds", type=float, default=10.0)
    ap.add_argument("--kill", action="store_true",
                    help="SIGKILL one pool worker mid-churn")
    ap.add_argument("--no-doctor", action="store_true",
                    help="skip the attribution audit + noisy-neighbor "
                         "doctor scenario")
    ap.add_argument("--doctor-events", type=int, default=1_500_000,
                    help="event count of the deliberately hot hog tenant")
    ap.add_argument("--workdir")
    ap.add_argument("--out", help="write the report JSON here")
    # StateServe read-load scenario (ISSUE 12)
    ap.add_argument("--serve", action="store_true",
                    help="run the queryable-state read-load scenario "
                         "instead of the churn harness")
    ap.add_argument("--serve-kill", action="store_true",
                    help="serve scenario chaos variant: SIGKILL a pool "
                         "worker mid-load (reads must degrade to "
                         "retriable errors, never wrong values)")
    ap.add_argument("--serve-duration", type=float, default=10.0)
    ap.add_argument("--serve-clients", type=int, default=6)
    ap.add_argument("--serve-tenants", type=int, default=4)
    ap.add_argument("--serve-keys", type=int, default=64)
    ap.add_argument("--serve-rate", type=int, default=10000)
    ap.add_argument("--serve-bulk", type=int, default=16)
    ap.add_argument("--serve-parked", type=int, default=8)
    ap.add_argument("--serve-pipeline-events", type=int, default=400_000)
    ap.add_argument("--min-lookups", type=float, default=2000.0,
                    help="fail the (non-kill) serve scenario below this "
                         "sustained lookups/s")
    # Follower read replicas (ISSUE 20)
    ap.add_argument("--followers", type=int, default=0,
                    help="serve scenario: follower replicas tailing the "
                         "checkpoint stream; reads must route follower-"
                         "first with ZERO worker QueryState RPCs and "
                         "staleness bounded at one checkpoint interval")
    ap.add_argument("--serve-kill-follower", action="store_true",
                    help="serve scenario chaos variant: kill follower 0 "
                         "mid-load — reads must fail over worker-ward "
                         "and the follower must reattach")
    # Watchtower SLO drill (ISSUE 13)
    ap.add_argument("--watch", action="store_true",
                    help="run the watchtower SLO drill: stall one "
                         "tenant, require the freshness alert to fire "
                         "with the right job, bundle, and clear — zero "
                         "false positives on healthy co-tenants")
    ap.add_argument("--watch-healthy", type=int, default=10,
                    help="healthy co-tenants beside the victim")
    ap.add_argument("--watch-rate", type=int, default=2000)
    ap.add_argument("--watch-keys", type=int, default=32)
    # Shared-plan fleet A/B (ISSUE 16)
    ap.add_argument("--shared-fleet", action="store_true",
                    help="run the shared-plan A/B: the same tenants "
                         "once mounted on one shared source scan, once "
                         "unshared; gates >5x aggregate eps, identical "
                         "outputs, full mount engagement, and the 95%% "
                         "attribution coverage over the shared fleet")
    ap.add_argument("--shared-events", type=int, default=50000,
                    help="source events per tenant in the A/B")
    args = ap.parse_args(argv)
    if args.shared_fleet:
        report = asyncio.run(run_shared_fleet(
            jobs=args.jobs, events=args.shared_events,
            pool=args.pool, workdir=args.workdir,
        ))
        report["pin_era"] = PIN_ERA
        print(json.dumps(report))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=2)
        rc = 0
        if not report["fleet_shared_outputs_ok"]:
            print(f"SHARED FLEET: per-tenant outputs diverged between "
                  f"shared and unshared passes: "
                  f"{report['fleet_shared_mismatches'][:10]}",
                  file=sys.stderr)
            rc = 1
        if report["fleet_shared_refcount_peak"] < args.jobs:
            print(f"SHARED FLEET: sharing never fully engaged — "
                  f"refcount peak {report['fleet_shared_refcount_peak']}"
                  f" < {args.jobs} tenants", file=sys.stderr)
            rc = 1
        if report["fleet_shared_speedup"] <= 5.0:
            print(f"SHARED FLEET: aggregate speedup "
                  f"{report['fleet_shared_speedup']}x is not > 5x",
                  file=sys.stderr)
            rc = 1
        if report["fleet_shared_attr_coverage_pct"] < 95.0:
            print(f"SHARED FLEET: attribution coverage "
                  f"{report['fleet_shared_attr_coverage_pct']}% < 95%",
                  file=sys.stderr)
            rc = 1
        if report["fleet_shared_attr_bucket"]:
            print(f"SHARED FLEET: host cost escaped apportioning into "
                  f"{report['fleet_shared_attr_bucket']}",
                  file=sys.stderr)
            rc = 1
        return rc
    if args.watch:
        report = asyncio.run(run_watch(
            healthy=args.watch_healthy, rate=args.watch_rate,
            keys=args.watch_keys, pool=args.pool,
            workdir=args.workdir,
        ))
        print(json.dumps(report))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=2)
        rc = 0
        if not report.get("watch_fired"):
            print("WATCH DRILL: freshness alert never fired for the "
                  "stalled victim", file=sys.stderr)
            rc = 1
        if report.get("watch_false_positive_count"):
            print(f"WATCH DRILL: false positives on healthy tenants: "
                  f"{report['watch_false_positives']}", file=sys.stderr)
            rc = 1
        if not report.get("watch_bundle_ok"):
            print("WATCH DRILL: diagnostic bundle missing or does not "
                  "cover the breach window", file=sys.stderr)
            rc = 1
        if not report.get("watch_cleared_ok"):
            print("WATCH DRILL: alert never cleared after recovery",
                  file=sys.stderr)
            rc = 1
        return rc
    if args.serve_kill_follower and not args.followers:
        args.followers = 1
    if args.serve or args.serve_kill or args.serve_kill_follower:
        report = asyncio.run(run_serve(
            tenants=args.serve_tenants, keys=args.serve_keys,
            rate=args.serve_rate, duration=args.serve_duration,
            clients=args.serve_clients, bulk=args.serve_bulk,
            parked=args.serve_parked, kill=args.serve_kill,
            pool=args.pool, pipeline_events=args.serve_pipeline_events,
            followers=args.followers,
            kill_follower=args.serve_kill_follower,
            workdir=args.workdir,
        ))
        report["pin_era"] = PIN_ERA
        print(json.dumps(report))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=2)
        rc = 0
        if report["serve_wrong_values"]:
            print(f"WRONG VALUES SERVED: "
                  f"{report['serve_wrong_sample']}", file=sys.stderr)
            rc = 1
        if report["serve_outcomes"]["fatal"]:
            print(f"NON-RETRIABLE READ ERRORS: "
                  f"{report['serve_outcomes']}", file=sys.stderr)
            rc = 1
        if (not args.serve_kill
                and report["serve_lookup_eps"] < args.min_lookups):
            print(f"READ THROUGHPUT BELOW TARGET: "
                  f"{report['serve_lookup_eps']} < {args.min_lookups} "
                  "lookups/s", file=sys.stderr)
            rc = 1
        if args.serve_kill and not report["serve_outcomes"]["retriable"]:
            print("KILL VARIANT SAW NO RETRIABLE DEGRADATION — the "
                  "kill did not land mid-load", file=sys.stderr)
            rc = 1
        if args.followers:
            if report["serve_staleness_max"] > 1:
                print(f"STALENESS ABOVE ONE CHECKPOINT INTERVAL: max "
                      f"{report['serve_staleness_max']} epochs",
                      file=sys.stderr)
                rc = 1
            if (not args.serve_kill and not args.serve_kill_follower
                    and (report["serve_follower_worker_rpcs"]
                         or report["serve_worker_reads"])):
                print(f"FOLLOWER READS TOUCHED WORKERS: "
                      f"{report['serve_follower_worker_rpcs']} QueryState"
                      f" RPCs, {report['serve_worker_reads']} worker-"
                      f"sourced lookups (must both be 0)",
                      file=sys.stderr)
                rc = 1
        if args.serve_kill_follower:
            if not report.get("serve_follower_reattached"):
                print("KILLED FOLLOWER NEVER REATTACHED", file=sys.stderr)
                rc = 1
            if not report.get("serve_worker_reads"):
                print("FOLLOWER KILL DID NOT LAND — no worker-ward "
                      "fallback reads observed mid-load", file=sys.stderr)
                rc = 1
        return rc
    report = asyncio.run(run_fleet(
        jobs=args.jobs, pool=args.pool, sample=args.sample,
        churn=args.churn, previews=args.previews,
        idle_seconds=args.idle_seconds, kill=args.kill,
        doctor=not args.no_doctor, doctor_events=args.doctor_events,
        workdir=args.workdir,
    ))
    report["pin_era"] = PIN_ERA
    print(json.dumps(report))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    rc = 0
    if not report["fleet_exactly_once_ok"]:
        print(f"EXACTLY-ONCE MISMATCH: jobs "
              f"{report['fleet_sample_mismatches']}", file=sys.stderr)
        rc = 1
    if not args.no_doctor:
        if report.get("fleet_attr_coverage_pct", 0) < 95.0:
            print(f"ATTRIBUTION GAP: attributed busy covers only "
                  f"{report.get('fleet_attr_coverage_pct')}% of measured "
                  "worker busy time", file=sys.stderr)
            rc = 1
        if not report.get("fleet_doctor_ok"):
            print(f"DOCTOR MISS: verdict="
                  f"{report.get('fleet_doctor_verdict')} suspect="
                  f"{report.get('fleet_doctor_suspect')} (expected "
                  "noisy-neighbor naming the hog job)", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
