"""Hot-standby failover (ISSUE 17).

Fast-tier proofs of the failover seams: the task-local chain cache is a
byte-capped LRU whose invalidation tracks the tailed manifest's chain
floor; the watchtower suppresses NEW freshness/e2e pages inside the
`failover.grace` window without silencing alerts that were already
firing; the bench gate refuses cross-era comparisons (`pin_era`); and
the E2E path — a SIGKILLed primary with an armed standby promotes with
ZERO cold restarts and byte-identical output (the `failover.promote`
span carries the measured gap), the standby tails within one epoch of
the primary, same-process restores hit the chain cache instead of
storage, and an alive-but-silent (heartbeat-blackout) zombie primary is
fenced before the standby's sink truncation so it cannot double-emit.
"""

import asyncio
import os
import sys
import time

import pytest

from arroyo_tpu.config import update
from arroyo_tpu.metrics import REGISTRY
from arroyo_tpu.state.chain_cache import ChainCache

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")


# -- task-local chain cache --------------------------------------------------


def _cache(cap=1 << 20):
    return update(failover={"local_chain_cache": True,
                            "cache_max_bytes": cap})


def test_chain_cache_hit_miss_and_stats():
    with _cache():
        c = ChainCache()
        c.put("mem://a", "jobx/checkpoints/checkpoint-1/chain-0", b"abc")
        assert c.get("mem://a",
                     "jobx/checkpoints/checkpoint-1/chain-0") == b"abc"
        # different storage url is a different key
        assert c.get("mem://b",
                     "jobx/checkpoints/checkpoint-1/chain-0") is None
        st = c.stats()
        assert st["entries"] == 1 and st["bytes"] == 3
        assert st["hits"] == 1 and st["misses"] == 1
    REGISTRY.drop_job("jobx")


def test_chain_cache_lru_evicts_by_bytes():
    with _cache(cap=10):
        c = ChainCache()
        c.put("u", "jobx/checkpoints/checkpoint-1/a", b"aaaa")
        c.put("u", "jobx/checkpoints/checkpoint-1/b", b"bbbb")
        # touch `a` so `b` is the LRU victim
        assert c.get("u", "jobx/checkpoints/checkpoint-1/a") == b"aaaa"
        c.put("u", "jobx/checkpoints/checkpoint-2/c", b"cccc")
        assert c.get("u", "jobx/checkpoints/checkpoint-1/b") is None
        assert c.get("u", "jobx/checkpoints/checkpoint-1/a") == b"aaaa"
        assert c.get("u", "jobx/checkpoints/checkpoint-2/c") == b"cccc"
        assert c.stats()["bytes"] <= 10
        # a blob above the cap is never admitted (it would evict all)
        c.put("u", "jobx/checkpoints/checkpoint-3/huge", b"x" * 11)
        assert c.get("u", "jobx/checkpoints/checkpoint-3/huge") is None
    REGISTRY.drop_job("jobx")


def test_chain_cache_invalidate_scopes_job_and_epoch():
    with _cache():
        c = ChainCache()
        c.put("u", "j1/checkpoints/checkpoint-1/a", b"1")
        c.put("u", "j1/checkpoints/checkpoint-3/b", b"3")
        c.put("u", "j2/checkpoints/checkpoint-1/c", b"1")
        # the chain floor moved to epoch 3: epochs below it drop, the
        # OTHER job's entries are untouched
        c.invalidate_below("j1", 3)
        assert c.get("u", "j1/checkpoints/checkpoint-1/a") is None
        assert c.get("u", "j1/checkpoints/checkpoint-3/b") == b"3"
        assert c.get("u", "j2/checkpoints/checkpoint-1/c") == b"1"
        c.invalidate_job("j2")
        assert c.get("u", "j2/checkpoints/checkpoint-1/c") is None
        assert c.stats()["entries"] == 1
    REGISTRY.drop_job("j1")
    REGISTRY.drop_job("j2")


def test_chain_cache_gate_off_is_a_noop():
    with update(failover={"local_chain_cache": False}):
        c = ChainCache()
        c.put("u", "jobx/checkpoints/checkpoint-1/a", b"abc")
        assert c.get("u", "jobx/checkpoints/checkpoint-1/a") is None
        assert c.stats()["entries"] == 0
        # gated gets do not mint miss metrics either
        assert c.stats()["misses"] == 0


# -- watchtower: failover.grace suppression ----------------------------------


class _FakeFailover:
    def __init__(self):
        self.grace_jobs = set()

    def in_grace(self, jid):
        return jid in self.grace_jobs


class _FakeCtrl:
    def __init__(self):
        self.failover = _FakeFailover()


class _Job:
    def __init__(self, job_id, tenant="t0"):
        self.job_id = job_id
        self.tenant = tenant
        self.backend = object()
        self.graph = None


_LAG = "arroyo_worker_watermark_lag_seconds"


def _evaluate_seq(wt, job, values, t0=100.0, dt=1.0):
    for i, v in enumerate(values):
        now = t0 + i * dt
        wt.history.ingest(
            {_LAG: [({"job": job.job_id, "task": "2-0"}, v)]}, now=now)
        wt.evaluate(now=now, jobs=[(job.job_id, job.tenant, job)])


def _grace_tower(tmp_path):
    from arroyo_tpu.obs.history import MetricHistory
    from arroyo_tpu.obs.watchtower import Watchtower

    ctrl = _FakeCtrl()
    wt = Watchtower(controller=ctrl,
                    history=MetricHistory(retain=(_LAG,)))
    return wt, ctrl


def test_failover_grace_suppresses_new_freshness_pages(tmp_path):
    with update(watch={"freshness_lag_s": 3.0, "sustain": 2.0,
                       "clear_sustain": 2.0, "clear_ratio": 0.5,
                       "spool_dir": str(tmp_path / "spool")}):
        wt, ctrl = _grace_tower(tmp_path)
        job = _Job("gsup")
        ctrl.failover.grace_jobs.add("gsup")
        # a catch-up lag blip inside the grace window: breach time must
        # not accrue and nothing fires
        _evaluate_seq(wt, job, [0.1, 5.0, 6.0, 7.0, 8.0])
        st = wt.alerts.get(("gsup", "freshness"))
        assert st is None or st.state == "ok"
        assert not [e for e in wt.ledger if e["event"] == "firing"]
        # grace over, lag still bad: the rule pages as usual
        ctrl.failover.grace_jobs.clear()
        _evaluate_seq(wt, job, [9.0, 9.0, 9.0, 9.0], t0=200.0)
        assert wt.alerts[("gsup", "freshness")].state == "firing"
    REGISTRY.drop_job("gsup")


def test_failover_grace_keeps_preexisting_firing_alert(tmp_path):
    with update(watch={"freshness_lag_s": 3.0, "sustain": 2.0,
                       "clear_sustain": 2.0, "clear_ratio": 0.5,
                       "spool_dir": str(tmp_path / "spool")}):
        wt, ctrl = _grace_tower(tmp_path)
        job = _Job("gfire")
        _evaluate_seq(wt, job, [0.1, 5.0, 6.0, 7.0, 8.0])
        assert wt.alerts[("gfire", "freshness")].state == "firing"
        # a promotion mid-incident must not silence the page: the
        # failover did not fix the lag
        ctrl.failover.grace_jobs.add("gfire")
        _evaluate_seq(wt, job, [9.0, 9.0], t0=200.0)
        assert wt.alerts[("gfire", "freshness")].state == "firing"
    REGISTRY.drop_job("gfire")


def test_failover_grace_only_covers_catchup_rules(tmp_path):
    """Rules OUTSIDE the grace set (e.g. checkpoint age) page normally
    even while the job is in grace — grace is scoped to the catch-up
    blip, not a blanket mute."""
    from arroyo_tpu.obs.watchtower import Watchtower

    assert set(Watchtower._FAILOVER_GRACE_RULES) == {
        "freshness", "e2e_p99", "replica_staleness"}


# -- bench gate: pin_era -----------------------------------------------------


def _bench_compare():
    sys.path.insert(0, TOOLS)
    try:
        import bench_compare
    finally:
        sys.path.remove(TOOLS)
    return bench_compare


def test_pin_era_gate():
    bc = _bench_compare()
    # matching eras (or a shared absence, pre-era baselines) compare
    assert bc.check_pin_era({"pin_era": "r2"}, {"pin_era": "r2"}) is None
    assert bc.check_pin_era({}, {}) is None
    # any disagreement — including one side missing — refuses loudly
    assert bc.check_pin_era({"pin_era": "r1"},
                            {"pin_era": "r2"}) is not None
    assert bc.check_pin_era({}, {"pin_era": "r2"}) is not None
    assert bc.check_pin_era({"pin_era": "r1"}, {}) is not None


def test_bench_payload_is_era_stamped():
    import bench as bench_mod

    assert isinstance(bench_mod.PIN_ERA, str) and bench_mod.PIN_ERA
    import json

    with open(os.path.join(os.path.dirname(TOOLS),
                           "BENCH_BASELINE.json")) as f:
        baseline = json.load(f)
    assert baseline.get("pin_era") == bench_mod.PIN_ERA


# -- E2E: arm, tail, promote -------------------------------------------------


def _pipeline_sql(out, n=4000, rate=1500):
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '{rate}',
      message_count = '{n}', start_time = '0',
      realtime = 'true', replay = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, start TIMESTAMP, cnt BIGINT) WITH (
      connector = 'single_file', path = '{out}',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, window.start as start, cnt FROM (
      SELECT counter % 4 as k, tumble(interval '500 millisecond') as window,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


def _canonical(path):
    with open(path) as f:
        return sorted(line for line in f.read().splitlines() if line)


async def _wait_armed(c, jid, min_epoch=0, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sb = c.failover._standbys.get(jid)
        if sb is not None and sb.epoch >= min_epoch:
            return sb
        await asyncio.sleep(0.05)
    raise AssertionError(f"standby for {jid} never armed/tailed "
                         f"to epoch {min_epoch}")


async def _run_job(tmp_path, tag, failover_on, fault=None,
                   heartbeat_timeout=0.5, checkpoint_interval=0.25):
    """One embedded run; `fault` (if set) is an async callable invoked
    once the job is RUNNING that installs the chaos plan."""
    from arroyo_tpu import chaos, obs
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    out = str(tmp_path / f"{tag}.json")
    with update(
        failover={"enabled": failover_on},
        worker={"heartbeat_interval": 0.05},
        controller={"heartbeat_timeout": heartbeat_timeout},
        pipeline={"checkpointing": {"interval": checkpoint_interval}},
    ):
        obs.reset()
        c = await ControllerServer(EmbeddedScheduler(),
                                   max_restarts=4).start()
        try:
            await c.submit_job(tag, sql=_pipeline_sql(out),
                               storage_url=str(tmp_path / f"{tag}-ck"),
                               n_workers=1, parallelism=1)
            await c.wait_for_state(tag, JobState.RUNNING, timeout=30)
            job = c.jobs[tag]
            if fault is not None:
                await fault(c, job)
            st = await c.wait_for_state(
                tag, JobState.FINISHED, JobState.FAILED, timeout=60)
            assert st == JobState.FINISHED, job.failure
            spans = [dict(s.get("attrs", {}))
                     for s in obs.recorder().snapshot()
                     if s.get("name") == "failover.promote"]
            return (_canonical(out), job.promotions, job.restarts, spans)
        finally:
            chaos.clear()
            await c.stop()


def test_e2e_promotion_is_byte_identical_and_restart_free(tmp_path):
    """SIGKILL the primary with a standby armed: the standby promotes
    (no SCHEDULING pass, zero cold restarts), output is byte-identical
    to the failover-off run, the gap is measured on the
    `failover.promote` span, the standby was tailing within one epoch
    of the primary at kill time, and same-process restores hit the
    task-local chain cache."""
    from arroyo_tpu import chaos
    from arroyo_tpu.state.chain_cache import CACHE

    async def kill_primary(c, job):
        sb = await _wait_armed(c, job.job_id, min_epoch=1)
        # delta tailing keeps the standby within one epoch of the
        # primary's published chain
        assert sb.epoch >= job.published_epoch - 1
        wid = job.workers[0].worker_id
        plan = chaos.FaultPlan(0)
        plan.add("worker.kill", at_hits=(1,),
                 match={"worker_id": str(wid)})
        chaos.install(plan)

    want, _, _, _ = asyncio.run(_run_job(tmp_path, "foe2e-clean", False))
    hits_before = CACHE.stats()["hits"]
    got, promotions, restarts, spans = asyncio.run(
        _run_job(tmp_path, "foe2e", True, fault=kill_primary))
    assert got == want
    assert promotions >= 1
    assert restarts == 0  # promotion, not cold recovery
    gaps = [s["gap_ms"] for s in spans if "gap_ms" in s]
    assert gaps and all(0 <= g < 500.0 for g in gaps)
    # the standby restores/tails blobs this process just wrote: the
    # chain cache serves them without a storage round-trip
    assert CACHE.stats()["hits"] > hits_before
    CACHE.invalidate_job("foe2e")
    REGISTRY.drop_job("foe2e")
    REGISTRY.drop_job("foe2e-clean")


def test_e2e_fenced_zombie_primary_cannot_double_emit(tmp_path):
    """The promote_while_primary_alive shape: the primary goes silent
    (heartbeat blackout) but stays ALIVE; the standby promotes over it.
    The zombie must be fenced before the standby's sink truncation —
    byte-identical output proves it never appended a straggler row."""
    from arroyo_tpu import chaos

    async def blackout_primary(c, job):
        # fan-out RPCs refresh worker liveness, so the checkpoint
        # period must exceed the heartbeat timeout for a pure blackout
        # to trip detection (same cadence the drill replay uses)
        sb = await _wait_armed(c, job.job_id, min_epoch=1)
        wid = job.workers[0].worker_id
        plan = chaos.FaultPlan(0)
        plan.add("worker.heartbeat_blackout", at_hits=(1,),
                 match={"worker_id": str(wid)},
                 params={"duration": 2.0}, max_fires=1)
        chaos.install(plan)

    want, _, _, _ = asyncio.run(_run_job(tmp_path, "fozomb-clean", False))
    got, promotions, restarts, _ = asyncio.run(
        _run_job(tmp_path, "fozomb", True, fault=blackout_primary,
                 heartbeat_timeout=0.4, checkpoint_interval=1.0))
    assert got == want  # the fenced zombie emitted nothing extra
    assert promotions >= 1
    assert restarts == 0
    REGISTRY.drop_job("fozomb")
    REGISTRY.drop_job("fozomb-clean")
