"""Iceberg table sink: parquet data files + spec-native metadata commits.

Capability parity with the reference's Iceberg integration
(/root/reference/crates/arroyo-connectors/src/filesystem/sink/iceberg/:
mod.rs commit flow, schema.rs field-id mapping, metadata.rs DataFile
construction). The reference rides iceberg-rust + a REST catalog; this
implementation writes the Iceberg v2 format directly — field-id'd
schemas, Avro manifest / manifest-list files (formats/avro.py OCF
writer), and table-metadata JSON — against either:

  * ``catalog = 'local'``  — a filesystem catalog (Hadoop-style
    ``metadata/vN.metadata.json`` + ``version-hint.text``, atomic via
    O_EXCL create), ideal for tests and single-warehouse deployments;
  * ``catalog = 'rest'``   — the Iceberg REST catalog protocol
    (create-namespace/table, load, and commit with
    assert-ref-snapshot-id requirements), talking ``requests``.

Exactly-once: data files become visible through the filesystem sink's
2PC rename, and the snapshot commit is idempotent across restores — the
transaction id (sha256 of job/operator/epoch/table-uuid, mirroring the
reference's transaction_id at mod.rs:67) is recorded in the snapshot
summary; a recovery that replays the commit sees its own id on the
current snapshot and skips.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

import pyarrow as pa

from ..formats.avro import write_ocf
from ..utils.logging import get_logger
from .base import ConnectionSchema, Connector, register_connector
from .filesystem import FileSystemSink

logger = get_logger("iceberg")

COMMIT_ID_PROP = "arroyo-tpu.commit-id"


# ---------------------------------------------------------------------------
# Schema: arrow -> iceberg (field ids assigned depth-first, like
# reference schema.rs add_parquet_field_ids)
# ---------------------------------------------------------------------------


def _iceberg_type(t: pa.DataType, next_id) -> Any:
    if pa.types.is_boolean(t):
        return "boolean"
    if pa.types.is_int32(t) or pa.types.is_int16(t) or pa.types.is_int8(t):
        return "int"
    if pa.types.is_integer(t):
        return "long"
    if pa.types.is_float32(t):
        return "float"
    if pa.types.is_floating(t):
        return "double"
    if pa.types.is_date(t):
        return "date"
    if pa.types.is_timestamp(t):
        return "timestamptz" if t.tz else "timestamp"
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return "binary"
    if pa.types.is_decimal(t):
        return f"decimal({t.precision}, {t.scale})"
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        eid = next_id()
        return {
            "type": "list",
            "element-id": eid,
            "element": _iceberg_type(t.value_type, next_id),
            "element-required": not t.value_field.nullable,
        }
    if pa.types.is_struct(t):
        return {
            "type": "struct",
            "fields": [
                _iceberg_field(f, next_id) for f in t
            ],
        }
    return "string"


def _iceberg_field(f: pa.Field, next_id) -> dict:
    fid = next_id()
    return {
        "id": fid,
        "name": f.name,
        "required": not f.nullable,
        "type": _iceberg_type(f.type, next_id),
    }


def iceberg_schema(schema: pa.Schema) -> dict:
    """Arrow schema -> Iceberg schema JSON with assigned field ids."""
    counter = {"v": 0}

    def next_id():
        counter["v"] += 1
        return counter["v"]

    fields = [
        _iceberg_field(f, next_id)
        for f in schema
        if not f.name.startswith("_")
    ]
    return {
        "type": "struct",
        "schema-id": 0,
        "fields": fields,
        "__last_column_id__": counter["v"],
    }


def arrow_with_field_ids(schema: pa.Schema) -> pa.Schema:
    """Stamp PARQUET:field_id metadata so written parquet matches the
    Iceberg schema's ids (reference schema.rs add_parquet_field_ids)."""
    counter = {"v": 0}

    def annotate(f: pa.Field) -> pa.Field:
        counter["v"] += 1
        fid = str(counter["v"]).encode()
        t = f.type
        if pa.types.is_list(t):
            inner = annotate(t.value_field)
            t = pa.list_(inner)
        elif pa.types.is_struct(t):
            t = pa.struct([annotate(c) for c in t])
        return pa.field(
            f.name, t, f.nullable, {b"PARQUET:field_id": fid}
        )

    return pa.schema(
        [annotate(f) for f in schema if not f.name.startswith("_")]
    )


# ---------------------------------------------------------------------------
# Manifest / manifest-list Avro schemas (Iceberg v2, required fields)
# ---------------------------------------------------------------------------

_PARTITION_STRUCT = {
    "type": "record", "name": "r102", "fields": [],
}

MANIFEST_ENTRY_SCHEMA = {
    "type": "record",
    "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int", "field-id": 0},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None,
         "field-id": 1},
        {"name": "sequence_number", "type": ["null", "long"],
         "default": None, "field-id": 3},
        {"name": "file_sequence_number", "type": ["null", "long"],
         "default": None, "field-id": 4},
        {"name": "data_file", "field-id": 2, "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int", "field-id": 134},
                {"name": "file_path", "type": "string", "field-id": 100},
                {"name": "file_format", "type": "string", "field-id": 101},
                {"name": "partition", "type": _PARTITION_STRUCT,
                 "field-id": 102},
                {"name": "record_count", "type": "long", "field-id": 103},
                {"name": "file_size_in_bytes", "type": "long",
                 "field-id": 104},
            ],
        }},
    ],
}

MANIFEST_FILE_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "content", "type": "int", "field-id": 517},
        {"name": "sequence_number", "type": "long", "field-id": 515},
        {"name": "min_sequence_number", "type": "long", "field-id": 516},
        {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        {"name": "added_files_count", "type": "int", "field-id": 504},
        {"name": "existing_files_count", "type": "int", "field-id": 505},
        {"name": "deleted_files_count", "type": "int", "field-id": 506},
        {"name": "added_rows_count", "type": "long", "field-id": 512},
        {"name": "existing_rows_count", "type": "long", "field-id": 513},
        {"name": "deleted_rows_count", "type": "long", "field-id": 514},
        {"name": "partitions", "type": ["null", {
            "type": "array", "items": {
                "type": "record", "name": "r508", "fields": [
                    {"name": "contains_null", "type": "boolean",
                     "field-id": 509},
                    {"name": "contains_nan", "type": ["null", "boolean"],
                     "default": None, "field-id": 518},
                    {"name": "lower_bound", "type": ["null", "bytes"],
                     "default": None, "field-id": 510},
                    {"name": "upper_bound", "type": ["null", "bytes"],
                     "default": None, "field-id": 511},
                ],
            },
        }], "default": None, "field-id": 507},
    ],
}


# ---------------------------------------------------------------------------
# Catalogs
# ---------------------------------------------------------------------------


class LocalCatalog:
    """Filesystem (Hadoop-style) catalog: table metadata versioned under
    ``<table>/metadata/vN.metadata.json`` with a ``version-hint.text``
    pointer; commits are atomic via O_EXCL create of the next version."""

    def __init__(self, table_path: str):
        self.table_path = table_path.rstrip("/")
        self.meta_dir = os.path.join(self.table_path, "metadata")

    # -- io -------------------------------------------------------------

    def _version(self) -> int:
        """Current metadata version: the hint file, self-healed by a scan
        of existing vN files (a crash between writing vN and updating the
        hint must not wedge the table on permanent CAS conflicts)."""
        hint = 0
        try:
            with open(os.path.join(self.meta_dir, "version-hint.text")) as f:
                hint = int(f.read().strip())
        except (OSError, ValueError):
            pass
        scan = 0
        try:
            for n in os.listdir(self.meta_dir):
                if n.startswith("v") and n.endswith(".metadata.json"):
                    try:
                        scan = max(scan, int(n[1: -len(".metadata.json")]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return max(hint, scan)

    def load(self) -> Optional[dict]:
        v = self._version()
        if v == 0:
            return None
        try:
            with open(os.path.join(
                self.meta_dir, f"v{v}.metadata.json"
            )) as f:
                return json.load(f)
        except OSError:
            return None

    def create_table(self, metadata: dict) -> dict:
        os.makedirs(self.meta_dir, exist_ok=True)
        existing = self.load()
        if existing is not None:
            return existing
        self._write_version(1, metadata)
        return metadata

    def commit(self, base: dict, new: dict) -> dict:
        """CAS-commit: the next version file must not exist. On conflict
        the caller reloads and retries (same contract as the reference's
        catalog transaction)."""
        v = self._version()
        current = self.load()
        if current is not None and base is not None and (
            current.get("current-snapshot-id")
            != base.get("current-snapshot-id")
        ):
            raise CommitConflict("table advanced since load")
        self._write_version(v + 1, new)
        return new

    def _write_version(self, v: int, metadata: dict):
        target = os.path.join(self.meta_dir, f"v{v}.metadata.json")
        try:
            with open(target, "x") as f:
                json.dump(metadata, f)
        except FileExistsError:
            raise CommitConflict(f"metadata v{v} already exists")
        with open(os.path.join(self.meta_dir, "version-hint.text"), "w") as f:
            f.write(str(v))

    def metadata_location(self) -> str:
        return self.meta_dir


class RestCatalog:
    """Iceberg REST catalog client (create/load/commit), mirroring the
    surface the reference uses through iceberg-catalog-rest."""

    def __init__(self, url: str, namespace: str, table: str,
                 warehouse: Optional[str] = None,
                 token: Optional[str] = None):
        self.url = url.rstrip("/")
        self.namespace = namespace
        self.table = table
        self.warehouse = warehouse
        self.token = token

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _ns_path(self) -> str:
        return self.namespace.replace(".", "\x1f")

    def ensure_namespace(self):
        import requests

        r = requests.post(
            f"{self.url}/v1/namespaces",
            json={"namespace": self.namespace.split(".")},
            headers=self._headers(), timeout=30,
        )
        if r.status_code not in (200, 409):  # 409 = already exists
            raise IOError(f"create namespace failed: {r.status_code} "
                          f"{r.text[:200]}")

    def load(self) -> Optional[dict]:
        import requests

        r = requests.get(
            f"{self.url}/v1/namespaces/{self._ns_path()}/tables/"
            f"{self.table}",
            headers=self._headers(), timeout=30,
        )
        if r.status_code == 404:
            return None
        if r.status_code != 200:
            raise IOError(f"load table failed: {r.status_code} "
                          f"{r.text[:200]}")
        return r.json()["metadata"]

    def create_table(self, metadata: dict) -> dict:
        import requests

        self.ensure_namespace()
        body = {
            "name": self.table,
            "schema": metadata["schemas"][0],
            "location": metadata["location"],
            "partition-spec": metadata["partition-specs"][0],
            "properties": {},
        }
        r = requests.post(
            f"{self.url}/v1/namespaces/{self._ns_path()}/tables",
            json=body, headers=self._headers(), timeout=30,
        )
        if r.status_code == 409:
            loaded = self.load()
            if loaded is not None:
                return loaded
        if r.status_code != 200:
            raise IOError(f"create table failed: {r.status_code} "
                          f"{r.text[:200]}")
        return r.json()["metadata"]

    def commit(self, base: dict, new: dict) -> dict:
        import requests

        snapshot = new["snapshots"][-1]
        base_snap = (base or {}).get("current-snapshot-id")
        requirements = [{
            "type": "assert-ref-snapshot-id",
            "ref": "main",
            "snapshot-id": base_snap,
        }]
        updates = [
            {"action": "add-snapshot", "snapshot": snapshot},
            {"action": "set-snapshot-ref", "ref-name": "main",
             "type": "branch", "snapshot-id": snapshot["snapshot-id"]},
        ]
        r = requests.post(
            f"{self.url}/v1/namespaces/{self._ns_path()}/tables/"
            f"{self.table}",
            json={"requirements": requirements, "updates": updates},
            headers=self._headers(), timeout=300,
        )
        if r.status_code == 409:
            raise CommitConflict(r.text[:200])
        if r.status_code != 200:
            raise IOError(f"commit failed: {r.status_code} {r.text[:200]}")
        return r.json()["metadata"]

    def metadata_location(self) -> str:
        return None  # REST catalogs own metadata placement


class CommitConflict(Exception):
    pass


# ---------------------------------------------------------------------------
# Sink
# ---------------------------------------------------------------------------


class IcebergSink(FileSystemSink):
    """Parquet filesystem sink committing Iceberg snapshots per epoch."""

    def __init__(self, path: str, catalog: str = "local",
                 rollover_rows: int = 100_000, rest_url: str = "",
                 namespace: str = "default", table_name: str = "table",
                 token: Optional[str] = None):
        # data files live under <table>/data/
        super().__init__(os.path.join(path, "data"), "parquet",
                         rollover_rows)
        self.table_path = path.rstrip("/")
        self._arrow_schema: Optional[pa.Schema] = None
        if catalog == "rest":
            self.catalog = RestCatalog(rest_url, namespace, table_name,
                                       token=token)
        else:
            self.catalog = LocalCatalog(path)
        self._task_info = None

    def _prepare_table(self, table: pa.Table) -> pa.Table:
        """Drop internal columns and stamp parquet field ids to match the
        Iceberg schema (reference schema.rs add_parquet_field_ids)."""
        keep = [n for n in table.schema.names if not n.startswith("_")]
        table = table.select(keep)
        annotated = arrow_with_field_ids(table.schema)
        return pa.Table.from_arrays(list(table.columns), schema=annotated)

    async def process_batch(self, batch, ctx, collector, input_index=0):
        if self._arrow_schema is None:
            self._arrow_schema = batch.schema
        self._task_info = ctx.task_info
        await super().process_batch(batch, ctx, collector, input_index)

    async def on_start(self, ctx):
        self._task_info = ctx.task_info
        await super().on_start(ctx)  # renames committed-pending .tmp files
        # crash between checkpoint durability and the snapshot commit: the
        # rename above made files visible, but the replayed handle_commit
        # would find no .tmp and commit nothing — reconcile by committing a
        # recovery snapshot for visible files no manifest references
        # (DeltaSink's orphan scan, delta.py on_start, for Iceberg)
        orphans = self._orphaned_files()
        if orphans:
            if self._arrow_schema is None:
                import pyarrow.parquet as pq

                self._arrow_schema = pq.read_schema(orphans[0])
            logger.info(
                "iceberg recovery: committing %d unreferenced data files",
                len(orphans),
            )
            self._commit_snapshot(orphans, epoch=None)

    def _orphaned_files(self) -> List[str]:
        if not os.path.isdir(self.path):
            return []
        visible = {
            os.path.join(self.path, n)
            for n in os.listdir(self.path)
            if n.endswith(".parquet")
        }
        if not visible:
            return []
        referenced: set = set()
        meta = self.catalog.load()
        if meta is not None:
            from ..formats.avro import read_ocf

            cur = meta.get("current-snapshot-id")
            for s in meta.get("snapshots", []):
                if s["snapshot-id"] != cur:
                    continue  # fast-append carries all manifests forward
                try:
                    with open(s["manifest-list"], "rb") as f:
                        _, manifests = read_ocf(f.read())
                    for m in manifests:
                        with open(m["manifest_path"], "rb") as f:
                            _, entries = read_ocf(f.read())
                        referenced.update(
                            e["data_file"]["file_path"] for e in entries
                        )
                except OSError:
                    pass
        return sorted(visible - referenced)

    # -- metadata assembly -----------------------------------------------

    def _tx_id(self, epoch: Optional[int], files: List[str],
               table_uuid: str) -> str:
        h = hashlib.sha256()
        h.update(b"arroyo-tpu-txid-v1\x00")
        ti = self._task_info
        h.update((ti.job_id if ti else "job").encode() + b"\x00")
        h.update(str(ti.node_id if ti else 0).encode() + b"\x00")
        # subtasks commit independently: without the task index, parallel
        # subtasks of one epoch would collide and the second would skip
        h.update(str(ti.task_index if ti else 0).encode() + b"\x00")
        if epoch is not None:
            h.update(str(epoch).encode())
        else:  # EOD/recovery commits: identity from the file set
            for f in sorted(files):
                h.update(os.path.basename(f).encode() + b"\x00")
        h.update(b"\x00" + table_uuid.encode())
        return "tx-" + h.hexdigest()[:32]

    def _new_metadata(self) -> dict:
        ice_schema = iceberg_schema(self._arrow_schema)
        last_col = ice_schema.pop("__last_column_id__")
        return {
            "format-version": 2,
            "table-uuid": str(uuid.uuid4()),
            "location": self.table_path,
            "last-sequence-number": 0,
            "last-updated-ms": int(time.time() * 1000),
            "last-column-id": last_col,
            "current-schema-id": 0,
            "schemas": [ice_schema],
            "default-spec-id": 0,
            "partition-specs": [{"spec-id": 0, "fields": []}],
            "last-partition-id": 999,
            "default-sort-order-id": 0,
            "sort-orders": [{"order-id": 0, "fields": []}],
            "properties": {},
            "current-snapshot-id": None,
            "refs": {},
            "snapshots": [],
            "snapshot-log": [],
            "metadata-log": [],
        }

    def _data_file_entry(self, fpath: str, snapshot_id: int,
                         seq: int) -> dict:
        import pyarrow.parquet as pq

        st = os.stat(fpath)
        return {
            "status": 1,  # ADDED
            "snapshot_id": snapshot_id,
            "sequence_number": seq,
            "file_sequence_number": seq,
            "data_file": {
                "content": 0,
                "file_path": fpath,
                "file_format": "PARQUET",
                "partition": {},
                "record_count": pq.read_metadata(fpath).num_rows,
                "file_size_in_bytes": st.st_size,
            },
        }

    def _commit_snapshot(self, files: List[str], epoch: Optional[int]):
        """Write manifest + manifest list, then commit the snapshot with
        an idempotent transaction id (reference mod.rs:347 commit())."""
        for _attempt in range(5):
            base = self.catalog.load()
            if base is None:
                base = self.catalog.create_table(self._new_metadata())
            tx_id = self._tx_id(epoch, files, base["table-uuid"])
            cur_id = base.get("current-snapshot-id")
            for s in base.get("snapshots", []):
                if s["snapshot-id"] == cur_id:
                    if s.get("summary", {}).get(COMMIT_ID_PROP) == tx_id:
                        logger.info(
                            "iceberg epoch %s already committed; skipping",
                            epoch,
                        )
                        return
            seq = base.get("last-sequence-number", 0) + 1
            snapshot_id = int.from_bytes(os.urandom(8), "big") >> 1
            meta_dir = (
                self.catalog.metadata_location()
                or os.path.join(self.table_path, "metadata")
            )
            os.makedirs(meta_dir, exist_ok=True)
            entries = [
                self._data_file_entry(f, snapshot_id, seq) for f in files
            ]
            added_rows = sum(
                e["data_file"]["record_count"] for e in entries
            )
            manifest_path = os.path.join(
                meta_dir, f"{uuid.uuid4()}-m0.avro"
            )
            ice_schema = dict(base["schemas"][0])
            manifest_bytes = write_ocf(
                MANIFEST_ENTRY_SCHEMA, entries, metadata={
                    "schema": json.dumps(ice_schema),
                    "partition-spec": json.dumps([]),
                    "partition-spec-id": "0",
                    "format-version": "2",
                    "content": "data",
                },
            )
            with open(manifest_path, "wb") as f:
                f.write(manifest_bytes)
            # the new manifest list carries the previous snapshot's
            # manifests forward (fast-append, reference mod.rs:419)
            prev_manifests: List[dict] = []
            if cur_id is not None:
                for s in base["snapshots"]:
                    if s["snapshot-id"] == cur_id:
                        from ..formats.avro import read_ocf

                        try:
                            with open(s["manifest-list"], "rb") as f:
                                _, prev_manifests = read_ocf(f.read())
                        except OSError:
                            prev_manifests = []
            list_path = os.path.join(
                meta_dir, f"snap-{snapshot_id}-1-{uuid.uuid4()}.avro"
            )
            manifest_entry = {
                "manifest_path": manifest_path,
                "manifest_length": len(manifest_bytes),
                "partition_spec_id": 0,
                "content": 0,
                "sequence_number": seq,
                "min_sequence_number": seq,
                "added_snapshot_id": snapshot_id,
                "added_files_count": len(entries),
                "existing_files_count": 0,
                "deleted_files_count": 0,
                "added_rows_count": added_rows,
                "existing_rows_count": 0,
                "deleted_rows_count": 0,
                "partitions": None,
            }
            with open(list_path, "wb") as f:
                f.write(write_ocf(
                    MANIFEST_FILE_SCHEMA,
                    prev_manifests + [manifest_entry],
                ))
            now_ms = int(time.time() * 1000)
            snapshot = {
                "snapshot-id": snapshot_id,
                "parent-snapshot-id": cur_id,
                "sequence-number": seq,
                "timestamp-ms": now_ms,
                "manifest-list": list_path,
                "schema-id": 0,
                "summary": {
                    "operation": "append",
                    COMMIT_ID_PROP: tx_id,
                    "added-data-files": str(len(entries)),
                    "added-records": str(added_rows),
                },
            }
            new = dict(base)
            new["snapshots"] = list(base.get("snapshots", [])) + [snapshot]
            new["current-snapshot-id"] = snapshot_id
            new["last-sequence-number"] = seq
            new["last-updated-ms"] = now_ms
            new["refs"] = {
                "main": {"snapshot-id": snapshot_id, "type": "branch"}
            }
            new["snapshot-log"] = list(base.get("snapshot-log", [])) + [
                {"snapshot-id": snapshot_id, "timestamp-ms": now_ms}
            ]
            try:
                self.catalog.commit(base, new)
                return
            except CommitConflict:
                continue  # reload and retry (idempotence check re-runs)
        raise IOError("iceberg commit: persistent catalog conflicts")

    async def _committed(self, files: List[str], ctx, epoch=None):
        files = [f for f in files if os.path.exists(f)]
        if not files:
            return
        if self._arrow_schema is None:
            import pyarrow.parquet as pq

            self._arrow_schema = pq.read_schema(files[0])
        self._commit_snapshot(files, epoch)


@register_connector
class IcebergConnector(Connector):
    name = "iceberg"
    description = "Apache Iceberg table sink (parquet + snapshot commits)"
    source = False
    sink = True
    config_schema = {
        "path": {"type": "string", "required": True},
        "catalog": {"type": "string"},  # local (default) | rest
        "rest_url": {"type": "string"},
        "namespace": {"type": "string"},
        "table_name": {"type": "string"},
        "token": {"type": "string"},
        "rollover_rows": {"type": "integer"},
    }

    def validate_options(self, options, schema):
        if "path" not in options:
            raise ValueError("iceberg requires a path option")
        catalog = options.get("catalog", "local")
        if catalog not in ("local", "rest"):
            raise ValueError("iceberg catalog must be 'local' or 'rest'")
        if catalog == "rest" and not options.get("rest_url"):
            raise ValueError("catalog = 'rest' requires rest_url")
        out = {"path": options["path"], "catalog": catalog}
        for k in ("rest_url", "namespace", "table_name", "token"):
            if k in options:
                out[k] = options[k]
        if "rollover_rows" in options:
            out["rollover_rows"] = int(options["rollover_rows"])
        return out

    def make_sink(self, config, schema: ConnectionSchema):
        return IcebergSink(
            config["path"],
            catalog=config.get("catalog", "local"),
            rollover_rows=config.get("rollover_rows", 100_000),
            rest_url=config.get("rest_url", ""),
            namespace=config.get("namespace", "default"),
            table_name=config.get("table_name", "table"),
            token=config.get("token"),
        )

    def make_source(self, config, schema: ConnectionSchema):
        raise ValueError("iceberg is sink-only; use the filesystem source")
