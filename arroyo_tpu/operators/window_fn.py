"""Placeholder: window_fn operators land with the window/join milestone."""
