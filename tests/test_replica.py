"""Follower read replicas — the serving tier off the checkpoint stream
(ISSUE 20).

Coverage of the replica tier's load-bearing contracts:

  * the model: with a follower enabled and follower-death faults in
    the alphabet, the faithful protocol explores exhaustively clean
    (the `follower_serves_unpublished_epoch` mutant's counterexample is
    exercised by test_model_check.py's per-mutant parametrization);
  * cache-vs-staleness (satellite 3): the gateway's read-through cache
    keys on the SOURCE's epoch, so a lagging follower can never serve
    a cached entry newer than its own served epoch;
  * view plans (satellite 1): session windows serve open sessions as
    `partial: true` rows; updating joins serve per-key joined row sets
    (cross product / outer null-padding) and refuse residual joins;
  * end to end: a durable job's reads route follower-first with
    response-carried staleness <= replica.max_lag_epochs (one
    checkpoint interval) and ZERO further worker QueryState RPCs;
    killing the follower fails reads over worker-ward (no fatal, no
    wrong value) and the mount reattaches by re-resolving latest.json.
"""

import asyncio
import time

import pytest

from arroyo_tpu.config import config, update
from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import EmbeddedScheduler
from arroyo_tpu.controller.state_machine import JobState
from arroyo_tpu.serve import ServeView
from arroyo_tpu.serve.gateway import StateGateway

from test_serve import _serve_sql, _wait_found, _wait_published


# -- the model: faithful protocol clean with followers enabled ---------------


def test_model_faithful_with_followers_clean():
    """The PR 9 checker with the follower actor enabled and abrupt
    follower death in the fault alphabet: the faithful protocol
    explores exhaustively with no REPLICA violation — every reattach
    re-resolves latest.json, so no reachable interleaving serves an
    unpublished epoch. (The mutant that reattaches from the in-memory
    issued-epoch counter is caught with a replayable counterexample in
    test_model_check.py.)"""
    from pathlib import Path

    from arroyo_tpu.analysis.model import explore as explore_mod
    from arroyo_tpu.analysis.model import mutants as mutants_mod
    from arroyo_tpu.analysis.model.extract import (
        job_state_machine,
        load_project,
    )
    from arroyo_tpu.analysis.model.spec import Model, ModelConfig

    repo = Path(__file__).resolve().parents[1]
    _m, terminals, table = job_state_machine(
        load_project(repo, roots=("arroyo_tpu/controller",))
    )
    cfg = ModelConfig(workers=2, epochs=1, inflight=2, faults=1,
                      restarts=2, reads=1, followers=1,
                      fault_kinds=("fault.follower_die",))
    res = explore_mod.explore(Model(cfg, table, terminals),
                              budget=400_000)
    assert res.exhaustive
    assert not res.violations, [t.violation for t in res.violations]
    assert "follower_serves_unpublished_epoch" in mutants_mod.MUTANTS


# -- satellite 3: the cache can never outrun its source ----------------------


class _StubFollowerView:
    def __init__(self, served_epoch, values):
        self.served_epoch = served_epoch
        self.values = values


class _StubReplicas:
    """route()/read_one() shaped like ReplicaManager, pinned to one
    lagging follower view."""

    def __init__(self, view):
        self._view = view

    def route(self, job, table):
        return self._view

    def read_one(self, job_id, table, key_values):
        if self._view is None:
            return None
        found = key_values in self._view.values
        return {"found": found,
                "value": self._view.values.get(key_values),
                "epoch": self._view.served_epoch}

    def tables_meta(self, job_id):
        return None

    def lag_epochs(self, job):
        return None


def _stub_job(published_epoch=5):
    class _State:
        value = "Running"

        @staticmethod
        def is_terminal():
            return False

    return type("J", (), {
        "job_id": "j", "tenant": "t", "schedules": 1,
        "backend": object(), "published_epoch": published_epoch,
        "state": _State, "workers": [], "assignments": {},
        "mount": None, "stop_requested": False,
    })()


def test_cache_never_serves_newer_than_follower_epoch():
    """Satellite 3 regression: pre-seed the cache with a value cached
    at the PUBLISHED epoch (5) by a worker-routed read; a follower-
    routed read whose mount is one epoch behind (served_epoch 4) must
    NOT answer from that newer cache entry — it serves the follower's
    own (older) value and re-caches it at the follower's epoch."""
    job = _stub_job(published_epoch=5)
    ctrl = type("C", (), {})()
    ctrl.jobs = {"j": job}
    follower = _StubFollowerView(4, {(0,): {"cnt": "follower-old"}})
    ctrl.replicas = _StubReplicas(follower)
    gw = StateGateway(ctrl)
    info = {"table": "t", "node_id": 1, "parallelism": 1,
            "key_kinds": ["i"], "routable": True}
    gw._tables["j"] = (job.schedules, {"t": info})

    async def main():
        # a worker-routed read cached this key at epoch 5
        gw.cache.put(("j", "t", "0"), 5, job.schedules,
                     {"cnt": "worker-new"}, budget=1 << 20)
        out = await gw._routed_read(job, "t", [0])
        assert out["source"] == "follower"
        assert out["served_epoch"] == 4
        assert out["staleness"] == 1
        r = out["results"][0]
        assert r["found"] and not r.get("cached"), out
        # the follower's value won, never the newer cached one
        assert r["value"] == {"cnt": "follower-old"}, out
        # the entry is now keyed at the follower's epoch: a follower
        # re-read hits it, a worker-routed probe at 5 drops it
        out2 = await gw._routed_read(job, "t", [0])
        assert out2["results"][0].get("cached"), out2
        assert out2["served_epoch"] == 4
        ctrl.replicas._view = None  # follower detached -> worker probe
        assert gw.cache.get(("j", "t", "0"), 5, job.schedules) is None

    asyncio.run(main())


def test_follower_detach_between_route_and_read_is_retriable():
    """A follower dying between route() and the key lookup degrades
    those keys to retriable errors — never a fatal, never a value."""
    job = _stub_job(published_epoch=3)
    ctrl = type("C", (), {})()
    ctrl.jobs = {"j": job}

    class _Vanishing(_StubReplicas):
        def read_one(self, job_id, table, key_values):
            return None  # mount vanished after route()

    ctrl.replicas = _Vanishing(_StubFollowerView(3, {}))
    gw = StateGateway(ctrl)
    gw._tables["j"] = (job.schedules, {"t": {
        "table": "t", "node_id": 1, "parallelism": 1,
        "key_kinds": ["i"], "routable": True}})

    async def main():
        out = await gw._routed_read(job, "t", [0, 1])
        assert out["outcome"] == "partial"
        for r in out["results"]:
            assert not r["found"] and r["retriable"], out

    asyncio.run(main())


# -- satellite 1: view plans for session windows and updating joins ----------


def _plan_view(**kw):
    base = dict(job_id="j", table="t", node_id=1, task_index=0,
                parallelism=1, key_names=["__key0"], key_kinds=("i",),
                value_names=["rows"], kind="join", live_mode=False)
    base.update(kw)
    return ServeView(**base)


def test_join_view_plan_refuses_residual():
    """_view_plan gates which operators get views: a residual
    (non-equi) join is refused — its output rows are filtered AFTER
    the cross product, so the per-key row-set snapshot would overserve
    (a documented known limit)."""
    from arroyo_tpu.operators.updating_join import UpdatingJoinOperator
    from arroyo_tpu.serve.store import _view_plan
    from arroyo_tpu.types import TaskInfo

    op = UpdatingJoinOperator.__new__(UpdatingJoinOperator)
    op.n_keys = 1
    op.residual = None
    op.out_schema = type("S", (), {"schema": [
        type("F", (), {"name": "l_v", "type": None})(),
        type("F", (), {"name": "r_v", "type": None})(),
    ]})()
    ti = TaskInfo("j", 1, "join", 0, 1)
    plan = _view_plan(op, ti)
    assert plan is not None
    kind, key_names, _kinds, vals = plan
    assert kind == "join" and key_names == ["__key0"]
    assert vals == ["l_v", "r_v"]
    op.residual = lambda b: b
    assert _view_plan(op, ti) is None


def test_join_snapshot_cross_product_outer_padding_and_tombs():
    """The join's serve snapshot: cross product when both sides match,
    null-padding per outer semantics, lone-side inner keys invisible,
    vanished keys tombstoned on the next capture."""
    from arroyo_tpu.operators.updating_join import UpdatingJoinOperator

    op = type("Op", (), {})()
    op.join_type = "left"
    op.left_out = ["l_v"]
    op.right_out = ["r_v"]
    op.state = [
        {(1,): [("L1",), ("L2",)], (2,): [("Lonly",)]},
        {(1,): [("R1",)]},
    ]
    v = _plan_view()
    UpdatingJoinOperator.serve_stage_snapshot(op, v)
    v.seal(1)
    found, val = v.read((1,), 1)
    assert found
    assert val["rows"] == [{"l_v": "L1", "r_v": "R1"},
                           {"l_v": "L2", "r_v": "R1"}]
    # left outer: lone left side null-pads the right
    found, val = v.read((2,), 1)
    assert found and val["rows"] == [{"l_v": "Lonly", "r_v": None}]
    # inner join: a lone side serves nothing; retired keys tombstone
    op.join_type = "inner"
    op.state = [{(1,): [("L1",)]}, {}]
    UpdatingJoinOperator.serve_stage_snapshot(op, v)
    v.seal(2)
    assert v.read((1,), 2) == (False, None)
    assert v.read((2,), 2) == (False, None)


def test_session_partial_tomb_never_clobbers_final():
    """Session partials tombstone a key whose sessions all closed ONLY
    when no final landed in the same barrier interval (the final wins);
    in live mode a non-partial served value is likewise protected."""
    from arroyo_tpu.operators.windows import SessionWindowOperator

    v = _plan_view(kind="window", key_names=["k"],
                   value_names=["cnt"])
    op = type("Op", (), {})()
    op.acc = type("A", (), {"gather": None})()  # mesh-fused: skip
    op.sessions = {}
    op._serve_partial_keys = {(7,), (8,)}
    # key 7's final landed this interval (staged); key 8 just vanished
    v.stage((7,), {"cnt": 42})
    SessionWindowOperator.serve_stage_snapshot(op, v)
    # gather is None -> partials skipped entirely, including tombs
    v.seal(1)
    assert v.read((7,), 1) == (True, {"cnt": 42})

    class _Gather:
        @staticmethod
        def gather(slots):
            return []

        @staticmethod
        def finalize(x):
            return []

    op2 = type("Op", (), {})()
    op2.acc = _Gather()
    op2.gap = 10
    op2.sessions = {}
    op2._serve_partial_keys = {(7,), (8,)}
    v2 = _plan_view(kind="window", key_names=["k"],
                    value_names=["cnt"])
    v2.stage((7,), {"cnt": 42})  # the final, staged this interval
    SessionWindowOperator.serve_stage_snapshot(op2, v2)
    v2.seal(1)
    assert v2.read((7,), 1) == (True, {"cnt": 42})  # final survived
    assert v2.read((8,), 1) == (False, None)        # stale partial gone


# -- end to end: follower-first serving, kill, reattach ----------------------


def test_e2e_follower_serves_with_zero_worker_rpcs(tmp_path):
    """The acceptance path: a durable job's reads route to the
    follower mount (source=follower) with staleness <=
    replica.max_lag_epochs and ZERO further worker QueryState RPCs;
    killing the follower mid-serve fails over worker-ward (reads keep
    answering, nothing fatal, nothing wrong) and the mount reattaches
    from latest.json; stop detaches the mount and job-metric GC drops
    the arroyo_replica_* series."""
    from arroyo_tpu.metrics import (
        REGISTRY,
        REPLICA_LOOKUPS,
        SERVE_WORKER_RPCS,
    )

    wd = str(tmp_path)

    async def _wait_follower(c, jid, keys, timeout=40.0):
        deadline = time.monotonic() + timeout
        while True:
            out = await c.serve.read(jid, "tumbling_window", keys)
            if (out.get("source") == "follower"
                    and all(r.get("found") for r in out["results"])):
                return out
            assert time.monotonic() < deadline, (
                f"reads never went follower-routed: {out}, "
                f"replica={c.replicas.status()}"
            )
            await asyncio.sleep(0.3)

    async def main():
        with update(
            pipeline={"checkpointing": {
                "interval": 0.5, "storage_url": f"{wd}/ck"}},
            replica={"followers": 1, "reattach_backoff": 1.0},
        ):
            sched = EmbeddedScheduler()
            c = await ControllerServer(sched).start()
            job = await c.submit_job(
                "fl", sql=_serve_sql(wd), n_workers=2, parallelism=2,
                storage_url=f"{wd}/ck/fl",
            )
            try:
                await c.wait_for_state("fl", JobState.RUNNING,
                                       timeout=30)
                await _wait_published(job, 1)
                await _wait_found(c, "fl", "tumbling_window", 0)
                keys = list(range(8))
                out = await _wait_follower(c, "fl", keys)
                # response-carried staleness, bounded at one interval
                lag_cap = int(config().replica.max_lag_epochs)
                assert out["staleness"] <= lag_cap, out
                assert out["served_epoch"] <= job.published_epoch
                # zero worker QueryState RPCs on follower-routed reads:
                # epochs advance every 0.5 s, so these reads MISS the
                # cache and still never leave the controller (a
                # transiently lagging mount may route a read worker-
                # ward — those legs are allowed RPCs; follower-routed
                # ones get none)
                look0 = REPLICA_LOOKUPS.labels(job="fl").get()
                follower_reads = 0
                for _ in range(40):
                    before = SERVE_WORKER_RPCS.labels(job="fl").get()
                    out = await c.serve.read("fl", "tumbling_window",
                                             keys)
                    after = SERVE_WORKER_RPCS.labels(job="fl").get()
                    if out.get("source") == "follower":
                        assert after == before, out
                        assert out["staleness"] <= lag_cap, out
                        follower_reads += 1
                        if follower_reads >= 5:
                            break
                    await asyncio.sleep(0.3)
                assert follower_reads >= 5, c.replicas.status()
                assert REPLICA_LOOKUPS.labels(job="fl").get() > look0
                # REST surfaces the replica lag on the table listing
                lag = c.replicas.lag_epochs(job)
                assert lag is not None and lag <= lag_cap
                # follower death: reads fail over worker-ward with no
                # fatal and no wrong value, then the mount reattaches
                c.replicas.kill(0)
                out = await c.serve.read("fl", "tumbling_window", keys)
                assert out["source"] == "worker", out
                assert out["staleness"] == 0
                for r in out["results"]:
                    assert r.get("found") or r.get("retriable"), out
                assert c.replicas.kills == 1
                out = await _wait_follower(c, "fl", keys)
                assert out["source"] == "follower"
                # detach on stop: mount gone, replica series GC'd with
                # the job's metrics
                await c.stop_job("fl", "immediate")
                await c.wait_for_state(
                    "fl", JobState.STOPPED, JobState.FAILED,
                    JobState.FINISHED, timeout=30,
                )
                assert all("fl" not in f.mounts
                           for f in c.replicas.followers)
                assert "fl" not in c.replicas._assign
                REGISTRY.drop_job("fl")  # TTL path shortcut for the test
                text = REGISTRY.expose()
                assert 'arroyo_replica_lag_epochs{job="fl"}' not in text
            finally:
                if "fl" in c.jobs and not c.jobs["fl"].state.is_terminal():
                    await c.stop_job("fl", "immediate")
                    await c.wait_for_state(
                        "fl", JobState.STOPPED, JobState.FAILED,
                        JobState.FINISHED, timeout=30,
                    )
                await c.stop()

    asyncio.run(main())


def test_e2e_session_partials_served(tmp_path):
    """Satellite 1 end to end: a session-window job with sessions held
    open by a continuous impulse serves per-key partials (`partial:
    true`, count still growing) at the published epoch — worker-ward
    and, once the mount catches up, follower-routed off the mirrored
    checkpoint stream."""
    wd = str(tmp_path)
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '20000',
      message_count = '2000000', start_time = '0',
      realtime = 'true', replay = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{wd}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 4 as k,
             session(interval '30 second') as w, count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """

    async def main():
        with update(
            pipeline={"checkpointing": {
                "interval": 0.5, "storage_url": f"{wd}/ck"}},
            replica={"followers": 1, "reattach_backoff": 1.0},
        ):
            c = await ControllerServer(EmbeddedScheduler()).start()
            job = await c.submit_job(
                "se", sql=sql, n_workers=2, parallelism=2,
                storage_url=f"{wd}/ck/se",
            )
            try:
                await c.wait_for_state("se", JobState.RUNNING,
                                       timeout=30)
                await _wait_published(job, 1)
                tables = await c.serve.tables("se")
                name = next(t for t in tables
                            if tables[t]["kind"] == "window")
                out = await _wait_found(c, "se", name, 0)
                r = out["results"][0]
                # the 30 s gap is far longer than the test: the session
                # is open, so this MUST be a partial with a live count
                assert r["value"].get("partial") is True, out
                num_fields = [f for f, v in r["value"].items()
                              if f != "partial"
                              and isinstance(v, (int, float))]
                assert num_fields, r
                # and the partial keeps growing across epochs (the
                # session count rises; start/end may shift too — any
                # numeric field strictly increasing proves re-staging)
                deadline = time.monotonic() + 30
                while True:
                    out2 = await _wait_found(c, "se", name, 0)
                    v2 = out2["results"][0]["value"]
                    if any(v2.get(f, 0) > r["value"][f]
                           for f in num_fields):
                        break
                    assert time.monotonic() < deadline, (r, out2)
                    await asyncio.sleep(0.5)
                assert v2.get("partial") is True, out2
                # follower-routed partials off the mirrored stream
                deadline = time.monotonic() + 40
                while True:
                    out3 = await c.serve.read("se", name, [0, 1, 2, 3])
                    if (out3.get("source") == "follower"
                            and all(x.get("found")
                                    for x in out3["results"])):
                        break
                    assert time.monotonic() < deadline, (
                        out3, c.replicas.status())
                    await asyncio.sleep(0.3)
                for x in out3["results"]:
                    assert x["value"].get("partial") is True, out3
            finally:
                await c.stop_job("se", "immediate")
                await c.wait_for_state(
                    "se", JobState.STOPPED, JobState.FAILED,
                    JobState.FINISHED, timeout=30,
                )
                await c.stop()

    asyncio.run(main())
