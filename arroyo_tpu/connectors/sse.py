"""Server-Sent-Events source.

Capability parity with the reference's sse connector
(/root/reference/crates/arroyo-connectors/src/sse/, 481 LoC): connects to
an SSE endpoint, optionally filters event types, deserializes `data:`
payloads; the last event id is checkpointed and replayed via the
Last-Event-ID header.
"""

from __future__ import annotations

from typing import Optional

from ..operators.base import SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from .base import ConnectionSchema, Connector, register_connector


class SSESource(SourceOperator):
    def __init__(self, endpoint: str, events: Optional[str], headers: dict,
                 schema, format: str, bad_data: str):
        super().__init__("sse_source")
        self.endpoint = endpoint
        self.events = set(events.split(",")) if events else None
        self.headers = headers
        self.out_schema = schema
        self.deserializer = Deserializer(schema, format=format or "json",
                                         bad_data=bad_data)
        self.last_id: Optional[str] = None

    def tables(self):
        from ..state.table_config import global_table

        return {"sse": global_table("sse")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("sse")
            self.last_id = table.get(ctx.task_info.task_index)

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("sse")
            table.put(ctx.task_info.task_index, self.last_id)

    async def run(self, ctx, collector) -> SourceFinishType:
        import aiohttp

        if ctx.task_info.task_index != 0:
            return SourceFinishType.FINAL  # SSE is single-reader
        headers = dict(self.headers)
        if self.last_id:
            headers["Last-Event-ID"] = self.last_id
        async with aiohttp.ClientSession() as session:
            async with session.get(self.endpoint, headers=headers) as resp:
                # SSE framing state, mutated by the per-line callback
                st = {"event": "message", "data": [], "id": None}

                async def on_line(raw: bytes):
                    line = raw.decode("utf-8").rstrip("\n").rstrip("\r")
                    if line.startswith(":"):
                        return
                    if not line:
                        if st["data"] and (
                            self.events is None
                            or st["event"] in self.events
                        ):
                            payload = "\n".join(st["data"]).encode()
                            for row in self.deserializer.deserialize_slice(
                                payload, error_reporter=ctx.error_reporter
                            ):
                                ctx.buffer_row(row)
                            if st["id"] is not None:
                                self.last_id = st["id"]
                        st["event"], st["data"], st["id"] = (
                            "message", [], None,
                        )
                        return
                    field, _, value = line.partition(":")
                    value = value.lstrip(" ")
                    if field == "event":
                        st["event"] = value
                    elif field == "data":
                        st["data"].append(value)
                    elif field == "id":
                        st["id"] = value

                # shared select-over-control poll loop: a QUIET stream
                # must not block checkpoint barriers or stop
                finish = await self.poll_async_iter(
                    resp.content.__aiter__(), ctx, collector, on_line
                )
                if finish is not None:
                    return finish
        return SourceFinishType.FINAL


@register_connector
class SSEConnector(Connector):
    name = "sse"
    description = "server-sent events (EventSource) source"
    source = True
    config_schema = {
        "endpoint": {"type": "string", "required": True},
        "events": {"type": "string"},
        "headers": {"type": "string"},
    }

    def validate_options(self, options, schema):
        if "endpoint" not in options:
            raise ValueError("sse requires an endpoint option")
        headers = {}
        for pair in (options.get("headers") or "").split(","):
            if ":" in pair:
                k, v = pair.split(":", 1)
                headers[k.strip()] = v.strip()
        return {
            "endpoint": options["endpoint"],
            "events": options.get("events"),
            "headers": headers,
        }

    def make_source(self, config, schema: ConnectionSchema):
        return SSESource(
            config["endpoint"], config.get("events"),
            config.get("headers", {}), config.get("schema"),
            config.get("format"), config.get("bad_data", "fail"),
        )
