"""Per-process admin HTTP server: /status, /metrics, /debug/tasks.

Capability parity with the reference's admin server
(/root/reference/crates/arroyo-server-common/src/lib.rs start_admin_server:
/status, /name, /metrics, /debug/pprof): every role (controller, worker,
api) can expose liveness, Prometheus metrics, and a stack/task dump on a
local port. The pprof heap/cpu endpoints map to Python equivalents — a
live asyncio-task listing and a faulthandler thread-stack dump.
"""

from __future__ import annotations

import asyncio
import io
import time
from typing import Optional

from aiohttp import web

from ..config import config
from ..utils.logging import get_logger

logger = get_logger("admin")

_STARTED = time.time()


def build_admin_app(role: str, details_fn=None) -> web.Application:
    """`details_fn() -> dict` supplies role-specific status fields."""

    async def status(request: web.Request):
        body = {
            "service": f"arroyo-tpu-{role}",
            "status": "ok",
            "uptime_seconds": round(time.time() - _STARTED, 1),
        }
        if details_fn is not None:
            try:
                body.update(details_fn() or {})
            except Exception as e:  # noqa: BLE001
                body["details_error"] = repr(e)
        return web.json_response(body)

    async def name(request: web.Request):
        return web.Response(text=f"arroyo-tpu-{role}\n")

    async def metrics(request: web.Request):
        from ..metrics import REGISTRY

        return web.Response(
            text=REGISTRY.expose(),
            content_type="text/plain",
        )

    async def debug_tasks(request: web.Request):
        lines = []
        for t in asyncio.all_tasks():
            coro = t.get_coro()
            lines.append(
                f"{'CANCELLED' if t.cancelled() else 'DONE' if t.done() else 'RUNNING'} "
                f"{getattr(coro, '__qualname__', coro)}"
            )
        return web.Response(text="\n".join(sorted(lines)) + "\n",
                            content_type="text/plain")

    async def debug_stacks(request: web.Request):
        import sys
        import threading
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        buf = io.StringIO()
        for tid, frame in sys._current_frames().items():
            buf.write(f"Thread {names.get(tid, tid)}:\n")
            buf.write("".join(traceback.format_stack(frame)))
            buf.write("\n")
        return web.Response(text=buf.getvalue(), content_type="text/plain")

    app = web.Application()
    app.router.add_get("/status", status)
    app.router.add_get("/name", name)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/tasks", debug_tasks)
    app.router.add_get("/debug/stacks", debug_stacks)
    return app


async def serve_admin(role: str, details_fn=None,
                      port: Optional[int] = None):
    """Start the admin server; returns (runner, bound port). Port 0 binds
    an ephemeral port; admin.http_port < 0 disables (returns (None, 0))."""
    cfg = config().admin
    if port is None:
        port = cfg.http_port
    if port < 0:
        return None, 0
    app = build_admin_app(role, details_fn)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, cfg.bind_address, port)
    try:
        await site.start()
    except OSError as e:
        # a fixed port is already held by another role on this host; the
        # admin surface is advisory, so log and continue without it
        logger.warning("admin server bind failed on port %s: %s", port, e)
        await runner.cleanup()
        return None, 0
    bound = site._server.sockets[0].getsockname()[1]
    logger.info("admin server for %s on %s:%s", role, cfg.bind_address, bound)
    return runner, bound
