"""Placeholder: updating operators land with the window/join milestone."""
