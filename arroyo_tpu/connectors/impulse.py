"""Impulse connector — synthetic counter source for tests and benchmarks.

Capability parity with the reference's impulse connector
(/root/reference/crates/arroyo-connectors/src/impulse/mod.rs:182): emits
rows {counter, subtask_index} at `event_rate` events/sec/subtask, optionally
bounded by `message_count`; counter offset persists in state so restores
resume exactly. Deterministic event-time mode (`start_time` + i/rate) for
reproducible tests. `realtime` paces generation by wall clock and stamps
wall-clock event time; `replay = 'true'` (with `realtime`) keeps the wall
pacing but stamps the synthetic `start_time + i/rate` timestamps instead,
so a slow run's output is byte-identical to a fast one (the fleet harness
and multiplexed chaos smokes park/kill jobs mid-run and still demand
byte-identical output).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

import pyarrow as pa

from ..operators.base import SourceFinishType, SourceOperator
from ..schema import StreamSchema
from ..types import now_nanos
from . import splits as splits_mod
from .base import ConnectionSchema, Connector, register_connector

IMPULSE_SCHEMA = StreamSchema.from_fields(
    [("counter", pa.uint64()), ("subtask_index", pa.uint64())]
)


class ImpulseSource(SourceOperator):
    def __init__(
        self,
        event_rate: float = 10_000.0,
        message_count: Optional[int] = None,
        start_time: Optional[int] = None,
        realtime: bool = False,
        replay: bool = False,
    ):
        super().__init__("impulse")
        self.event_rate = event_rate
        self.message_count = message_count
        self.start_time = start_time
        self.realtime = realtime
        self.replay = replay
        self.out_schema = IMPULSE_SCHEMA
        # owned splits (ISSUE 15 source elasticity): counter progressions
        # {emit, next, step, hi} keyed by split id — offset state is
        # checkpointed per SPLIT, so the autoscaler can repartition this
        # source at any checkpoint boundary (connectors/splits.py)
        self.splits: dict = {}

    @property
    def counter(self) -> int:
        """Legacy single-split view (tests/bench introspection): the
        lowest unemitted counter across owned splits."""
        nxt = [int(p["next"]) for p in self.splits.values()]
        return min(nxt) if nxt else 0

    def tables(self):
        from ..state.table_config import global_table

        return {"i": global_table("i")}

    async def on_start(self, ctx):
        p = ctx.task_info.parallelism
        me = ctx.task_info.task_index
        stored: dict = {}
        if ctx.table_manager is not None:
            table = await ctx.table("i")
            stored = splits_mod.load_splits(table)
            if not stored:
                # legacy per-subtask counters (pre-elasticity layouts)
                # upgrade in place: subtask k's counter becomes split
                # "ik"'s position
                for k, v in table.items():
                    if isinstance(k, int):
                        stored[f"i{k}"] = {
                            "emit": k, "next": int(v), "step": 1,
                            "hi": self.message_count,
                        }
        if not stored:
            stored = splits_mod.impulse_plan(p, self.message_count)
        stored = splits_mod.ensure_splits(
            stored, p, splits_mod.impulse_subdivide
        )
        self.splits = splits_mod.owned(stored, p, me)

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("i")
            for sid, payload in self.splits.items():
                table.put(splits_mod.split_key(sid), dict(payload))

    def drain_status(self):
        if self.message_count is None:
            return None  # unbounded: FINAL only ever means exhausted-less
        rem = {
            sid: n for sid, p in self.splits.items()
            if (n := splits_mod.impulse_remaining(p))
        }
        if not rem:
            return (True, "")
        return (False, f"impulse splits undrained: {rem}")

    def _next_split(self):
        """The owned split with the lowest pending counter (None when
        every split is exhausted): events leave in global counter order,
        matching the classic single-progression schedule."""
        best = None
        for sid, p in self.splits.items():
            hi = p.get("hi")
            if hi is not None and int(p["next"]) >= int(hi):
                continue
            if best is None or int(p["next"]) < int(self.splits[best]["next"]):
                best = sid
        return best

    async def run(self, ctx, collector) -> SourceFinishType:
        start = self.start_time if self.start_time is not None else now_nanos()
        period = 1.0 / self.event_rate if self.event_rate > 0 else 0.0
        # schedule origin shifted by the restored position so a restore /
        # rescale resumes pacing at "now" instead of stalling out the
        # entire pre-checkpoint runtime (the nexmark source's fix)
        wall_start = time.monotonic() - self.counter * period
        busy_t0 = time.perf_counter()
        while True:
            sid = self._next_split()
            if sid is None:
                break
            sp = self.splits[sid]
            nxt = int(sp["next"])
            finish = await ctx.check_control(collector)
            if finish is not None:
                return finish
            if self.realtime:
                target = wall_start + nxt * period
                delay = target - time.monotonic()
                if delay > 0:
                    # pacing sleep: close the busy burst first so the
                    # autoscaler's busy ratio reflects generation time,
                    # not wall time (DS2 source sizing reads it)
                    ctx.note_busy(time.perf_counter() - busy_t0)
                    while delay > 0:
                        # sleep in bounded slices: a low-rate source
                        # (parked fleet jobs pace one event per tens of
                        # seconds) must keep answering control — a stop
                        # or checkpoint barrier cannot wait out a full
                        # inter-event gap
                        await asyncio.sleep(min(delay, 0.5))
                        finish = await ctx.check_control(collector)
                        if finish is not None:
                            return finish
                        delay = target - time.monotonic()
                    busy_t0 = time.perf_counter()
                # replay mode: wall-paced arrival, synthetic event time
                # (byte-identical output whatever the wall clock did);
                # plain realtime keeps stamping wall-clock time
                if self.replay:
                    ts = start + int(round(nxt * (1e9 / self.event_rate)))
                else:
                    ts = now_nanos()
            else:
                ts = start + int(round(nxt * (1e9 / self.event_rate)))
            ctx.buffer_row(
                {"counter": nxt, "subtask_index": int(sp["emit"]),
                 "_timestamp": ts}
            )
            sp["next"] = nxt + int(sp.get("step", 1))
            if ctx.should_flush():
                await self.flush_buffer(ctx, collector)
                ctx.note_busy(time.perf_counter() - busy_t0)
                # yield so queues/control stay live even in non-realtime mode
                await asyncio.sleep(0)
                busy_t0 = time.perf_counter()
        await self.flush_buffer(ctx, collector)
        ctx.note_busy(time.perf_counter() - busy_t0)
        return SourceFinishType.FINAL


@register_connector
class ImpulseConnector(Connector):
    name = "impulse"
    description = "synthetic counter source at a fixed event rate"
    source = True
    config_schema = {
        "event_rate": {"type": "number", "required": True},
        "message_count": {"type": "integer"},
        "realtime": {"type": "boolean"},
        "replay": {"type": "boolean"},
    }

    def validate_options(self, options, schema):
        out = {
            "event_rate": float(options.get("event_rate", 10_000)),
            "realtime": str(options.get("realtime", "false")).lower() == "true",
            "replay": str(options.get("replay", "false")).lower() == "true",
        }
        if "message_count" in options:
            out["message_count"] = int(options["message_count"])
        if "start_time" in options:
            out["start_time"] = int(options["start_time"])
        return out

    def table_schema(self):
        return IMPULSE_SCHEMA

    def make_source(self, config, schema: ConnectionSchema) -> ImpulseSource:
        return ImpulseSource(
            event_rate=config.get("event_rate", 10_000.0),
            message_count=config.get("message_count"),
            start_time=config.get("start_time"),
            realtime=config.get("realtime", False),
            replay=config.get("replay", False),
        )
