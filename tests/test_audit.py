"""Conservation ledger (obs/audit.py): fingerprint algebra, edge taps,
reconciler intake/reconcile checks, the process-wide breach ring, and the
report surfaces (status payloads, watchtower rule, openapi route)."""

import pyarrow as pa
import pytest

from arroyo_tpu.obs import audit

MOD = 1 << 64


@pytest.fixture(autouse=True)
def _clean_ledger():
    audit.reset()
    yield
    audit.reset()


def _batch(vals, extra=None):
    arrays = [pa.array(vals, type=pa.int64())]
    names = ["v"]
    if extra is not None:
        arrays.append(extra)
        names.append("x")
    return pa.RecordBatch.from_arrays(arrays, names=names)


# -- batch fingerprint -------------------------------------------------------


def test_fingerprint_counts_rows_and_zero_rows():
    assert audit.batch_fingerprint(_batch([]))[0] == 0
    assert audit.batch_fingerprint(_batch([])) == (0, 0)
    n, d = audit.batch_fingerprint(_batch([1, 2, 3]))
    assert n == 3 and d != 0


def test_fingerprint_is_order_insensitive():
    a = audit.batch_fingerprint(_batch([1, 2, 3, 4]))
    b = audit.batch_fingerprint(_batch([4, 2, 1, 3]))
    assert a == b


def test_fingerprint_is_slicing_invariant():
    whole = _batch(list(range(100)))
    _, want = audit.batch_fingerprint(whole)
    total = 0
    for lo in range(0, 100, 7):
        _, d = audit.batch_fingerprint(whole.slice(lo, 7))
        total = (total + d) % MOD
    assert total == want


def test_fingerprint_sees_content_not_just_counts():
    _, a = audit.batch_fingerprint(_batch([1, 2, 3]))
    _, b = audit.batch_fingerprint(_batch([1, 2, 4]))
    assert a != b


def test_fingerprint_hashes_struct_children():
    def struct(vals):
        return pa.array([{"a": v, "b": v * 2} for v in vals])

    _, a = audit.batch_fingerprint(_batch([1, 2], struct([7, 8])))
    _, b = audit.batch_fingerprint(_batch([1, 2], struct([7, 9])))
    assert a != b
    # row-order invariance holds with struct columns too
    _, c = audit.batch_fingerprint(_batch([2, 1], struct([8, 7])))
    _, d = audit.batch_fingerprint(_batch([1, 2], struct([7, 8])))
    assert c == d


def test_fingerprint_handles_list_columns():
    """unnest / ARRAY_AGG shapes: list columns hash per-row (elements
    order-insensitive within the row, length + nullness salted in) and
    keep the slicing/ordering algebra of the flat fast path."""
    def lists(vals):
        return pa.array(vals, type=pa.list_(pa.int64()))

    whole = _batch([1, 2, 3, 4], lists([[1, 2], [], None, [3]]))
    n, want = audit.batch_fingerprint(whole)
    assert n == 4
    n1, d1 = audit.batch_fingerprint(whole.slice(0, 2))
    n2, d2 = audit.batch_fingerprint(whole.slice(2, 2))
    assert (n1 + n2, (d1 + d2) % MOD) == (n, want)
    # NULL list != empty list; element placement across rows matters
    _, a = audit.batch_fingerprint(
        _batch([1, 2, 3, 4], lists([[1, 2], [], [], [3]])))
    _, b = audit.batch_fingerprint(
        _batch([1, 2, 3, 4], lists([[1], [2], None, [3]])))
    assert len({want, a, b}) == 3


# -- edge taps ---------------------------------------------------------------


def test_edge_tap_seals_per_epoch_and_resets():
    tap = audit.EdgeTap("a:0->b:0")
    tap.observe(_batch([1, 2]))
    tap.observe(_batch([3]))
    tap.seal(1)
    tap.observe(_batch([9]))
    tap.seal(2)
    r1, d1 = tap.sealed[1]
    r2, d2 = tap.sealed[2]
    assert r1 == 3 and r2 == 1 and d1 != d2
    assert tap.drain(1) == (r1, d1)
    assert tap.drain(1) is None  # drained exactly once
    assert tap.drain(99) is None


def test_edge_tap_split_vs_whole_attestation_matches():
    """A keyed shuffle slices batches; the sum of the slices' attestation
    must equal the unsliced stream's (digest commutativity end-to-end)."""
    whole, split = audit.EdgeTap("e"), audit.EdgeTap("e")
    b = _batch(list(range(50)))
    whole.observe(b)
    for lo in range(0, 50, 11):
        split.observe(b.slice(lo, 11))
    whole.seal(1)
    split.seal(1)
    assert whole.sealed[1] == split.sealed[1]


def test_edge_key_shape():
    assert audit.edge_key("3", 0, "5", 1) == "3:0->5:1"


# -- reconciler: intake (recovery conservation) ------------------------------


def _att(rows=5, dig=0xAB, edge="1:0->2:0", gen="j@1"):
    return {"tx": {edge: [rows, dig]}, "rx": {}, "ops": {}, "flow": {},
            "gen": gen}


def test_intake_accepts_fresh_epochs():
    r = audit.Reconciler("j")
    assert r.intake("t1", 1, _att(), None) is False
    assert r.intake("t1", 5, _att(), 4) is False
    assert r.breaches == []


def test_intake_fences_republished_epoch_silently():
    """Redelivery of exactly the published epoch is an rpc retry racing
    the publish: fenced, never flagged."""
    r = audit.Reconciler("j")
    assert r.intake("t1", 4, _att(), 4) is True
    assert r.breaches == []


def test_intake_flags_strictly_stale_epoch_as_rewind():
    r = audit.Reconciler("j")
    assert r.intake("t1", 2, _att(edge="7:1->9:0"), 5) is True
    (b,) = r.breaches
    assert b["kind"] == "rewind_behind_commit"
    assert b["edge"] == "7:1->9:0" and b["epoch"] == 2


def test_intake_flags_fenced_generation_as_zombie():
    r = audit.Reconciler("j")
    assert r.intake("t1", 3, _att(gen="j@2"), None) is False
    assert r.max_incarnation == 2
    assert r.intake("t2", 4, _att(gen="j@1", edge="1:0->2:1"), None) is True
    (b,) = r.breaches
    assert b["kind"] == "zombie_generation"
    assert b["edge"] == "1:0->2:1" and b["epoch"] == 4
    # the live generation keeps reporting unhindered
    assert r.intake("t1", 4, _att(gen="j@2"), None) is False


def test_intake_ignores_reports_without_attestation():
    r = audit.Reconciler("j")
    assert r.intake("t1", 1, None, 5) is False
    assert r.intake("t1", 1, {}, 5) is False
    assert r.breaches == []


def test_incarnation_parsing():
    r = audit.Reconciler
    assert r._incarnation("job@3") == 3
    assert r._incarnation("a@b@12") == 12
    assert r._incarnation("no-suffix") is None
    assert r._incarnation("job@x") is None
    assert r._incarnation(None) is None


# -- reconciler: reconcile (edge joins + flow) -------------------------------


def test_reconcile_verifies_matching_edges():
    r = audit.Reconciler("j")
    r.reconcile(3, {
        "t1": {"tx": {"1:0->2:0": [10, 77]}, "rx": {}, "ops": {}, "flow": {}},
        "t2": {"tx": {}, "rx": {"1:0->2:0": [10, 77]}, "ops": {}, "flow": {}},
    })
    assert r.breaches == []
    assert r.epochs_reconciled == 1
    assert r.edges_verified == 1
    assert r.rows_attested == 10
    assert r.last_epoch == 3
    assert r.edges["1:0->2:0"]["ok"] is True


def test_reconcile_flags_count_then_digest_mismatch():
    r = audit.Reconciler("j")
    r.reconcile(2, {
        "t1": {"tx": {"a": [10, 1], "b": [5, 2]}, "rx": {}, "ops": {},
               "flow": {}},
        "t2": {"tx": {}, "rx": {"a": [9, 1], "b": [5, 3]}, "ops": {},
               "flow": {}},
    })
    kinds = {b["edge"]: b["kind"] for b in r.breaches}
    assert kinds == {"a": "count_mismatch", "b": "digest_mismatch"}
    assert all(b["epoch"] == 2 for b in r.breaches)
    assert r.edges["a"]["ok"] is False and r.edges["b"]["ok"] is False


def test_reconcile_skips_one_sided_edges():
    """A peer that finished before this barrier contributes no attestation;
    one-sided edges are skipped, never flagged."""
    r = audit.Reconciler("j")
    r.reconcile(1, {
        "t1": {"tx": {"a": [10, 1]}, "rx": {}, "ops": {}, "flow": {}},
        "t2": None,
    })
    assert r.breaches == [] and r.edges_verified == 0


def test_reconcile_checks_declared_flow_classes():
    r = audit.Reconciler("j")
    r.reconcile(1, {
        "t1": {
            "tx": {}, "rx": {},
            "ops": {"0:filter": [10, 12], "1:map": [12, 11],
                    "2:window": [11, 2], "3:udf": [2, 9]},
            "flow": {"0:filter": "contracting", "1:map": "exact",
                     "2:window": "buffering", "3:udf": "any"},
        },
    })
    kinds = {b["edge"]: b["kind"] for b in r.breaches}
    # contracting amplified + exact lossy flagged; buffering/any never
    assert kinds == {"op:t1/0:filter": "flow_violation",
                     "op:t1/1:map": "flow_violation"}


def test_reconcile_flags_mixed_generation_epoch():
    r = audit.Reconciler("j")
    r.reconcile(6, {
        "t1": dict(_att(gen="j@2"), rx={}),
        "t2": dict(_att(gen="j@1", edge="4:0->5:0"), rx={}),
    })
    zombies = [b for b in r.breaches if b["kind"] == "zombie_generation"]
    (b,) = zombies
    assert b["edge"] == "4:0->5:0" and b["epoch"] == 6


# -- breach ring + registry --------------------------------------------------


def test_ring_mark_since_and_job_filter():
    mark = audit.breach_mark()
    audit.reconciler("j1").intake("t", 1, _att(gen="j1@1"), 3)
    audit.reconciler("j2").intake("t", 2, _att(gen="j2@1"), 9)
    assert [b["job"] for b in audit.breaches_since(mark)] == ["j1", "j2"]
    assert [b["epoch"] for b in audit.breaches_since(mark, "j2")] == [2]
    mark2 = audit.breach_mark()
    assert audit.breaches_since(mark2) == []


def test_ring_survives_job_expunge():
    """Drills assert audit silence AFTER the embedded controller tears
    the job down; the ring must outlive the reconciler."""
    mark = audit.breach_mark()
    audit.reconciler("j").intake("t", 1, _att(), 3)
    assert audit.peek("j") is not None
    audit.expunge_job("j")
    assert audit.peek("j") is None
    assert len(audit.breaches_since(mark, "j")) == 1


def test_breach_count_abstains_without_reconciler():
    assert audit.breach_count("nope") is None
    audit.reconciler("j")
    assert audit.breach_count("j") == 0.0
    audit.reconciler("j").intake("t", 1, _att(), 3)
    assert audit.breach_count("j") == 1.0


def test_status_shapes():
    audit.reconciler("j1").reconcile(1, {
        "t": {"tx": {"a": [1, 2]}, "rx": {"a": [1, 2]}, "ops": {},
              "flow": {}},
    })
    all_status = audit.status()
    assert all_status["enabled"] is True
    assert set(all_status["jobs"]) == {"j1"}
    one = audit.status("j1")
    assert one["job"] == "j1" and one["edges_verified"] == 1
    assert one["breach_count"] == 0 and one["incarnation"] is None
    assert audit.status("ghost") == {"job": "ghost"}


# -- surfaces ----------------------------------------------------------------


def test_watchtower_has_conservation_rule():
    from arroyo_tpu.obs.watchtower import build_rules

    rules = {r.name: r for r in build_rules()}
    assert "conservation" in rules
    rule = rules["conservation"]
    assert rule.kind == "above"
    assert rule.threshold == 0.5  # watch.conservation_breaches default


def test_openapi_exposes_audit_route_and_schema():
    from arroyo_tpu.api.openapi import build_spec

    s = build_spec()
    assert "/api/v1/jobs/{job_id}/audit" in s["paths"]
    schemas = s["components"]["schemas"]
    assert "AuditReport" in schemas and "AuditBreach" in schemas
    assert "kind" in schemas["AuditBreach"]["properties"]


def test_audit_disabled_via_config_env():
    from arroyo_tpu.config import update

    assert audit.enabled() is True
    with update(audit={"enabled": False}):
        assert audit.enabled() is False
    assert audit.enabled() is True
