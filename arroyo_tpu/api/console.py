"""Minimal web console served at /console.

A single-page stand-in for the reference's React webui
(/root/reference/webui): pipeline list with states, SQL editor with
validate/submit/preview, and the plan graph. Talks to the same /api/v1
the full UI would.
"""

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>arroyo-tpu console</title>
<style>
  body { font-family: ui-monospace, Menlo, monospace; margin: 0;
         background: #0d1117; color: #e6edf3; }
  header { padding: 12px 20px; background: #161b22;
           border-bottom: 1px solid #30363d; font-weight: bold; }
  main { display: grid; grid-template-columns: 1fr 1fr; gap: 16px;
         padding: 16px; }
  section { background: #161b22; border: 1px solid #30363d;
            border-radius: 6px; padding: 12px; }
  h2 { font-size: 13px; text-transform: uppercase; color: #7d8590;
       margin: 0 0 8px; }
  textarea { width: 100%; height: 220px; background: #0d1117;
             color: #e6edf3; border: 1px solid #30363d; border-radius: 4px;
             font-family: inherit; font-size: 12px; padding: 8px;
             box-sizing: border-box; }
  button { background: #238636; color: white; border: 0; border-radius: 4px;
           padding: 6px 14px; margin: 6px 6px 0 0; cursor: pointer; }
  button.alt { background: #1f6feb; }
  table { width: 100%; border-collapse: collapse; font-size: 12px; }
  td, th { text-align: left; padding: 4px 8px;
           border-bottom: 1px solid #21262d; }
  pre { background: #0d1117; border: 1px solid #30363d; border-radius: 4px;
        padding: 8px; font-size: 11px; overflow: auto; max-height: 260px; }
  .state-Running { color: #3fb950; } .state-Finished { color: #58a6ff; }
  .state-Failed { color: #f85149; } .state-Stopped { color: #d29922; }
</style>
</head>
<body>
<header>arroyo-tpu &mdash; streaming SQL on TPUs</header>
<main>
  <section>
    <h2>New pipeline</h2>
    <textarea id="sql">CREATE TABLE impulse WITH (
  connector = 'impulse', event_rate = '100000',
  message_count = '100000', start_time = '0'
);
SELECT counter % 10 as k, tumble(interval '100 millisecond') as w,
       count(*) as cnt
FROM impulse GROUP BY 1, 2;</textarea>
    <div>
      <button onclick="validateQ()">Validate</button>
      <button class="alt" onclick="preview()">Preview</button>
      <button onclick="submit()">Create pipeline</button>
    </div>
    <pre id="result">&nbsp;</pre>
  </section>
  <section>
    <h2>Pipelines</h2>
    <table id="pipelines"><tr><th>id</th><th>name</th><th>state</th>
      <th>actions</th></tr></table>
    <h2 style="margin-top:14px">Plan</h2>
    <pre id="plan">&nbsp;</pre>
  </section>
</main>
<script>
const api = p => '/api/v1' + p;
const esc = s => String(s).replace(/[&<>"']/g,
    c => '&#' + c.charCodeAt(0) + ';');
const out = (id, v) => document.getElementById(id).textContent =
    typeof v === 'string' ? v : JSON.stringify(v, null, 2);
async function post(p, body) {
  const r = await fetch(api(p), {method: 'POST',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify(body)});
  return r.json();
}
async function validateQ() {
  const v = await post('/pipelines/validate_query',
                       {query: document.getElementById('sql').value});
  out('result', v.errors && v.errors.length ? v.errors : 'valid');
  if (v.graph) out('plan', v.graph.nodes.map(n =>
      `#${n.node_id} ${n.operator} (p=${n.parallelism})`).join('\\n'));
}
async function preview() {
  out('result', 'previewing...');
  const p = await post('/pipelines/preview',
                       {query: document.getElementById('sql').value});
  if (p.error) { out('result', p.error); return; }
  for (let i = 0; i < 120; i++) {
    const o = await (await fetch(
        api(`/pipelines/preview/${p.id}/output`))).json();
    out('result', o.rows.slice(-40));
    if (o.done) { if (o.error) out('result', o.error); break; }
    await new Promise(r => setTimeout(r, 500));
  }
  refresh();
}
async function submit() {
  const p = await post('/pipelines',
                       {name: 'console', query:
                        document.getElementById('sql').value});
  out('result', p);
  refresh();
}
async function stop(id) {
  await fetch(api(`/pipelines/${id}`), {method: 'PATCH',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify({stop: 'checkpoint'})});
  refresh();
}
async function del(id) {
  await fetch(api(`/pipelines/${id}`), {method: 'DELETE'});
  refresh();
}
async function refresh() {
  const d = await (await fetch(api('/pipelines'))).json();
  const t = document.getElementById('pipelines');
  t.innerHTML = '<tr><th>id</th><th>name</th><th>state</th>' +
                '<th>actions</th></tr>';
  for (const p of d.data) {
    const tr = document.createElement('tr');
    const id = esc(p.id);
    tr.innerHTML = `<td>${id}</td><td>${esc(p.name)}</td>` +
      `<td class="state-${esc(p.state)}">${esc(p.state)}</td>` +
      `<td><a href="#" onclick="stop('${id}')">stop</a> ` +
      `<a href="#" onclick="del('${id}')">delete</a></td>`;
    t.appendChild(tr);
  }
}
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""


def add_console_routes(app):
    from aiohttp import web

    async def console(request):
        return web.Response(text=PAGE, content_type="text/html")

    app.router.add_get("/console", console)
    app.router.add_get("/", console)
