"""Always-on batch timeline profiler (ISSUE 11): the per-batch phase
ledger.

The runner's batch path decomposes into phases — arrow decode/pack,
host operator processing, device dispatch, exchange, emit, checkpoint
flush — and ROADMAP item 1 (async device pipelining) needs per-batch
evidence of where the ~2ms dispatch floor and host decode time actually
sit. Recording a real span per batch would churn the flight recorder's
ring (that is why the compile anchors are lazy), so phases land in a
dedicated bounded ring of plain tuples instead: one `perf_counter` pair
plus a deque append per phase, cheap enough to leave on in production.

The ledger exports into Perfetto dumps (`obs.perfetto_trace` renders
each (job, phase) pair as its own named track) and rolls up into
`arroyo_job_attributed_phase_seconds` via the attribution accounting —
so a q5 checkpoint epoch or a rescale renders as a real timeline, and
the bottleneck doctor can read phase shares online or offline from a
trace dump. Gated on `obs.timeline_events > 0`.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# ring entries: (ts_us_end, dur_us, phase, job, task)
_RING: deque = deque(maxlen=8192)
_LOCK = threading.Lock()

# canonical phase order for reports (decode -> ... -> flush); unknown
# phases sort after these
PHASES = ("decode", "process", "segment", "dispatch", "exchange", "emit",
          "watermark", "flush", "loop.lag")


def enabled() -> bool:
    from ..config import config

    return int(config().obs.timeline_events) > 0


def _resize() -> None:
    from ..config import config

    global _RING
    cap = int(config().obs.timeline_events)
    if cap > 0 and _RING.maxlen != cap:
        with _LOCK:
            _RING = deque(_RING, maxlen=cap)


def note(phase: str, dur_s: float, *, job: Optional[str] = None,
         task: str = "") -> None:
    """Record one phase instant (duration ending now). `job` defaults to
    the ambient attribution context; also feeds the per-job phase-seconds
    rollup so the metric surface and the ledger cannot drift."""
    from ..config import config

    cap = int(config().obs.timeline_events)
    if cap <= 0:
        return
    if _RING.maxlen != cap:
        _resize()
    from . import attribution

    if job is None:
        job = attribution.current_job()
    _RING.append((time.time() * 1e6, dur_s * 1e6, phase, job, task))
    attribution.note(job=job, phase=phase, phase_secs=dur_s)


def snapshot(job: Optional[str] = None) -> List[dict]:
    """The ledger as dicts, oldest first; `job` filters one job's
    entries."""
    with _LOCK:
        entries = list(_RING)
    out = []
    for ts_us, dur_us, phase, j, task in entries:
        if job is not None and j != job:
            continue
        out.append({"ts": ts_us - dur_us, "dur": dur_us, "phase": phase,
                    "job": j, "task": task})
    return out


def phase_totals(job: Optional[str] = None) -> Dict[str, dict]:
    """Per-phase {count, total_s, max_s} over the ledger window — the
    offline doctor's primary signal when only a trace dump is at hand."""
    totals: Dict[str, dict] = {}
    for e in snapshot(job):
        t = totals.setdefault(e["phase"],
                              {"count": 0, "total_s": 0.0, "max_s": 0.0})
        t["count"] += 1
        t["total_s"] += e["dur"] / 1e6
        t["max_s"] = max(t["max_s"], e["dur"] / 1e6)
    for t in totals.values():
        t["total_s"] = round(t["total_s"], 6)
        t["max_s"] = round(t["max_s"], 6)
    return totals


def expunge_job(job_id: str) -> int:
    """Job-scoped GC (StopJob / Registry.drop_job path): drop the torn-
    down job's phase instants instead of letting them linger until
    overwrite. Returns the number removed."""
    with _LOCK:
        kept = [e for e in _RING if e[3] != job_id]
        removed = len(_RING) - len(kept)
        _RING.clear()
        _RING.extend(kept)
    return removed


def clear() -> None:
    with _LOCK:
        _RING.clear()
    _resize()
