from .logical import (  # noqa: F401
    EdgeType,
    LogicalEdge,
    LogicalGraph,
    LogicalNode,
    OperatorName,
)
from .optimizer import ChainingOptimizer  # noqa: F401
