"""Bottleneck doctor (ISSUE 11): per-job ranked limiting-factor verdicts.

Combines the fleet observatory's signals — per-job busy ratio,
backpressure, queue depth, watermark lag, the device dispatch floor,
padding waste, event-loop lag, and per-job attributed cost shares —
into one ranked verdict naming the limiting operator and the suspected
cause:

  host-bound       the job is busy and nearly all of it is host python/
                   arrow work (ROADMAP item 1's decode/pack overlap is
                   the fix);
  device-bound     the job is busy and its time sits inside jitted
                   device programs (dispatch floor / padding waste are
                   the levers);
  exchange-bound   the keyed shuffle (data-plane frames or the mesh
                   collective) dominates the phase ledger;
  starved          the job is idle with empty queues, no backpressure
                   and an uncontended loop: upstream has nothing for it;
  noisy-neighbor   the job is idle *because the shared worker is not*:
                   a co-resident tenant holds the loop (high loop lag +
                   a dominant attributed-busy share) — named explicitly
                   so operators know who to throttle.

The same `diagnose()` runs online (`GET /api/v1/jobs/{id}/doctor`,
`/debug/doctor?job=`) against the live registry, and offline
(`tools/trace_report.py --doctor`) against signals reconstructed from a
Perfetto trace dump.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# signal thresholds: a busy ratio above BUSY_HIGH reads as "the job is
# the bottleneck of itself"; loop lag above LAG_FLOOR_MS reads as loop
# contention; a co-resident tenant above NEIGHBOR_SHARE of attributed
# busy is a nameable neighbor
BUSY_HIGH = 0.5
LAG_FLOOR_MS = 20.0
NEIGHBOR_SHARE = 0.5
# steady-state dispatch wall above this reads as "paying the dispatch
# floor" (the round-11 ledger put the per-op floor at ~2ms)
DISPATCH_FLOOR_MS = 1.5


def _windowed_overlay(sig: dict, job_id: str, ops: Dict[str, dict]) -> None:
    """Re-point the busy/neighbor signals at the retained metric
    history (ISSUE 13): cumulative attributed totals describe a job's
    LIFETIME average, but the doctor is asked about NOW — overlay
    windowed deltas (`watch.window` lookback, the same
    `history.Series.delta` rate path the SLO engine and autoscaler
    read) wherever the history has coverage, keeping the cumulative
    values as the no-history fallback."""
    from ..config import config
    from .history import HISTORY

    win = float(config().watch.window)
    busy_series = HISTORY.get("arroyo_job_attributed_busy_seconds")
    deltas: Dict[str, float] = {}
    covered = 0.0
    for s in busy_series:
        d = s.delta(win)
        if d is None:
            continue
        pts = s.window(win)
        covered = max(covered, pts[-1][0] - pts[0][0])
        job = s.label("job")
        deltas[job] = deltas.get(job, 0.0) + d
    if not deltas or covered <= 0:
        return
    sig["windowed"] = True
    sig["window_s"] = round(min(win, covered), 3)
    busy_s = deltas.get(job_id, 0.0)
    sig["busy_s"] = round(busy_s, 4)
    sig["busy_ratio"] = round(
        min(1.0, busy_s / sig["window_s"]), 4
    ) if sig["window_s"] > 0 else 0.0
    neighbors = [
        {"job": j, "busy_s": round(d, 4)}
        for j, d in deltas.items() if j not in (job_id, "") and d > 0
    ]
    neighbors.sort(key=lambda n: -n["busy_s"])
    others = sum(n["busy_s"] for n in neighbors)
    sig["neighbors"] = neighbors[:8]
    sig["neighbor_top_share"] = round(
        neighbors[0]["busy_s"] / (busy_s + others), 4
    ) if neighbors and (busy_s + others) > 0 else 0.0
    dev = 0.0
    for s in HISTORY.get("arroyo_job_attributed_device_seconds",
                         job=job_id):
        d = s.delta(win)
        if d is not None:
            dev += d
    if dev:
        sig["device_s"] = round(dev, 4)
    # per-task busy: windowed where the series has coverage
    for s in HISTORY.get("arroyo_worker_busy_seconds", job=job_id):
        d = s.delta(win)
        task = s.label("task")
        if d is not None and task in ops:
            ops[task]["busy_s"] = round(d, 4)
    sig["operators"] = sorted(ops.values(),
                              key=lambda o: -o.get("busy_s", 0.0))


def collect(job_id: str, registry=None) -> dict:
    """Gather one job's doctor signals from this process's registry,
    the attribution accounting, the timeline ledger — and, where the
    watchtower history tier has coverage, WINDOWED rates instead of
    lifetime cumulatives (see _windowed_overlay)."""
    from ..metrics import REGISTRY, hist_quantiles
    from . import attribution, timeline

    registry = registry or REGISTRY
    attribution.ACCOUNTING.flush()
    snap = registry.snapshot()

    def per_task(name: str, field: str, ops: Dict[str, dict],
                 hist_q: Optional[str] = None):
        for labels, value in snap.get(name, []):
            if labels.get("job") != job_id or "task" not in labels:
                continue
            ent = ops.setdefault(labels["task"], {"task": labels["task"]})
            if isinstance(value, dict):
                q = hist_quantiles(value)
                ent[field] = round(1e3 * q.get(hist_q or "p95", 0.0), 3)
            else:
                ent[field] = round(float(value), 4)

    ops: Dict[str, dict] = {}
    per_task("arroyo_worker_busy_seconds", "busy_s", ops)
    per_task("arroyo_worker_backpressure", "backpressure", ops)
    per_task("arroyo_worker_watermark_lag_seconds", "watermark_lag_s", ops)
    per_task("arroyo_worker_batch_processing_seconds", "batch_p95_ms", ops)
    queue_depth = 0.0
    for labels, value in snap.get("arroyo_worker_queue_size", []):
        if labels.get("job") == job_id:
            queue_depth = max(queue_depth, float(value))

    summary = attribution.ACCOUNTING.summary()
    mine = summary["jobs"].get(job_id, {})
    window = mine.get("window_s") or 0.0
    busy_s = mine.get("busy", 0.0)
    neighbors = [
        {"job": j, "busy_s": e.get("busy", 0.0)}
        for j, e in summary["jobs"].items()
        if j not in (job_id, "(unattributed)") and e.get("busy", 0.0) > 0
    ]
    neighbors.sort(key=lambda n: -n["busy_s"])
    others = sum(n["busy_s"] for n in neighbors)

    dispatch_p50 = 0.0
    dispatches = 0
    for _labels, h in snap.get("arroyo_device_dispatch_seconds", []):
        dispatches += int(h.get("count", 0))
        dispatch_p50 = max(
            dispatch_p50, hist_quantiles(h).get("p50", 0.0)
        )
    padding = max(
        (float(v) for _l, v in snap.get("arroyo_device_padding_waste", [])),
        default=0.0,
    )

    phases = {
        p: t["total_s"]
        for p, t in timeline.phase_totals(job_id).items()
    }
    sig = {
        "job": job_id,
        "window_s": round(window, 3),
        "busy_s": round(busy_s, 4),
        "busy_ratio": round(busy_s / window, 4) if window > 0 else 0.0,
        "device_s": round(mine.get("device", 0.0), 4),
        "operators": sorted(ops.values(),
                            key=lambda o: -o.get("busy_s", 0.0)),
        "backpressure": max(
            (o.get("backpressure", 0.0) for o in ops.values()), default=0.0
        ),
        "queue_depth": queue_depth,
        "watermark_lag_s": max(
            (o.get("watermark_lag_s", 0.0) for o in ops.values()),
            default=0.0,
        ),
        "phases": phases,
        "dispatch_p50_ms": round(1e3 * dispatch_p50, 3),
        "dispatches": dispatches,
        "padding_waste": round(padding, 4),
        "loop_lag_ms_p99": summary.get("loop_lag_ms", {}).get("p99", 0.0),
        "neighbors": neighbors[:8],
        "neighbor_top_share": round(
            neighbors[0]["busy_s"] / (busy_s + others), 4
        ) if neighbors and (busy_s + others) > 0 else 0.0,
        "attribution_coverage": summary.get("coverage", 1.0),
    }
    _windowed_overlay(sig, job_id, ops)
    return sig


def diagnose(sig: dict) -> dict:
    """Rank the five causes against one job's signals and name the
    limiting operator. Pure function of the signal dict so the offline
    (trace-dump) and online paths cannot drift."""
    busy = float(sig.get("busy_ratio") or 0.0)
    phases = sig.get("phases") or {}
    phase_total = sum(
        v for p, v in phases.items() if p != "loop.lag"
    ) or 1e-9
    device_s = float(sig.get("device_s") or phases.get("dispatch", 0.0))
    busy_s = float(sig.get("busy_s") or 0.0) or phase_total
    device_share = min(1.0, device_s / busy_s) if busy_s > 0 else 0.0
    exchange_share = phases.get("exchange", 0.0) / phase_total
    lag_ms = float(sig.get("loop_lag_ms_p99") or 0.0)
    lag_factor = min(1.0, lag_ms / LAG_FLOOR_MS)
    neighbor_share = float(sig.get("neighbor_top_share") or 0.0)
    bp = float(sig.get("backpressure") or 0.0)
    pressure = max(bp, min(1.0, float(sig.get("queue_depth") or 0.0) / 4.0))

    scores = {
        # busy and mostly host work: the job's own python/arrow path is
        # the wall (decode/pack/emit dominate the ledger)
        "host-bound": busy * (1.0 - device_share) * (1.0 - exchange_share),
        # busy and inside jitted programs; paying the dispatch floor or
        # shipping padding amplifies the verdict
        "device-bound": busy * device_share * (
            1.0 + (0.5 if float(sig.get("dispatch_p50_ms") or 0.0)
                   >= DISPATCH_FLOOR_MS else 0.0)
            + min(0.5, float(sig.get("padding_waste") or 0.0))
        ),
        # the keyed shuffle dominates the phase ledger, or downstream
        # queues are full (the classic backpressure chain)
        "exchange-bound": max(exchange_share, bp) * max(busy, 0.3),
        # idle with an idle worker: upstream simply has nothing for it
        "starved": (1.0 - busy) * (1.0 - lag_factor)
        * (1.0 - neighbor_share) * (1.0 - pressure),
        # idle because a co-resident tenant holds the shared loop: only
        # scores when a neighbor actually dominates attributed busy AND
        # the loop shows contention
        "noisy-neighbor": (1.0 - busy) * neighbor_share
        * (0.4 + 0.6 * lag_factor)
        * (1.0 if neighbor_share >= NEIGHBOR_SHARE else 0.5),
    }
    ranked = sorted(
        ({"cause": c, "score": round(s, 4)} for c, s in scores.items()),
        key=lambda e: -e["score"],
    )
    top = ranked[0]
    operators = sig.get("operators") or []
    limiting = operators[0]["task"] if operators else None
    if top["cause"] == "exchange-bound" and operators:
        # under backpressure the slow consumer, not the busiest producer,
        # is the limiting operator: pick the most backpressured task's
        # downstream-most sibling (highest backpressure reading)
        limiting = max(
            operators, key=lambda o: o.get("backpressure", 0.0)
        )["task"]
    verdict = {
        "cause": top["cause"],
        "score": top["score"],
        "operator": limiting,
        "confidence": round(
            top["score"] / (top["score"] + ranked[1]["score"] + 1e-9), 3
        ),
    }
    if top["cause"] == "noisy-neighbor" and sig.get("neighbors"):
        verdict["suspect"] = sig["neighbors"][0]["job"]
    detail = {
        "host-bound": "host python/arrow work dominates; overlap "
                      "decode/pack with in-flight dispatch (ROADMAP 1)",
        "device-bound": "time sits inside jitted programs; check the "
                        "dispatch floor and padding waste",
        "exchange-bound": "the keyed shuffle / downstream queues limit "
                          "throughput",
        "starved": "idle with empty queues on an uncontended worker; "
                   "upstream produces too little",
        "noisy-neighbor": "idle while a co-resident tenant holds the "
                          "shared worker loop",
    }[top["cause"]]
    verdict["detail"] = detail
    return {"job": sig.get("job"), "verdict": verdict, "ranked": ranked,
            "signals": sig}


def report(job_id: str) -> dict:
    """collect + diagnose: the REST/debug doctor payload."""
    return diagnose(collect(job_id))


def signals_from_trace(events: List[dict], job_id: str) -> dict:
    """Reconstruct doctor signals from a (merged) Perfetto/Chrome trace
    dump: phase.* events carry the ledger, loop.lag events the loop
    contention, and per-job phase sums stand in for attributed busy.
    Enough to render the verdict offline when only artifacts survive."""
    phases: Dict[str, float] = {}
    by_job: Dict[str, float] = {}
    lags: List[float] = []
    t_min, t_max = None, None
    for ev in events:
        if ev.get("ph") != "X" or not ev.get("name", "").startswith("phase."):
            continue
        args = ev.get("args") or {}
        job = args.get("job", "")
        dur_s = (ev.get("dur") or 0.0) / 1e6
        phase = ev["name"][len("phase."):]
        if phase == "loop.lag":
            lags.append(dur_s)
            continue
        by_job[job] = by_job.get(job, 0.0) + dur_s
        ts = ev.get("ts", 0.0)
        t_min = ts if t_min is None else min(t_min, ts)
        t_max = ts + ev.get("dur", 0.0) if t_max is None else max(
            t_max, ts + ev.get("dur", 0.0))
        if job == job_id:
            phases[phase] = phases.get(phase, 0.0) + dur_s
    window = (t_max - t_min) / 1e6 if t_min is not None else 0.0
    busy_s = by_job.get(job_id, 0.0)
    neighbors = sorted(
        ({"job": j, "busy_s": round(s, 4)} for j, s in by_job.items()
         if j not in (job_id, "")),
        key=lambda n: -n["busy_s"],
    )
    others = sum(n["busy_s"] for n in neighbors)
    lags.sort()
    return {
        "job": job_id,
        "window_s": round(window, 3),
        "busy_s": round(busy_s, 4),
        "busy_ratio": round(busy_s / window, 4) if window > 0 else 0.0,
        "device_s": phases.get("dispatch", 0.0),
        "operators": [],
        "backpressure": 0.0,
        "queue_depth": 0.0,
        "watermark_lag_s": 0.0,
        "phases": {p: round(v, 6) for p, v in phases.items()},
        "dispatch_p50_ms": 0.0,
        "dispatches": 0,
        "padding_waste": 0.0,
        "loop_lag_ms_p99": round(
            1e3 * lags[min(len(lags) - 1, int(0.99 * len(lags)))], 3
        ) if lags else 0.0,
        "neighbors": neighbors[:8],
        "neighbor_top_share": round(
            neighbors[0]["busy_s"] / (busy_s + others), 4
        ) if neighbors and (busy_s + others) > 0 else 0.0,
        "offline": True,
    }
