"""In-memory fake broker clients for the gated connectors.

The reference tests its kafka sink logic broker-less
(/root/reference/crates/arroyo-connectors/src/kafka/sink/test.rs with a
MockKafkaClient); these fakes go one step further and emulate enough of
each client library's surface to drive the REAL connector operators
end-to-end through the engine — produce/consume, partition assignment,
transactions with read-committed isolation and transactional-id fencing
(kafka), shard iterators (kinesis), and subject streams with durable
consumers (NATS JetStream).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


# ---------------------------------------------------------------------------
# Kafka (confluent_kafka surface)
# ---------------------------------------------------------------------------


class FakeKafkaException(Exception):
    """Stands in for confluent_kafka.KafkaException (fencing/state errors)."""


class FakeKafkaBroker:
    """Topic/partition logs with protocol-shaped transactional semantics:

    - messages from a transactional producer stay invisible until
      commit_transaction (read-committed consumers stop at the LSO);
    - init_transactions bumps the transactional.id's PRODUCER EPOCH and
      fences (aborts) the previous epoch's open transaction — any further
      call through a stale-epoch producer raises FakeKafkaException
      ("fenced"), including commit-after-fence;
    - abort_transaction discards the in-flight transaction's messages
      (they stay invisible forever);
    - a replayed commit for an already-committed transaction is
      idempotent at the broker (no duplicate visibility, no error) — the
      2PC recovery path replays commits."""

    def __init__(self, partitions_per_topic: int = 2):
        self.partitions_per_topic = partitions_per_topic
        # topic -> partition -> [FakeMessage]
        self.logs: Dict[str, Dict[int, List["FakeMessage"]]] = {}
        # transactional.id -> list of uncommitted FakeMessage
        self.open_tx: Dict[str, List["FakeMessage"]] = {}
        self.aborted_tx: List[str] = []
        # transactional.id -> current producer epoch (init_transactions)
        self.tx_epochs: Dict[str, int] = {}
        # transactional.id -> epochs whose transaction committed
        self.committed_tx: Dict[str, set] = {}
        self.lock = threading.Lock()

    def topic(self, name: str) -> Dict[int, List["FakeMessage"]]:
        with self.lock:
            return self.logs.setdefault(
                name, {p: [] for p in range(self.partitions_per_topic)}
            )

    def append(self, topic: str, partition: int, key, value,
               committed: bool, tx_id: Optional[str]) -> "FakeMessage":
        log = self.topic(topic)[partition]
        m = FakeMessage(topic, partition, len(log), key, value,
                        committed=committed)
        log.append(m)
        if not committed and tx_id is not None:
            self.open_tx.setdefault(tx_id, []).append(m)
        return m

    def begin_tx(self, tx_id: str):
        """Open a (possibly empty) transaction: committing an epoch that
        produced no messages is legal and must not read as 'no such
        transaction'."""
        with self.lock:
            self.open_tx.setdefault(tx_id, [])

    def register_producer(self, tx_id: str) -> int:
        """init_transactions: bump the epoch, fence the previous one."""
        with self.lock:
            epoch = self.tx_epochs.get(tx_id, 0) + 1
            self.tx_epochs[tx_id] = epoch
        self.fence(tx_id)
        return epoch

    def check_epoch(self, tx_id: str, epoch: int):
        cur = self.tx_epochs.get(tx_id)
        if cur != epoch:
            raise FakeKafkaException(
                f"transactional.id {tx_id!r} epoch {epoch} fenced by "
                f"newer producer epoch {cur}"
            )

    def commit_tx(self, tx_id: str, epoch: Optional[int] = None):
        msgs = self.open_tx.pop(tx_id, None)
        if msgs is None:
            # duplicate/replayed commit: already-committed transactions
            # commit idempotently, never re-expose or error
            if epoch is not None and epoch in self.committed_tx.get(
                tx_id, ()
            ):
                return
            if epoch is None:
                return
            raise FakeKafkaException(
                f"commit for {tx_id!r} epoch {epoch}: no open or "
                "committed transaction"
            )
        for m in msgs:
            m.committed = True
        if epoch is not None:
            self.committed_tx.setdefault(tx_id, set()).add(epoch)

    def abort_tx(self, tx_id: str):
        """Explicit abort: the in-flight transaction's messages stay
        invisible forever (read-committed consumers skip past them, like
        abort markers let real consumers do)."""
        msgs = self.open_tx.pop(tx_id, None)
        if msgs is not None:
            for m in msgs:
                m.aborted = True
            self.aborted_tx.append(tx_id)

    def fence(self, tx_id: str):
        """Abort any open transaction for this transactional.id (its
        messages stay invisible forever)."""
        self.abort_tx(tx_id)

    def visible(self, topic: str, partition: int) -> List["FakeMessage"]:
        return self.topic(topic)[partition]

    def make_module(self):
        """An object quacking like the confluent_kafka module, bound to
        this broker (patch connectors.kafka._load_client to return it)."""
        broker = self

        class _Module:
            @staticmethod
            def Consumer(conf):
                return FakeConsumer(broker, conf)

            @staticmethod
            def Producer(conf):
                return FakeProducer(broker, conf)

            TopicPartition = FakeTopicPartition
            KafkaException = FakeKafkaException

        return _Module


class FakeMessage:
    def __init__(self, topic, partition, offset, key, value,
                 committed=True):
        self._topic = topic
        self._partition = partition
        self._offset = offset
        self._key = key
        self._value = value
        self.committed = committed
        self.aborted = False
        self._ts_ms = int(time.time() * 1000)

    def error(self):
        return None

    def topic(self):
        return self._topic

    def partition(self):
        return self._partition

    def offset(self):
        return self._offset

    def key(self):
        return self._key

    def value(self):
        return self._value

    def timestamp(self):
        return (1, self._ts_ms)  # (CREATE_TIME, ms)


class FakeTopicPartition:
    def __init__(self, topic, partition, offset=-1001):
        self.topic = topic
        self.partition = partition
        self.offset = offset


class _TopicMeta:
    def __init__(self, partitions: Dict[int, object]):
        self.partitions = partitions


class _ClusterMeta:
    def __init__(self, topics):
        self.topics = topics


class FakeConsumer:
    """read_committed consumer over assigned partitions."""

    def __init__(self, broker: FakeKafkaBroker, conf: dict):
        self.broker = broker
        self.conf = conf
        self.auto_reset = conf.get("auto.offset.reset", "earliest")
        self.positions: Dict[tuple, int] = {}
        self._assigned: List[FakeTopicPartition] = []
        self.closed = False

    def list_topics(self, topic=None, timeout=None):
        parts = {p: object() for p in self.broker.topic(topic)}
        return _ClusterMeta({topic: _TopicMeta(parts)})

    def assign(self, tps: List[FakeTopicPartition]):
        self._assigned = tps
        for tp in tps:
            key = (tp.topic, tp.partition)
            if tp.offset >= 0:
                self.positions[key] = tp.offset
            elif self.auto_reset == "latest":
                self.positions[key] = len(
                    self.broker.visible(tp.topic, tp.partition)
                )
            else:
                self.positions[key] = 0

    def poll(self, timeout=0):
        for tp in self._assigned:
            key = (tp.topic, tp.partition)
            log = self.broker.visible(tp.topic, tp.partition)
            pos = self.positions[key]
            # read_committed: skip aborted messages (abort markers), stop
            # at the first open-transaction message (LSO)
            while pos < len(log):
                m = log[pos]
                if m.aborted:
                    pos += 1
                    self.positions[key] = pos
                    continue
                if not m.committed:
                    break
                self.positions[key] = pos + 1
                return m
        return None

    def close(self):
        self.closed = True


class FakeProducer:
    def __init__(self, broker: FakeKafkaBroker, conf: dict):
        self.broker = broker
        self.conf = conf
        self.tx_id = conf.get("transactional.id")
        self.epoch: Optional[int] = None  # assigned by init_transactions
        self.in_tx = False
        self._committed = False
        self._n = 0

    def _check_fenced(self):
        if self.tx_id is not None and self.epoch is not None:
            self.broker.check_epoch(self.tx_id, self.epoch)

    def init_transactions(self, timeout=None):
        assert self.tx_id, "init_transactions without transactional.id"
        self.epoch = self.broker.register_producer(self.tx_id)

    def begin_transaction(self):
        if self.tx_id and self.epoch is None:
            raise FakeKafkaException(
                "begin_transaction before init_transactions"
            )
        self._check_fenced()
        if self.in_tx:
            raise FakeKafkaException("begin_transaction while in transaction")
        if self.tx_id:
            self.broker.begin_tx(self.tx_id)
        self.in_tx = True
        self._committed = False

    def produce(self, topic, value=None, key=None):
        self._check_fenced()
        partition = (
            hash(key) % self.broker.partitions_per_topic
            if key is not None else self._n % self.broker.partitions_per_topic
        )
        self._n += 1
        self.broker.append(
            topic, partition, key, value,
            committed=not self.in_tx, tx_id=self.tx_id,
        )

    def poll(self, timeout=0):
        return 0

    def flush(self, timeout=None):
        return 0

    def commit_transaction(self, timeout=None):
        self._check_fenced()  # commit-after-fence is an error
        if not self.in_tx:
            if self._committed:
                return  # replayed commit: idempotent
            raise FakeKafkaException("commit without an open transaction")
        self.broker.commit_tx(self.tx_id, self.epoch)
        self.in_tx = False
        self._committed = True

    def abort_transaction(self, timeout=None):
        self._check_fenced()
        self.broker.abort_tx(self.tx_id)
        self.in_tx = False


# ---------------------------------------------------------------------------
# Kinesis (boto3 module + kinesis client surface the source/sink use)
# ---------------------------------------------------------------------------


class FakeKinesisStream:
    """Shard logs; install via sys.modules['boto3'] = stream.boto3()."""

    def __init__(self, shards: int = 2):
        self.shards = {
            f"shardId-{i:012d}": [] for i in range(shards)
        }
        self.closed_shards: set = set()
        self.parents: Dict[str, str] = {}  # child -> parent shard id

    def put(self, shard_id: str, data: bytes):
        self.shards[shard_id].append(data)

    def boto3(self):
        stream = self

        class _Boto3:
            @staticmethod
            def client(service, region_name=None):
                assert service == "kinesis"
                return _FakeKinesisClient(stream)

        return _Boto3

    def split_shard(self, shard_id: str, new_ids: List[str]):
        """Resharding: the parent closes (get_records returns a null next
        iterator at its end) and children appear in list_shards."""
        self.closed_shards.add(shard_id)
        for n in new_ids:
            self.shards.setdefault(n, [])
            self.parents[n] = shard_id


class _FakeKinesisClient:
    def __init__(self, stream: FakeKinesisStream):
        self.stream = stream

    def list_shards(self, StreamName=None):
        out = []
        for s in sorted(self.stream.shards):
            d = {"ShardId": s, "SequenceNumberRange": {}}
            if s in self.stream.parents:
                d["ParentShardId"] = self.stream.parents[s]
            if s in self.stream.closed_shards:
                d["SequenceNumberRange"]["EndingSequenceNumber"] = str(
                    len(self.stream.shards[s])
                )
            out.append(d)
        return {"Shards": out}

    def get_shard_iterator(self, StreamName=None, ShardId=None,
                           ShardIteratorType="TRIM_HORIZON",
                           StartingSequenceNumber=None):
        if ShardIteratorType == "AFTER_SEQUENCE_NUMBER":
            seq = int(StartingSequenceNumber) + 1
        elif ShardIteratorType == "LATEST":
            seq = len(self.stream.shards[ShardId])
        else:  # TRIM_HORIZON
            seq = 0
        return {"ShardIterator": f"{ShardId}:{seq}"}

    def get_records(self, ShardIterator=None, Limit=1000):
        import datetime

        shard, seq = ShardIterator.rsplit(":", 1)
        seq = int(seq)
        log = self.stream.shards[shard]
        recs = [
            {
                "Data": d,
                "SequenceNumber": str(i),
                "ApproximateArrivalTimestamp": datetime.datetime.now(
                    datetime.timezone.utc
                ),
            }
            for i, d in enumerate(log[seq: seq + Limit], start=seq)
        ]
        nxt = seq + len(recs)
        closed = (
            shard in self.stream.closed_shards and nxt >= len(log)
        )
        return {
            "Records": recs,
            "NextShardIterator": None if closed else f"{shard}:{nxt}",
            "MillisBehindLatest": 0,
        }

    def put_records(self, StreamName=None, Records=None):
        for i, r in enumerate(Records):
            sid = sorted(self.stream.shards)[
                hash(r.get("PartitionKey", i)) % len(self.stream.shards)
            ]
            self.stream.shards[sid].append(r["Data"])
        return {"FailedRecordCount": 0}


# ---------------------------------------------------------------------------
# NATS / JetStream (nats-py surface subset the source/sink use)
# ---------------------------------------------------------------------------


class FakeNatsServer:
    """Subject log; install via sys.modules['nats'] = server.module().
    JetStream subscriptions replay from opt_start_seq and tag messages
    with stream sequence metadata; core subscriptions only see messages
    published after subscribe."""

    def __init__(self):
        self.log: List[bytes] = []
        self.stop_at: Optional[int] = None  # sub iterator end (for tests)

    def publish(self, payload: bytes):
        self.log.append(payload)

    def module(self):
        server = self

        class _NatsModule:
            @staticmethod
            async def connect(servers):
                return _FakeNatsConn(server)

        return _NatsModule


class _Seq:
    def __init__(self, stream):
        self.stream = stream


class _Meta:
    def __init__(self, seq):
        self.sequence = _Seq(seq)


class _FakeNatsMsg:
    def __init__(self, data: bytes, seq: int):
        self.data = data
        self.metadata = _Meta(seq)


class _FakeSub:
    def __init__(self, server: FakeNatsServer, start: int):
        self.server = server
        self.pos = start

    @property
    def messages(self):
        sub = self

        class _Iter:
            def __aiter__(self):
                return self

            async def __anext__(self):
                import asyncio

                while True:
                    if (
                        sub.server.stop_at is not None
                        and sub.pos >= sub.server.stop_at
                    ):
                        raise StopAsyncIteration
                    if sub.pos < len(sub.server.log):
                        m = _FakeNatsMsg(
                            sub.server.log[sub.pos], sub.pos + 1
                        )  # stream seqs are 1-based
                        sub.pos += 1
                        return m
                    await asyncio.sleep(0.005)

        return _Iter()


class _FakeJetStream:
    def __init__(self, server: FakeNatsServer):
        self.server = server

    async def subscribe(self, subject, opt_start_seq: int = 1, **kw):
        return _FakeSub(self.server, max(0, opt_start_seq - 1))


class _FakeNatsConn:
    def __init__(self, server: FakeNatsServer):
        self.server = server

    def jetstream(self):
        return _FakeJetStream(self.server)

    async def subscribe(self, subject):
        return _FakeSub(self.server, len(self.server.log))

    async def publish(self, subject, payload: bytes):
        self.server.publish(payload)

    async def close(self):
        pass


class FakeMqttBroker:
    """In-memory broker emulating the aiomqtt surface the connector uses:
    async-context Client, subscribe, a messages iterator, publish capture,
    MqttError-driven disconnects, and durable-session resume (delivery
    position kept per client_id when clean_session=False)."""

    def __init__(self):
        self.queue: List[tuple] = []  # (topic, payload, qos) to deliver
        self.published: List[tuple] = []  # sink capture: (topic, payload, qos, retain)
        self.sessions: Dict[str, int] = {}  # client_id -> delivered pos
        self.drop_after: Optional[int] = None  # raise MqttError after N deliveries
        self.stop_at: Optional[int] = None  # StopAsyncIteration bound (tests)
        self.connects = 0

    def preload(self, topic: str, payloads: List[bytes], qos: int = 1):
        for p in payloads:
            self.queue.append((topic, p, qos))

    def module(self):
        broker = self

        class MqttError(Exception):
            pass

        class _Module:
            pass

        def Client(url, identifier=None, clean_session=True, username=None,
                   password=None):
            return _FakeMqttClient(broker, identifier, clean_session,
                                   MqttError)

        _Module.MqttError = MqttError
        _Module.Client = staticmethod(Client)
        return _Module


class _FakeMqttTopic:
    def __init__(self, value):
        self.value = value

    def __str__(self):
        return self.value


class _FakeMqttMessage:
    def __init__(self, topic, payload, qos):
        self.topic = _FakeMqttTopic(topic)
        self.payload = payload
        self.qos = qos
        self.retain = False


class _FakeMqttClient:
    def __init__(self, broker, client_id, clean_session, err_cls):
        self.broker = broker
        self.client_id = client_id
        self.clean_session = clean_session
        self.err_cls = err_cls
        self.delivered = 0

    async def __aenter__(self):
        self.broker.connects += 1
        return self

    async def __aexit__(self, *exc):
        return False

    async def subscribe(self, topic, qos=0):
        self.topic = topic
        if self.client_id and not self.clean_session:
            self.pos = self.broker.sessions.get(self.client_id, 0)
        else:
            self.pos = 0

    async def publish(self, topic, payload, qos=0, retain=False):
        self.broker.published.append((topic, payload, qos, retain))

    @property
    def messages(self):
        client = self

        class _Iter:
            def __aiter__(self):
                return self

            async def __anext__(self):
                import asyncio

                b = client.broker
                while True:
                    if (
                        b.drop_after is not None
                        and client.delivered >= b.drop_after
                    ):
                        b.drop_after = None
                        raise client.err_cls("connection lost")
                    if b.stop_at is not None and client.pos >= b.stop_at:
                        raise StopAsyncIteration
                    if client.pos < len(b.queue):
                        topic, payload, qos = b.queue[client.pos]
                        client.pos += 1
                        client.delivered += 1
                        if client.client_id and not client.clean_session:
                            b.sessions[client.client_id] = client.pos
                        return _FakeMqttMessage(topic, payload, qos)
                    await asyncio.sleep(0.005)

        return _Iter()


class FakeRabbit:
    """aio-pika surface subset: robust connection, channel with qos,
    durable queue with an async iterator, default/named exchange publish
    capture, message.process() ack tracking."""

    def __init__(self):
        self.queue_msgs: List[bytes] = []
        self.published: List[tuple] = []  # (exchange, routing_key, body)
        self.acked = 0
        self.prefetch = None
        self.stop_at: Optional[int] = None

    def module(self):
        rabbit = self

        class _Msg:
            def __init__(self, body, delivery_mode=None):
                self.body = body
                self.delivery_mode = delivery_mode

        class _DeliveryMode:
            PERSISTENT = 2

        class _Module:
            Message = _Msg
            DeliveryMode = _DeliveryMode

            @staticmethod
            async def connect_robust(url):
                return _FakeRabbitConn(rabbit, _Msg)

        return _Module


class _FakeRabbitConn:
    def __init__(self, rabbit, msg_cls):
        self.rabbit = rabbit
        self.msg_cls = msg_cls

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False

    async def close(self):
        pass

    async def channel(self):
        return _FakeRabbitChannel(self.rabbit)


class _FakeRabbitChannel:
    def __init__(self, rabbit):
        self.rabbit = rabbit
        self.default_exchange = _FakeExchange(rabbit, "")

    async def set_qos(self, prefetch_count=None):
        self.rabbit.prefetch = prefetch_count

    async def get_exchange(self, name):
        return _FakeExchange(self.rabbit, name)

    async def declare_queue(self, name, durable=False):
        return _FakeRabbitQueue(self.rabbit)


class _FakeExchange:
    def __init__(self, rabbit, name):
        self.rabbit = rabbit
        self.name = name

    async def publish(self, msg, routing_key=None):
        self.rabbit.published.append((self.name, routing_key, msg.body))


class _FakeIncoming:
    def __init__(self, rabbit, body):
        self.rabbit = rabbit
        self.body = body

    async def ack(self):
        self.rabbit.acked += 1

    def process(self):
        incoming = self

        class _Ctx:
            async def __aenter__(self):
                return incoming

            async def __aexit__(self, *exc):
                incoming.rabbit.acked += 1
                return False

        return _Ctx()


class _FakeRabbitQueue:
    def __init__(self, rabbit):
        self.rabbit = rabbit

    def iterator(self):
        rabbit = self.rabbit

        class _It:
            def __init__(self):
                self.pos = 0

            async def __aenter__(self):
                return self

            async def __aexit__(self, *exc):
                return False

            def __aiter__(self):
                return self

            async def __anext__(self):
                import asyncio

                while True:
                    if (
                        rabbit.stop_at is not None
                        and self.pos >= rabbit.stop_at
                    ):
                        raise StopAsyncIteration
                    if self.pos < len(rabbit.queue_msgs):
                        body = rabbit.queue_msgs[self.pos]
                        self.pos += 1
                        return _FakeIncoming(rabbit, body)
                    await asyncio.sleep(0.005)

        return _It()


class FakeRedisServer:
    """Dict-backed redis: string/list/hash targets + GET with a call
    counter (the lookup-join cache test asserts on it)."""

    def __init__(self):
        self.strings: Dict[str, bytes] = {}
        self.lists: Dict[str, List[bytes]] = {}
        self.hashes: Dict[str, Dict[str, bytes]] = {}
        self.get_calls = 0
        self.lock = threading.Lock()

    def make_module(self):
        server = self

        class _Pipe:
            def __init__(self):
                self.ops = []

            def set(self, k, v):
                self.ops.append(("set", k, v))

            def rpush(self, k, v):
                self.ops.append(("rpush", k, v))

            def hset(self, k, f, v):
                self.ops.append(("hset", k, f, v))

            def execute(self):
                with server.lock:
                    for op in self.ops:
                        if op[0] == "set":
                            server.strings[op[1]] = _b(op[2])
                        elif op[0] == "rpush":
                            server.lists.setdefault(op[1], []).append(
                                _b(op[2])
                            )
                        else:
                            server.hashes.setdefault(op[1], {})[
                                op[2]
                            ] = _b(op[3])
                self.ops = []

        def _b(v):
            return v if isinstance(v, bytes) else str(v).encode()

        class _Client:
            def pipeline(self):
                return _Pipe()

            def set(self, k, v):
                with server.lock:
                    server.strings[k] = _b(v)

            def get(self, k):
                with server.lock:
                    server.get_calls += 1
                    return server.strings.get(k)

        class Redis:
            @classmethod
            def from_url(cls, url):
                return _Client()

        class _Module:
            pass

        _Module.Redis = Redis
        return _Module


class FakeFluvioCluster:
    """Partitioned topic logs with a BLOCKING consumer stream (like the
    real client): the iterator waits for new records instead of ending,
    so sources stop via engine control, and resume is offset-driven."""

    def __init__(self, partitions: int = 1):
        self.partitions = partitions
        self.logs: Dict[tuple, List[bytes]] = {}
        self.cond = threading.Condition()

    def append(self, topic: str, partition: int, value: bytes):
        with self.cond:
            self.logs.setdefault((topic, partition), []).append(value)
            self.cond.notify_all()

    def records(self, topic: str, partition: int) -> List[bytes]:
        with self.cond:
            return list(self.logs.get((topic, partition), []))

    def make_module(self):
        cluster = self

        class _Record:
            def __init__(self, off, val):
                self._off = off
                self._val = val

            def value(self):
                return self._val

            def offset(self):
                return self._off

        class Offset:
            @staticmethod
            def absolute(n):
                return int(n)

        class _Consumer:
            def __init__(self, topic, partition):
                self.topic = topic
                self.partition = partition

            def stream(self, offset):
                i = int(offset)
                while True:
                    with cluster.cond:
                        log = cluster.logs.get(
                            (self.topic, self.partition), []
                        )
                        if i >= len(log):
                            cluster.cond.wait(timeout=0.05)
                            continue
                        val = log[i]
                    yield _Record(i, val)
                    i += 1

        class _Producer:
            def __init__(self, topic):
                self.topic = topic

            def send(self, key, value):
                cluster.append(
                    self.topic, 0,
                    value if isinstance(value, bytes) else value.encode(),
                )

        class _Conn:
            def partition_consumer(self, topic, partition):
                return _Consumer(topic, partition)

            def topic_producer(self, topic):
                return _Producer(topic)

        class Fluvio:
            @staticmethod
            def connect():
                return _Conn()

        class _Module:
            pass

        _Module.Fluvio = Fluvio
        _Module.Offset = Offset
        return _Module
