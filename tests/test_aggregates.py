"""Accumulator kernels: jax (device) vs numpy (host) vs pandas golden."""

import numpy as np
import pandas as pd
import pytest

from arroyo_tpu.ops.aggregates import AggSpec, make_accumulator
from arroyo_tpu.ops.directory import SlotDirectory

SPECS = [
    AggSpec("count", None, "cnt"),
    AggSpec("sum", 0, "total"),
    AggSpec("min", 1, "lo", is_float=True),
    AggSpec("max", 1, "hi", is_float=True),
    AggSpec("avg", 1, "mean", is_float=True),
]


def golden(bins, keys, ints, floats):
    df = pd.DataFrame({"b": bins, "k": keys, "i": ints, "f": floats})
    g = df.groupby(["b", "k"])
    return pd.DataFrame(
        {
            "cnt": g.size(),
            "total": g["i"].sum(),
            "lo": g["f"].min(),
            "hi": g["f"].max(),
            "mean": g["f"].mean(),
        }
    )


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_accumulator_matches_pandas(backend):
    rng = np.random.default_rng(42)
    n = 5000
    bins = rng.integers(0, 4, n)
    keys = rng.integers(0, 17, n)
    ints = rng.integers(-100, 100, n)
    floats = rng.random(n) * 100
    acc = make_accumulator(SPECS, capacity=64, backend=backend)
    d = SlotDirectory()
    # feed in several batches to exercise slot reuse and growth
    for lo in range(0, n, 1234):
        hi = min(lo + 1234, n)
        slots = d.assign(bins[lo:hi], [keys[lo:hi]])
        if d.required_capacity() > acc.capacity - 1:
            acc.grow(d.required_capacity() + 1)
        acc.update(slots, {0: ints[lo:hi], 1: floats[lo:hi]})
    want = golden(bins, keys, ints, floats)
    for b in d.live_bins():
        got_keys, slots = d.take_bin(b)
        cols = acc.finalize(acc.gather(slots))
        for key, cnt, total, lo_, hi_, mean in zip(
            got_keys, cols[0], cols[1], cols[2], cols[3], cols[4]
        ):
            row = want.loc[(b, key[0])]
            assert cnt == row["cnt"]
            assert total == row["total"]  # exact int arithmetic
            assert lo_ == pytest.approx(row["lo"])
            assert hi_ == pytest.approx(row["hi"])
            assert mean == pytest.approx(row["mean"])


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_slot_reuse_after_reset(backend):
    acc = make_accumulator([AggSpec("sum", 0, "s")], capacity=8, backend=backend)
    d = SlotDirectory()
    slots = d.assign(np.array([1, 1]), [np.array([7, 7])])
    acc.update(slots, {0: np.array([10, 20])})
    _, taken = d.take_bin(1)
    assert acc.finalize(acc.gather(taken))[0][0] == 30
    acc.reset_slots(taken)
    # the freed slot must start clean for a new group
    slots2 = d.assign(np.array([2]), [np.array([9])])
    assert slots2[0] == taken[0]  # reused
    acc.update(slots2, {0: np.array([5])})
    assert acc.finalize(acc.gather(slots2))[0][0] == 5


def test_jax_numpy_bit_identical():
    rng = np.random.default_rng(0)
    n = 2000
    bins = rng.integers(0, 3, n)
    keys = rng.integers(0, 11, n)
    ints = rng.integers(-(2**40), 2**40, n)  # exercise >32-bit sums
    accs = {}
    for backend in ("numpy", "jax"):
        acc = make_accumulator(
            [AggSpec("sum", 0, "s"), AggSpec("count", None, "c")],
            capacity=64,
            backend=backend,
        )
        d = SlotDirectory()
        slots = d.assign(bins, [keys])
        if d.required_capacity() > acc.capacity - 1:
            acc.grow(d.required_capacity() + 1)
        acc.update(slots, {0: ints})
        out = {}
        for b in d.live_bins():
            ks, sl = d.take_bin(b)
            cols = acc.finalize(acc.gather(sl))
            for k, s, c in zip(ks, cols[0], cols[1]):
                out[(b, k[0])] = (int(s), int(c))
        accs[backend] = out
    assert accs["numpy"] == accs["jax"]


def test_directory_growth_and_scratch():
    acc = make_accumulator([AggSpec("count", None, "c")], capacity=4,
                           backend="numpy")
    d = SlotDirectory()
    slots = d.assign(np.zeros(100, dtype=np.int64),
                     [np.arange(100, dtype=np.int64)])
    acc.grow(d.required_capacity() + 1)
    acc.update(slots, {})
    ks, sl = d.take_bin(0)
    assert len(ks) == 100
    assert all(c == 1 for c in acc.finalize(acc.gather(sl))[0])


def test_count_distinct_excludes_nulls():
    from arroyo_tpu.ops.aggregates import AggSpec, make_accumulator

    acc = make_accumulator(
        [AggSpec("count_distinct", 0, "d")], backend="numpy"
    )
    slots = np.zeros(5, dtype=np.int64)
    vals = np.array(["a", None, "b", None, "a"], dtype=object)
    acc.update(slots, {0: vals})
    acc.gather(np.array([0]))
    assert acc.finalize([])[0].tolist() == [2]  # NULLs excluded


def test_count_distinct_raw_precision_beyond_2_53():
    """A BIGINT column shared with a float-cast spec must reach the
    multiset uncast: 2^53 and 2^53+1 are equal as float64."""
    from arroyo_tpu.ops.aggregates import AggSpec, make_accumulator

    acc = make_accumulator(
        [AggSpec("avg", 0, "a", is_float=True),
         AggSpec("count_distinct", 0, "d")],
        backend="numpy",
    )
    big = np.array([2**53, 2**53 + 1], dtype=np.int64)
    acc.update(np.zeros(2, dtype=np.int64),
               {0: big.astype(np.float64), ("raw", 0): big})
    acc.gather(np.array([0]))
    out = acc.finalize(acc.gather(np.array([0])))
    assert out[1].tolist() == [2], "distinct collapsed via float64 keys"


def test_count_distinct_multiset_snapshot_roundtrip_ragged():
    """Slots with different numbers of distinct values snapshot as ragged
    object columns and must restore exactly."""
    from arroyo_tpu.ops.aggregates import AggSpec, make_accumulator

    acc = make_accumulator(
        [AggSpec("count_distinct", 0, "d")], backend="numpy"
    )
    slots = np.array([0, 0, 1, 1, 1], dtype=np.int64)
    vals = np.array(["x", "y", "p", "q", "r"], dtype=object)
    acc.update(slots, {0: vals})
    snap = acc.snapshot(np.array([0, 1]))
    acc2 = make_accumulator(
        [AggSpec("count_distinct", 0, "d")], backend="numpy"
    )
    acc2.restore(np.array([0, 1]), snap)
    acc2.gather(np.array([0, 1]))
    assert acc2.finalize([])[0].tolist() == [2, 3]


def test_32bit_device_accumulators_exact():
    """The opt-in 32-bit device mode (TPU v5e has no native int64)
    produces identical results for count/min/max/avg at 32-bit-safe
    magnitudes."""
    import numpy as np

    from arroyo_tpu.config import config
    from arroyo_tpu.ops.aggregates import AggSpec, make_accumulator

    specs = [
        AggSpec("count", None, "c"),
        AggSpec("min", 0, "mn"),
        AggSpec("max", 0, "mx"),
        AggSpec("avg", 0, "a", is_float=True),
    ]
    config().tpu.use_32bit_accumulators = True
    try:
        acc = make_accumulator(specs, capacity=64, backend="jax")
        assert acc.use32
        vals = np.array([5, -3, 1000000, 7, -3], dtype=np.int64)
        slots = np.array([1, 1, 2, 2, 1], dtype=np.int64)
        acc.update(slots, {0: vals.astype(np.float64)})
        out = acc.finalize(acc.gather(np.array([1, 2])))
        assert list(out[0]) == [3, 2]              # counts
        assert list(out[1]) == [-3, 7]             # mins
        assert list(out[2]) == [5, 1000000]        # maxes
        assert np.allclose(out[3], [(5 - 3 - 3) / 3, 1000007 / 2])
    finally:
        config().tpu.use_32bit_accumulators = False
