"""Node daemon: a long-lived per-machine agent that spawns worker
processes on demand.

Capability parity with the reference's node scheduler
(/root/reference/crates/arroyo-controller/src/schedulers/mod.rs node +
crates/arroyo-node): `arroyo-tpu node` registers its slot capacity with
the controller; the controller's NodeScheduler places workers on
registered nodes (most-free-slots first) via StartWorkers/StopWorkers
RPCs, and the node forks `arroyo-tpu worker` subprocesses.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
from typing import Dict, List, Optional

from ..config import config
from ..engine.rpc import RpcClient, RpcServer
from ..utils.logging import get_logger

logger = get_logger("node")

# worker ids must be unique ACROSS node daemons (the controller keys
# workers by id): derive the base from this daemon's pid
_next_node_worker_id = 3_000_000 + (os.getpid() % 100_000) * 100


class NodeServer:
    def __init__(self, controller_addr: str, node_id: Optional[str] = None,
                 slots: Optional[int] = None, bind: str = "127.0.0.1",
                 extra_env: Optional[dict] = None):
        self.controller_addr = controller_addr
        self.node_id = node_id or f"node-{os.getpid()}"
        self.slots = slots or config().worker.task_slots
        self.bind = bind
        self.extra_env = extra_env or {}
        self.rpc = RpcServer(bind)
        self.controller: Optional[RpcClient] = None
        # job_id -> worker subprocesses started for it
        self.procs: Dict[str, List[subprocess.Popen]] = {}
        self._stop = asyncio.Event()

    async def start(self) -> "NodeServer":
        self.rpc.add_service(
            "NodeGrpc",
            {
                "StartWorkers": self.start_workers,
                "StopWorkers": self.stop_workers,
            },
        )
        port = await self.rpc.start()
        self.addr = f"{self.bind}:{port}"
        self.controller = RpcClient(self.controller_addr)
        await self.controller.call(
            "ControllerGrpc", "RegisterNode",
            {"node_id": self.node_id, "addr": self.addr,
             "slots": self.slots},
        )
        logger.info("node %s up at %s (%d slots)", self.node_id, self.addr,
                    self.slots)
        return self

    async def start_workers(self, req: dict) -> dict:
        global _next_node_worker_id

        from .scheduler import spawn_worker

        job_id = req["job_id"]
        started = []
        for _ in range(req.get("n", 1)):
            wid = _next_node_worker_id
            _next_node_worker_id += 1
            # multi-host mesh: the scheduler rides the per-worker rank
            # assignment (ARROYO__TPU__MESH_*) in the RPC so the worker's
            # ensure_initialized() joins the job's global mesh
            env = dict(self.extra_env or {})
            env.update(req.get("extra_env") or {})
            p = spawn_worker(
                req.get("controller_addr", self.controller_addr), wid,
                extra_env=env,
            )
            self.procs.setdefault(job_id, []).append(p)
            started.append(wid)
        logger.info("node %s started workers %s for job %s", self.node_id,
                    started, job_id)
        return {"worker_ids": started}

    async def stop_workers(self, req: dict) -> dict:
        from .scheduler import terminate_procs

        procs = self.procs.pop(req["job_id"], [])
        await terminate_procs(procs, req.get("force", False))
        return {"stopped": len(procs)}

    async def run_forever(self):
        await self._stop.wait()

    async def stop(self):
        for job_id in list(self.procs):
            await self.stop_workers({"job_id": job_id, "force": True})
        if self.controller is not None:
            await self.controller.close()
        await self.rpc.stop()
        self._stop.set()
