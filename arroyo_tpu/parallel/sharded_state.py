"""Mesh-sharded window state: the multi-chip execution path.

The reference scales keyed aggregation by running parallel subtasks wired
with a TCP shuffle (/root/reference/crates/arroyo-worker/src/
network_manager.rs; engine.rs:209-365 is the subtask wiring). The
TPU-native equivalent keeps ALL key shards' accumulator state resident on
a device mesh — window/join accumulator state never leaves HBM between
micro-batches — and replaces the network shuffle with an exchange tier
chosen per deployment (`tpu.mesh_exchange`, default `auto`):

    host: rows -> global slots   [MeshSlotDirectory: hash keys to an
                                  owning shard (splitmix64, the same
                                  routing contract as the host shuffle
                                  and `device_owners_for` below);
                                  per-shard directories assign locals]

  * `device` — the GSPMD device-resident keyed exchange (real chip
    meshes). ONE fused route+scatter+reduce jitted program takes the
    src-major packed buffer ([S, C]: rows chopped positionally across
    source shards, `NamedSharding` over the 1-D "keys" mesh), derives
    each row's owner shard from its global slot ON DEVICE, positions
    rows into the [S, R] all_to_all cells with a one-hot rank cumsum,
    exchanges them over ICI (`jax.lax.all_to_all` — the collective XLA
    compiles into the step), and scatter-reduces into the local state
    shard. Duplicate slots reduce IN the scatter, so the steady-state
    path has NO host combiner: host work per flush is a concatenate,
    a pad and a bincount (cell-rung sizing).

  * `host_fed` — the fallback exchange (multi-process meshes without
    ICI collectives, and single-process VIRTUAL meshes — see below).
    Rows are pre-reduced by the host combiner (one row per touched
    slot per flush) and hash-routed at packing time into a dst-major
    [S, R] buffer: the sharded host->device transfer IS the shuffle
    and the step has no collective at all.

  * `a2a` — the legacy host-packed [S, S, R] src-major layout with the
    in-step all_to_all (kept for device-resident producers that are
    already sharded by source, and as the shard_map exchange tier the
    multihost tests drive).

    emission: jitted (shard, slot) gather -> host, once per watermark
    wave, chunked at `tpu.mesh_emission_chunk` and padded on the
    sticky emission rung ladder (see _StickyRung).

Why `auto` resolves to `host_fed` on a virtual mesh: under
`--xla_force_host_platform_device_count=N` every "device" is the same
host CPU — the all_to_all is a memcpy between buffers of one process,
XLA-CPU scatters execute serially, and S shards' route work shares one
core, so on-device routing costs strictly more than routing in the
packing pass while buying zero parallelism. On a real chip mesh the
same routing is S-way parallel and the collective rides ICI, which is
where the `device` tier wins (and why it is the default there).

Shape discipline (the round-11 ledger's lesson — 52 XLA compiles cost
1.7s of a 2.4s mesh run): jitted programs are cached PROCESS-WIDE and
shared by every accumulator with the same physical layout (the two
identical hop-count stages of nexmark q5 trace one program set, not
two), and all padding rungs are chosen by sticky hysteresis ladders
(_StickyRung) so steady state locks onto one shape per program instead
of re-specializing on every flush's row-count wander.

This is an *engine execution mode*, not a demo: window operators
construct this pair when `tpu.mesh_devices >= 2` (operators/windows.py)
and run their normal assign/update/gather/checkpoint protocol against
it — global slots encode (shard, local slot) so every Accumulator API
carries over, and checkpoint capture (snapshot/gather) flushes pending
micro-batched rows before reading, which keeps chaos drills
byte-identical across exchange tiers.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import device as obs_device
from ..ops.aggregates import (
    Accumulator,
    AggSpec,
    _bucket,
    _neutral,
)
from ..ops.directory import SlotDirectory
from ..types import hash_arrays, hash_column, server_for_hash_array

# global slot encoding: slot = shard * STRIDE + local. The stride is fixed
# (not the current capacity) so capacity growth never re-numbers live slots.
STRIDE = 1 << 32

# process-wide packed-exchange traffic diagnostics (direct [S, R] or
# all_to_all [S, S, R] layout, whichever each update used), aggregated
# across every ShardedAccumulator instance; bench --mesh reads these to
# report the padding overhead of the host->device/ICI row shipment and
# the dispatch amortization (device steps per engine update call).
# flushes_elided counts state reads that skipped the pre-read flush
# because no pending update row touched the slots being read.
MESH_STATS = {"rows_sent": 0, "rows_padded": 0,
              "dispatches": 0, "updates": 0, "flushes_elided": 0,
              "rows_combined": 0}


class MeshSlotDirectory:
    """SlotDirectory facade over per-shard directories: keys hash to an
    owning shard (same splitmix64 hashing as the host shuffle), the shard's
    directory assigns a local slot, and callers see global slots.

    Per-shard directories default to the python SlotDirectory; operators
    whose keys flatten to int64 words swap them to the native C++ table
    (`swap_to_native`) — round-5 mesh profile showed the python per-shard
    assigns + tuple-per-key emission as the largest host cost on the
    mesh path. Session windows keep python shards (imperative
    alloc_slot/free lists live there)."""

    def __init__(self, n_shards: int):
        self.n_shards = n_shards
        self.dirs = [SlotDirectory() for _ in range(n_shards)]
        self._native = False

    def swap_to_native(self, native_mod, n_keys: int) -> bool:
        """Replace the per-shard python directories with C++ tables
        (callable only while empty). Returns True on swap."""
        if native_mod is None or any(d.n_live for d in self.dirs):
            return False
        from ..ops.native import NativeSlotDirectory

        self.dirs = [
            NativeSlotDirectory(native_mod, n_keys=n_keys)
            for _ in range(self.n_shards)
        ]
        self._native = True
        # bound as instance attributes so the window operators' array
        # fast paths (attribute probes) engage exactly when arrays exist
        self.take_bin_arrays = self._take_bin_arrays
        self.bin_entries_multi = self._bin_entries_multi
        return True

    @property
    def n_live(self) -> int:
        return sum(d.n_live for d in self.dirs)

    @property
    def by_bin(self):
        # truthiness/membership probe ("anything live?", "which bins?") —
        # values are True like the native directory, not per-key maps, so
        # the per-watermark check stays O(bins) not O(keys)
        return {b: True for d in self.dirs for b in d.by_bin}

    def required_capacity(self) -> int:
        """Per-shard capacity needed (max across shards, + scratch)."""
        return max(d.required_capacity() for d in self.dirs)

    def owners_for(self, key_cols: List[np.ndarray], n_rows: int) -> np.ndarray:
        if not key_cols:
            return np.zeros(n_rows, dtype=np.int64)
        return server_for_hash_array(
            hash_arrays([hash_column(c) for c in key_cols]), self.n_shards
        )

    def assign(
        self, bins: np.ndarray, key_cols: List[np.ndarray]
    ) -> np.ndarray:
        n = len(bins)
        if n == 0:
            return np.empty(0, dtype=np.int64)
        owners = self.owners_for(key_cols, n)
        out = np.empty(n, dtype=np.int64)
        for shard in range(self.n_shards):
            sel = np.nonzero(owners == shard)[0]
            if len(sel) == 0:
                continue
            local = self.dirs[shard].assign(
                bins[sel], [c[sel] for c in key_cols]
            )
            out[sel] = shard * STRIDE + local
        return out

    def bins_up_to(self, bin_exclusive: int) -> List[int]:
        bins = set()
        for d in self.dirs:
            bins.update(b for b in d.by_bin if b < bin_exclusive)
        return sorted(bins)

    def live_bins(self) -> List[int]:
        bins = set()
        for d in self.dirs:
            bins.update(d.by_bin)
        return sorted(bins)

    def peek_bin(self, b: int) -> Optional[dict]:
        out = {}
        for shard, d in enumerate(self.dirs):
            m = d.peek_bin(b)
            if m:
                for key, slot in m.items():
                    out[key] = shard * STRIDE + slot
        return out or None

    def bin_entries(self, b: int):
        if self._native:
            # native shards return int64 key MATRICES — concatenating
            # them keeps the emission path vectorized end to end (the
            # sliding merge branches on ndarray keys)
            mats: List[np.ndarray] = []
            slot_chunks = []
            for shard, d in enumerate(self.dirs):
                kmat, s = d.bin_entries(b)
                if len(s):
                    mats.append(kmat)
                    slot_chunks.append(s + shard * STRIDE)
            if not slot_chunks:
                return (np.empty((0, self.dirs[0]._stride), dtype=np.int64),
                        np.empty(0, dtype=np.int64))
            return np.concatenate(mats), np.concatenate(slot_chunks)
        keys: List[tuple] = []
        slot_chunks = []
        for shard, d in enumerate(self.dirs):
            k, s = d.bin_entries(b)
            keys.extend(k)
            slot_chunks.append(s + shard * STRIDE)
        return keys, (
            np.concatenate(slot_chunks)
            if slot_chunks
            else np.empty(0, dtype=np.int64)
        )

    def take_bin(self, b: int) -> Tuple[List[tuple], np.ndarray]:
        keys: List[tuple] = []
        slot_chunks: List[np.ndarray] = []
        for shard, d in enumerate(self.dirs):
            k, s = d.take_bin(b)
            keys.extend(k)
            slot_chunks.append(s + shard * STRIDE)
        return keys, (
            np.concatenate(slot_chunks)
            if slot_chunks
            else np.empty(0, dtype=np.int64)
        )

    def _take_bin_arrays(self, b: int):
        """Vectorized take (native shards only — bound as
        `take_bin_arrays` by swap_to_native so the attribute probe in
        the window watermark path engages exactly when arrays exist).
        One C call per shard; outputs fill preallocated buffers."""
        per_shard: List[tuple] = []  # (shard, key cols, local slots)
        total = 0
        for shard, d in enumerate(self.dirs):
            cols, s = d.take_bin_arrays(b)
            if len(s):
                per_shard.append((shard, cols, s))
                total += len(s)
        stride = self.dirs[0]._stride
        if not per_shard:
            z = np.empty(0, dtype=np.int64)
            return [z for _ in range(stride)], z
        out_cols = [np.empty(total, dtype=np.int64) for _ in range(stride)]
        out_slots = np.empty(total, dtype=np.int64)
        off = 0
        for shard, cols, s in per_shard:
            n = len(s)
            for j, c in enumerate(cols):
                out_cols[j][off:off + n] = c
            np.add(s, shard * STRIDE, out=out_slots[off:off + n])
            off += n
        return out_cols, out_slots

    def _bin_entries_multi(self, bins) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenated (key matrix, global slots) over SEVERAL bins in
        one native C call per shard (the sliding merge reads width/slide
        bins per emission; per-bin calls cost S x k crossings). Native
        shards only — bound by swap_to_native like take_bin_arrays."""
        bins_arr = np.ascontiguousarray(np.asarray(bins, dtype=np.int64))
        mats: List[np.ndarray] = []
        slot_chunks: List[np.ndarray] = []
        for shard, d in enumerate(self.dirs):
            kmat, s = d.bin_entries_multi(bins_arr)
            if len(s):
                mats.append(kmat)
                slot_chunks.append(s + shard * STRIDE)
        if not slot_chunks:
            return (np.empty((0, self.dirs[0]._stride), dtype=np.int64),
                    np.empty(0, dtype=np.int64))
        return np.concatenate(mats), np.concatenate(slot_chunks)

    def items(self):
        for shard, d in enumerate(self.dirs):
            base = shard * STRIDE
            if self._native:
                # one C call per shard; tuple building and iteration
                # stay in C-level passes (_rows_to_tuples + zip)
                bins, kmat, slots = d.entries_arrays()
                yield from zip(bins.tolist(), d._rows_to_tuples(kmat),
                               (slots + base).tolist())
            else:
                for b, key, slot in d.items():
                    yield b, key, base + slot

    def keys_for_slots(self, slots: np.ndarray):
        """(bin, key) per global slot via the shard directories' reverse
        maps (updating-aggregate dirty tracking); dispatched per shard so
        native shards answer in one C call, results scattered back with
        one object-array assignment per shard."""
        slots = np.asarray(slots, dtype=np.int64)
        out = np.empty(len(slots), dtype=object)
        shards = slots // STRIDE
        locs = slots % STRIDE
        for shard in range(self.n_shards):
            idx = np.nonzero(shards == shard)[0]
            if not len(idx):
                continue
            res = self.dirs[shard].keys_for_slots(locs[idx])
            # element-wise object fill (a bare out[idx] = res would let
            # numpy reshape the (bin, key) 2-tuples into a 2-D array)
            tmp = np.empty(len(res), dtype=object)
            tmp[:] = res
            out[idx] = tmp
        return out.tolist()

    def slots_for_keys(self, b: int, keys: List[tuple]) -> Dict[tuple, int]:
        """Point lookups across shards: each key lives on exactly one
        shard, so probe all shards with the full list and merge (native
        shards share ONE key matrix and answer in one C lookup each; the
        merge is a zip over the hit indices, no per-key method calls)."""
        if not keys:
            return {}
        out: Dict[tuple, int] = {}
        if self._native:
            flat = np.ascontiguousarray(
                self.dirs[0]._keys_to_matrix(keys).reshape(-1)
            )
            for shard, d in enumerate(self.dirs):
                present, slots_raw = d._d.lookup(int(b), flat)
                pres = np.frombuffer(present, dtype=np.uint8)
                hit = np.nonzero(pres)[0]
                if not len(hit):
                    continue
                gslots = np.frombuffer(slots_raw, dtype=np.int64)[hit]
                out.update(zip(
                    (keys[i] for i in hit.tolist()),
                    (gslots + shard * STRIDE).tolist(),
                ))
            return out
        for shard, d in enumerate(self.dirs):
            sub = d.slots_for_keys(b, keys)
            if sub:
                base = shard * STRIDE
                out.update((k, base + int(v)) for k, v in sub.items())
        return out

    def remove(self, b: int, keys: List[tuple]) -> np.ndarray:
        """Remove keys from a bin across shards; each key lives in exactly
        one shard, so per-shard removal of the full list is safe. Native
        shards share one key matrix (built once, one C call per shard).
        Returns freed GLOBAL slots."""
        if not keys:
            return np.empty(0, dtype=np.int64)
        freed = []
        if self._native:
            flat = np.ascontiguousarray(
                self.dirs[0]._keys_to_matrix(keys).reshape(-1)
            )
            for shard, d in enumerate(self.dirs):
                f = np.frombuffer(d._d.remove(int(b), flat), dtype=np.int64)
                if len(f):
                    freed.append(f + shard * STRIDE)
        else:
            for shard, d in enumerate(self.dirs):
                f = d.remove(b, keys)
                if len(f):
                    freed.append(f + shard * STRIDE)
        return (
            np.concatenate(freed) if freed else np.empty(0, dtype=np.int64)
        )

    # -- imperative slot allocation (session windows) -----------------------

    def alloc_slot(self, shard_hint: int) -> int:
        """Allocate one slot on a shard (round-robin hint from the caller);
        session bookkeeping assigns slots imperatively rather than through
        assign(). Python shards only (sessions never swap to native —
        the imperative free lists live in the python directory)."""
        if self._native:
            raise RuntimeError(
                "imperative slot allocation requires python shards"
            )
        d = self.dirs[shard_hint % self.n_shards]
        local = d.free.pop() if d.free else d._alloc()
        return (shard_hint % self.n_shards) * STRIDE + local

    def alloc_slots(self, n: int, shard_hint: int = 0) -> np.ndarray:
        """Vectorized round-robin block allocation: one call allocates n
        slots dealt evenly across shards (the session operator's slot
        pool refill — replaces one Python alloc_slot call per session)."""
        shards = (np.arange(n, dtype=np.int64) + shard_hint) % self.n_shards
        out = np.empty(n, dtype=np.int64)
        for shard in range(self.n_shards):
            idx = np.nonzero(shards == shard)[0]
            if not len(idx):
                continue
            block = self.dirs[shard].alloc_block(len(idx))
            out[idx] = np.asarray(block, dtype=np.int64) + shard * STRIDE
        return out

    def free_slot(self, slot: int):
        self.dirs[int(slot) // STRIDE].free.append(int(slot) % STRIDE)

    def free_slots(self, slots: np.ndarray):
        """Batch free: one list-extend per shard (session expiry waves
        and the session operator's slot-pool return at checkpoint)."""
        slots = np.asarray(slots, dtype=np.int64)
        if not len(slots):
            return
        shards = slots // STRIDE
        locs = slots % STRIDE
        for shard in range(self.n_shards):
            sel = np.nonzero(shards == shard)[0]
            if len(sel):
                self.dirs[shard].free.extend(locs[sel].tolist())


def _pow2_ladder(cap: int, floor: int = 16, fine_from: int = 512) -> tuple:
    """Bucket rungs from `floor` up to and including `cap`: power-of-2 at
    the bottom, then eighth rungs (x1.125 steps) from `fine_from` so the
    large packed buffers — where padded rows actually cost
    host->device/ICI bytes — overshoot by at most 12.5%. Coarser than
    the round-5 sixteenth ladder on purpose: every DISTINCT rung a run
    hits costs a python-side trace + XLA compile per process (~15-45ms
    each — the round-11 ledger's dominant mesh cost), and rung WANDER is
    now absorbed by _StickyRung hysteresis rather than by ladder
    density. Compiled programs persist across processes
    (tpu.compilation_cache_dir); the python trace does not."""
    rb, b = [], floor
    while b < cap:
        rb.append(b)
        if b >= fine_from:
            num, denom = range(9, 16), 8
        elif b >= max(32, fine_from // 8):
            num, denom = range(5, 8), 4
        else:
            num, denom = (), 1
        rb.extend(x for x in (b * s // denom for s in num) if x < cap)
        b *= 2
    rb.append(cap)
    return tuple(sorted(set(x for x in rb if x <= cap)))


def _arith_ladder(cap: int, quantum: int, floor: int = 16) -> tuple:
    """Emission-side ladder: power-of-2 below `quantum`, then arithmetic
    multiples of `quantum` up to `cap`. Big watermark waves (the
    sliding-merge unions, where padded slots cost real gather work +
    device->host bytes) overshoot by at most `quantum` rows — under 5%
    average for waves a few quanta deep — while the signature count
    stays hard-bounded at cap/quantum + log2(quantum/floor)."""
    rb = []
    b = floor
    while b < quantum:
        rb.append(b)
        b *= 2
    rb.extend(range(quantum, cap + 1, quantum))
    if rb[-1] != cap:
        rb.append(cap)
    return tuple(sorted(set(x for x in rb if x <= cap)))


class _StickyRung:
    """Quantize a stream of buffer sizes onto a ladder with hysteresis.

    A fresh shape signature re-traces and re-compiles its jitted program
    (~15-45ms on CPU-jax, 20-40s through the TPU relay) — worth ~20+
    steady-state dispatches — so the rung must not follow every flush's
    row-count wander (the round-11 ledger shows mesh.step_direct
    specializing 14 ways in ONE bench child exactly that way). fit(n)
    reuses the current rung while n fits; on overflow it climbs straight
    to bucket(n); after `decay_after` consecutive fits below half the
    rung it steps down one rung, so a permanently shrunken workload
    stops shipping 2x filler but a single small flush changes nothing."""

    __slots__ = ("ladder", "rung", "_low", "decay_after", "headroom")

    def __init__(self, ladder: tuple, decay_after: int = 8,
                 headroom: float = 1.25):
        self.ladder = ladder
        self.rung = 0
        self._low = 0
        self.decay_after = decay_after
        self.headroom = headroom

    def fit(self, n: int) -> int:
        if n > self.rung:
            # climb with headroom: a ramping workload (window cardinality
            # growing through the run) would otherwise walk EVERY ladder
            # rung on its way up, tracing each once — the exact signature
            # storm the ladder coarsening fights. Successive climbs are
            # geometric in the headroom factor, so a KxX ramp costs
            # ~log(K)/log(headroom*step) climbs; the overshoot decays
            # back one rung at a time once sizes settle.
            self.rung = _bucket(
                n if self.rung == 0 else int(n * self.headroom),
                self.ladder,
            )
            self._low = 0
            return self.rung
        if n <= self.rung // 2:
            self._low += 1
            if self._low >= self.decay_after:
                i = self.ladder.index(self.rung) if self.rung in \
                    self.ladder else 0
                if i > 0:
                    self.rung = self.ladder[i - 1]
                self._low = 0
        else:
            self._low = 0
        return self.rung


# -- device-side owner hashing ------------------------------------------------
#
# The routing contract (PAPER §2.9-2.11): a row's owning shard is
# server_for_hash(splitmix64-combine(per-column splitmix64), n_shards).
# MeshSlotDirectory.owners_for computes it host-side (numpy) at assign
# time; device_owners_for is the jax mirror used ON DEVICE wherever rows
# carry raw key words instead of pre-assigned slots (device-resident
# producers feeding the route step, multi-host shuffles). The two MUST
# agree bit-for-bit — tests/test_parallel.py property-tests them against
# each other across shard counts.

_U64_MASK = 0xFFFFFFFFFFFFFFFF


def _jax_splitmix64(jnp, x):
    """splitmix64 finalizer over uint64 lanes (types._splitmix64)."""
    x = (x + jnp.uint64(0x9E3779B97F4A7C15)) & jnp.uint64(_U64_MASK)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def device_owners_for(key_cols, n_shards: int):
    """Owner shard per row from int64/uint64 key-word columns, computed
    with jax ops (traceable inside jitted route steps). Mirrors
    MeshSlotDirectory.owners_for = server_for_hash_array(hash_arrays(
    [hash_column(c) for c in cols])) exactly: per-column splitmix64,
    seeded xor-mix combine, then the contiguous hash-range map."""
    from ..types import HASH_SEED, _range_size

    from .mesh import _get_jnp

    jnp = _get_jnp()
    if not key_cols:
        return jnp.zeros(0, dtype=jnp.int64)
    cols = [jnp.asarray(c).astype(jnp.uint64) for c in key_cols]
    out = jnp.full(cols[0].shape, jnp.uint64(int(HASH_SEED)),
                   dtype=jnp.uint64)
    for col in cols:
        out = _jax_splitmix64(jnp, out ^ _jax_splitmix64(jnp, col))
    if n_shards == 1:
        return jnp.zeros(cols[0].shape, dtype=jnp.int64)
    owners = (out // jnp.uint64(_range_size(n_shards))).astype(jnp.int64)
    return jnp.minimum(owners, n_shards - 1)


# -- process-wide jitted program cache ----------------------------------------
#
# Jitted mesh programs are pure functions of (mesh, physical layout,
# capacity, mode flags): two accumulators with the same key — e.g. the
# two identical hop-count stages of nexmark q5 — must share ONE traced
# program set, not trace it twice (q5's per-child compile bill halves).
# key_mesh() caches Mesh instances so `id(mesh)` is a stable cache
# component; entries hold the InstrumentedJit wrapper so compile/dispatch
# telemetry is shared too.

_PROGRAMS: Dict[tuple, object] = {}


def _shared_program(key: tuple, build):
    prog = _PROGRAMS.get(key)
    if prog is None:
        prog = _PROGRAMS.setdefault(key, build())
    return prog


def _get_shard_map():
    """jax.shard_map moved out of experimental in newer jax; support
    both homes (the 0.4.x line only ships jax.experimental.shard_map)."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    return shard_map


def _donate_state() -> tuple:
    """donate_argnums for the state-consuming jitted programs. On the
    jax 0.4.x line (shard_map still experimental) donating sharded
    int64 state buffers corrupts the allocator across repeated engine
    runs (glibc "corrupted double-linked list", observed on 0.4.37-cpu
    whenever a mesh run shares a process with another engine run), so
    donation only engages where shard_map has moved into core jax."""
    try:
        from jax import shard_map  # noqa: F401

        return (0,)
    except ImportError:
        return ()


def _scatter_body(phys, jnp, neutral=_neutral):
    """Shared per-shard scatter-reduce: applies (flat_slots, valid, vals)
    rows into each physical accumulator row. Rows arrive PRE-REDUCED by
    the host combiner (one row per slot per flush): `valid` carries the
    segment's summed signs (row count for append-only streams, 0 for
    padding), add-source values arrive sign-folded (0 for padding), and
    min/max sources replace padding with the op's neutral."""

    def scatter(state_shards, flat_slots, valid_r, vals_r):
        out = []
        vi = 0
        for (op, dt, src, si), s in zip(phys, state_shards):
            row = s[0]
            if src == "one":
                v = valid_r.astype(row.dtype)
            else:
                v = vals_r[vi]
                vi += 1
                if op != "add":
                    v = jnp.where(valid_r != 0, v, neutral(op, dt))
            if op == "add":
                row = row.at[flat_slots].add(v.astype(row.dtype))
            elif op == "min":
                row = row.at[flat_slots].min(v.astype(row.dtype))
            else:
                row = row.at[flat_slots].max(v.astype(row.dtype))
            out.append(row[None, :])
        return tuple(out)

    return scatter


class SharedMeshSlotDirectory:
    """Slot directory for SALTED mesh aggregation (low-cardinality
    groups, e.g. q5/q7's MAX-per-window stage where every key is the
    window itself): one flat host directory allocates GLOBALLY-unique
    local ids, the nominal owner shard derives as local % S, and the
    salted accumulator spreads each update row across ALL shards at the
    same local index, folding across the shard axis at gather. Without
    this, hash ownership puts every row of a window on one shard — at
    most #windows of S shards ever receive rows (the round-4 mesh
    padding analysis)."""

    def __init__(self, n_shards: int):
        self.n_shards = n_shards
        self._flat = SlotDirectory()

    def swap_to_native(self, native_mod, n_keys: int) -> bool:
        """Swap the flat python directory for the C++ table (callable
        only while empty): the salted window-only groupings flatten
        their window struct to int64 words, and the python per-row
        interning + dict assign showed up as the salted stage's largest
        host cost in the mesh profile. Session operators never swap —
        their imperative alloc_slot/free lists live python-side."""
        if native_mod is None or self._flat.n_live:
            return False
        from ..ops.native import NativeSlotDirectory

        self._flat = NativeSlotDirectory(native_mod, n_keys=n_keys)
        # bound as instance attributes so the window operators' array
        # fast paths (attribute probes) engage exactly when arrays exist
        self.take_bin_arrays = self._take_bin_arrays
        self.bin_entries_multi = self._bin_entries_multi
        return True

    def _take_bin_arrays(self, b: int):
        cols, slots = self._flat.take_bin_arrays(b)
        return cols, self._g(slots)

    def _bin_entries_multi(self, bins) -> Tuple[np.ndarray, np.ndarray]:
        kmat, slots = self._flat.bin_entries_multi(bins)
        return kmat, self._g(slots)

    def _g(self, locals_: np.ndarray) -> np.ndarray:
        locals_ = np.asarray(locals_, dtype=np.int64)
        return (locals_ % self.n_shards) * STRIDE + locals_

    def _g1(self, local: int) -> int:
        return (local % self.n_shards) * STRIDE + local

    @property
    def n_live(self) -> int:
        return self._flat.n_live

    @property
    def by_bin(self):
        return {b: True for b in self._flat.by_bin}

    def required_capacity(self) -> int:
        return self._flat.required_capacity()

    def assign(self, bins, key_cols) -> np.ndarray:
        return self._g(self._flat.assign(bins, key_cols))

    def bins_up_to(self, limit):
        return self._flat.bins_up_to(limit)

    def live_bins(self):
        return self._flat.live_bins()

    def peek_bin(self, b):
        m = self._flat.peek_bin(b)
        if not m:
            return None
        return {k: self._g1(s) for k, s in m.items()}

    def bin_entries(self, b):
        keys, slots = self._flat.bin_entries(b)
        return keys, self._g(slots)

    def take_bin(self, b):
        keys, slots = self._flat.take_bin(b)
        return keys, self._g(slots)

    def items(self):
        for b, key, s in self._flat.items():
            yield b, key, self._g1(s)

    def keys_for_slots(self, slots):
        return self._flat.keys_for_slots(
            np.asarray(slots, dtype=np.int64) % STRIDE
        )

    def remove(self, b, keys):
        return self._g(self._flat.remove(b, keys))

    def alloc_slot(self, shard_hint: int = 0) -> int:
        return self._g1(self._flat.alloc_slot())

    def alloc_slots(self, n: int, shard_hint: int = 0) -> np.ndarray:
        return self._g(self._flat.alloc_slots(n))

    def free_slot(self, slot: int):
        self._flat.free_slot(int(slot) % STRIDE)

    def free_slots(self, slots: np.ndarray):
        self._flat.free_slots(np.asarray(slots, dtype=np.int64) % STRIDE)


class ShardedAccumulator(Accumulator):
    """Accumulator whose slot arrays live sharded across a 1-D device mesh;
    updates route rows to their owning device with an in-step all_to_all.
    Slots are MeshSlotDirectory global slots (shard * STRIDE + local)."""

    def __init__(
        self,
        specs: List[AggSpec],
        mesh,
        capacity_per_shard: int = 4096,
        rows_per_shard: int = 1024,
        host_fed: bool = True,
        salted: bool = False,
        flush_rows: int = 0,
        exchange: Optional[str] = None,
    ):
        # initialize host-side bookkeeping via the base class with backend
        # 'numpy' (cheap), then replace the state with mesh-sharded arrays
        super().__init__(specs, capacity=capacity_per_shard, backend="numpy")
        from ..config import config as config_fn

        self.backend = "jax-mesh"
        # honor tpu.use_32bit_accumulators exactly like the single-device
        # jax backend (the base ctor only engages it for backend "jax"):
        # halves state bytes, transfer bytes and scatter width on v5e
        self.use32 = bool(
            getattr(config_fn().tpu, "use_32bit_accumulators", False)
        )
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.rows_per_shard = rows_per_shard
        # packing-rung ladders (eighth rungs up top, ≤12.5% overshoot)
        # with sticky hysteresis per layout: steady state locks onto one
        # shape per program instead of re-specializing per flush
        self._rung_direct = _StickyRung(
            _pow2_ladder(rows_per_shard * self.n_shards, floor=16)
        )
        self._rung_a2a = _StickyRung(_pow2_ladder(rows_per_shard, floor=2))
        # device-routed exchange rungs: C = src-major rows per source
        # shard, R = all_to_all cell rows (sized from the host bincount)
        self._rung_chunk = _StickyRung(
            _pow2_ladder(max(rows_per_shard, 16), floor=16, fine_from=1 << 30)
        )
        self._rung_cell = _StickyRung(
            _pow2_ladder(max(rows_per_shard, 16), floor=16)
        )
        # multi-host: the mesh may span devices owned by several
        # processes (jax.distributed — parallel/multihost.py). All host
        # buffers then enter the device as GLOBAL arrays (each process
        # materializes only its addressable shards) and every mesh
        # process runs the same steps in lockstep.
        from .multihost import is_multiprocess_mesh

        self._multiproc = is_multiprocess_mesh(mesh)
        # exchange tier: 'device' (fused GSPMD route+scatter+reduce, no
        # host combiner), 'host_fed' (combiner + dst-major [S, R] packed
        # transfer — the multi-process / virtual-mesh fallback), 'a2a'
        # (host-packed [S, S, R] + in-step all_to_all). See module
        # docstring for the auto-resolution rationale.
        self._exchange = self._resolve_exchange(exchange, host_fed)
        self.host_fed = self._exchange == "host_fed"
        # emission/reset/restore reads are chunked at
        # tpu.mesh_emission_chunk and padded on their own sticky ladder:
        # big drain waves re-use the full-chunk program instead of
        # specializing a fresh XLA program per wave size, and steady
        # waves ride one rung with ≤12.5% filler (quarter/eighth rungs
        # from 256) — the round-11 ledger's "emission-rung padding"
        self._emission_chunk = int(
            getattr(config_fn().tpu, "mesh_emission_chunk", 16384) or 16384
        )
        from .mesh import mesh_is_virtual

        if mesh_is_virtual(mesh):
            # virtual mesh: the bottleneck is the per-process python
            # trace each distinct rung costs, not padded bytes (padded
            # slots gather at ~50ns each on the shared host core) — two
            # rungs bound the emission program count at 2 per kind
            self._buckets = (max(self._emission_chunk // 8, 16),
                             self._emission_chunk)
        else:
            # real chip mesh: device->host bytes and 20-40s TPU-relay
            # compiles both matter; quantum rungs keep steady waves
            # under ~5% padding at a hard-bounded signature count
            self._buckets = _arith_ladder(
                self._emission_chunk, max(self._emission_chunk // 16, 64)
            )
        # owner-sliced emission rung: per-shard slice length (~wave/S)
        self._rung_slice = _StickyRung(_pow2_ladder(1 << 20, floor=16))
        # salted mode (SharedMeshSlotDirectory): update rows spread
        # row-position round-robin across ALL shards at the slot's local
        # index — perfectly balanced regardless of key skew — and gather
        # folds across the shard axis. Requires globally-unique locals
        # and fold-able phys ops (add/min/max; no host-state aggregates).
        self.salted = salted
        # padding diagnostics (VERDICT r3: "document rows-sent vs
        # rows-padded"): rows_sent counts real rows pushed through the
        # packed exchange (either layout); rows_padded counts the
        # neutral filler rows shipped alongside them
        self.rows_sent = 0
        self.rows_padded = 0
        # micro-batching: update() buffers rows host-side and ships one
        # packed exchange + scatter per `flush_rows` rows instead of per
        # engine batch; every state read (gather/reset/restore) that
        # touches a pending slot flushes first, so observers never see
        # stale state — reads of untouched slots keep buffering (the
        # watermark-emission gathers otherwise force a flush per engine
        # batch and pin dispatches/updates near 1). 0 = immediate.
        self.flush_rows = int(flush_rows)
        self._pending: List[tuple] = []   # (slots, vals_list, signs)
        self._pending_rows = 0
        # observed engine-batch row EWMA: the effective flush threshold
        # auto-tunes to >= 4 batches so a configured threshold below the
        # pipeline's natural batch size still coalesces dispatches
        self._ewma_rows = 0
        self._sharding = self._make_sharding()
        self.state = self._fresh_state(capacity_per_shard)

    def _resolve_exchange(self, exchange: Optional[str],
                          host_fed: bool) -> str:
        """Pick the exchange tier. Explicit ctor/config choices win; auto
        keeps the host-fed combiner path wherever the device-routed
        exchange cannot pay for itself: multi-process meshes (no ICI
        collectives under the CPU backend) and single-process VIRTUAL
        meshes (every "device" is the same host core — see module
        docstring). Real chip meshes default to the device route."""
        from ..config import config as config_fn
        from .mesh import mesh_is_virtual

        mode = exchange or str(
            getattr(config_fn().tpu, "mesh_exchange", "auto") or "auto"
        )
        if mode not in ("auto", "device", "host_fed", "a2a"):
            raise ValueError(
                f"tpu.mesh_exchange must be auto|device|host_fed|a2a, "
                f"got {mode!r}"
            )
        if mode != "auto":
            return mode
        if not host_fed:
            return "a2a"  # ctor opt-in to the src-major packed layout
        if self._multiproc or mesh_is_virtual(self.mesh):
            return "host_fed"
        return "device"

    def _program(self, kind: str, build, *extra):
        """Process-wide shared jitted program for this accumulator's
        layout: identical stages (same mesh, phys ops/dtypes, capacity,
        salted/multiproc mode) resolve to ONE traced program set."""
        key = (kind, id(self.mesh), self.capacity, tuple(self.phys),
               self.salted, self._multiproc, self.use32, *extra)
        return _shared_program(key, build)

    def _make_sharding(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return NamedSharding(self.mesh, P(self.axis, None))

    def _fresh_state(self, capacity: int):
        from jax.sharding import PartitionSpec as P

        from .mesh import _get_jnp
        from .multihost import put_global

        _get_jnp()  # enable x64 before any placement
        return [
            put_global(
                np.full(
                    (self.n_shards, capacity),
                    self._neutral(op, dt),
                    dtype=self._dt(dt),
                ),
                self.mesh,
                P(self.axis, None),
            )
            for op, dt, _, _ in self.phys
        ]

    def _to_dev(self, arr: np.ndarray, shard_dim0: bool):
        """Host buffer -> device array for step/gather inputs: sharded on
        dim 0 over the mesh axis (packed row buffers) or replicated
        (index vectors). Single-process fast path: plain jnp.asarray —
        jit re-shards as needed."""
        from .mesh import _get_jnp

        jnp = _get_jnp()
        if not self._multiproc:
            return jnp.asarray(arr)
        from jax.sharding import PartitionSpec as P

        from .multihost import put_global

        return put_global(arr, self.mesh,
                          P(self.axis) if shard_dim0 else P())

    def _decompose(self, slots: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        return slots // STRIDE, slots % STRIDE

    # -- capacity -----------------------------------------------------------

    def grow(self, min_capacity: int):
        """Grow every shard's local capacity (4x steps). Global slot ids are
        stride-encoded, so no live slot is re-numbered; the old per-shard
        scratch slot is reset to neutral before it becomes allocatable."""
        new_cap = self.capacity
        while new_cap < min_capacity:
            new_cap *= 4
        if new_cap == self.capacity:
            return
        import jax

        from .mesh import _get_jnp

        jnp = _get_jnp()
        old_cap = self.capacity
        phys = list(self.phys)
        n_shards = self.n_shards

        # one jitted program for ALL columns, with explicit out_shardings:
        # valid in both single- and multi-process mode (eager concatenate
        # of a global sharded array with a process-local pad is not).
        # grow() is rare (4x capacity steps), so a compile per call is
        # acceptable; a single program per grow beats one per column.
        neutral, dtype = self._neutral, self._dt

        @partial(jax.jit, donate_argnums=_donate_state(), out_shardings=self._sharding)
        def grow_fn(state):
            out = []
            for (op, dt, _, _), x in zip(phys, state):
                pad = jnp.full(
                    (n_shards, new_cap - old_cap), neutral(op, dt),
                    dtype=dtype(dt),
                )
                g = jnp.concatenate([x, pad], axis=1)
                out.append(g.at[:, old_cap - 1].set(neutral(op, dt)))
            return out

        self.state = grow_fn(list(self.state))
        self.capacity = new_cap

    # -- update (hot path) --------------------------------------------------

    def update(
        self,
        slots: np.ndarray,
        cols: Dict[int, np.ndarray],
        signs: Optional[np.ndarray] = None,
    ):
        n = len(slots)
        if n == 0:
            return
        self._check_signed(signs)
        self._update_host(slots, cols, signs)
        if not self.phys:
            return
        MESH_STATS["updates"] += 1
        slots = np.asarray(slots)
        max_local = int((slots % STRIDE).max())
        if max_local >= self.capacity - 1:
            # jit scatters silently drop out-of-bounds updates — callers
            # must grow() first (windows.py _ensure_capacity does);
            # checked at update() time (capacity only ever grows before a
            # deferred flush, so the buffered check stays valid)
            raise ValueError(
                f"shard accumulator capacity exceeded: local slot "
                f"{max_local} >= capacity-1={self.capacity - 1}"
            )
        from ..ops.aggregates import _src_values

        vals = [
            np.asarray(_src_values(self.specs[si], src, cols))
            for op, dt, src, si in self.phys if src != "one"
        ]
        self._ewma_rows = (
            n if not self._ewma_rows else (self._ewma_rows * 7 + n) // 8
        )
        thr = self._flush_threshold()
        if thr <= n and not self._pending:
            self._dispatch_rows(slots, vals, signs)
            return
        self._pending.append(
            (slots, vals, None if signs is None else np.asarray(signs))
        )
        self._pending_rows += n
        if self._pending_rows >= thr:
            self.flush()

    def _flush_threshold(self) -> int:
        """Effective micro-batch threshold: the configured
        tpu.mesh_flush_rows, auto-raised to ~8 observed engine batches
        (bounded) so a threshold tuned for one workload still coalesces
        dispatches when the pipeline feeds bigger batches — watermark
        waves force a flush anyway, so between waves bigger is cheaper.
        0 disables buffering entirely (immediate dispatch)."""
        if self.flush_rows <= 0:
            return 0
        return max(self.flush_rows, min(8 * self._ewma_rows, 1 << 20))

    def _flush_if_touches(self, slots: np.ndarray):
        """Flush pending update rows only when one could affect `slots`.
        State reads (gather/reset/restore) of slots no pending row
        touches keep buffering — correctness holds because every read
        path comes through here first, and the eventual flush applies
        the buffered scatters in their original order relative to any
        elided read (disjoint slot sets commute)."""
        if not self._pending:
            return
        slots = np.asarray(slots)
        if len(slots):
            # the isin probe sorts both sides — against a large raw
            # pending buffer it costs more than the dispatch it might
            # save, and emission reads of hot bins nearly always overlap
            # pending rows anyway. Probe only when it is cheap AND has a
            # real chance of eliding; otherwise just flush.
            if self._pending_rows * len(slots) > (1 << 22):
                self.flush()
                return
            for p_slots, _, _ in self._pending:
                if np.isin(p_slots, slots, assume_unique=False).any():
                    self.flush()
                    return
        MESH_STATS["flushes_elided"] += 1

    def flush(self):
        """Ship any buffered update rows to the device (one packed
        exchange covering every pending engine batch)."""
        if not self._pending:
            return
        if len(self._pending) == 1:
            slots, vals, signs = self._pending[0]
        else:
            slots = np.concatenate([p[0] for p in self._pending])
            vals = [
                np.concatenate([p[1][i] for p in self._pending])
                for i in range(len(self._pending[0][1]))
            ]
            if any(p[2] is not None for p in self._pending):
                signs = np.concatenate([
                    p[2] if p[2] is not None
                    else np.ones(len(p[0]), dtype=np.int64)
                    for p in self._pending
                ])
            else:
                signs = None
        self._pending = []
        self._pending_rows = 0
        self._dispatch_rows(slots, vals, signs)

    def _prereduce(self, slots: np.ndarray, vals: List[np.ndarray],
                   signs: Optional[np.ndarray]):
        """Host-side combiner: rows sharing a slot within one flush
        collapse into a single packed row — add sources sum (sign-
        weighted), min/max take their extremum, and the valid word
        carries the segment's summed signs (= row count on append-only
        streams). The packed exchange then ships O(unique slots) rows:
        hot keys no longer skew the per-destination counts that size the
        padded [S, R] buffer (the dominant residual padding source), and
        shipped bytes drop with the dedup ratio. Integer accumulators
        are exact under the reassociation; float sums see the same
        reordering class as XLA's scatter reduction."""
        n = len(slots)
        if n == 0:
            return slots, vals, signs
        # one argsort does all the segmenting work (np.unique would sort
        # a second time and build an inverse nothing needs): sorted-run
        # boundaries give the unique slots and the reduceat bounds
        order = np.argsort(slots, kind="stable")
        s_sorted = slots[order]
        new_seg = np.empty(n, dtype=bool)
        new_seg[0] = True
        np.not_equal(s_sorted[1:], s_sorted[:-1], out=new_seg[1:])
        bounds = np.nonzero(new_seg)[0]
        uniq = s_sorted[bounds]
        MESH_STATS["rows_combined"] += n - len(uniq)
        if len(uniq) == n:
            # no duplicates: only fold signs into add-source values so
            # the kernel's uniform pre-reduced semantics hold
            if signs is not None:
                out_vals = []
                vi = 0
                for op, dt, src, si in self.phys:
                    if src == "one":
                        continue
                    v = vals[vi]
                    vi += 1
                    out_vals.append(
                        v * signs.astype(v.dtype) if op == "add" else v
                    )
                vals = out_vals
            return slots, vals, signs
        sgn = signs[order] if signs is not None else None
        out_vals = []
        vi = 0
        for op, dt, src, si in self.phys:
            if src == "one":
                continue
            v = vals[vi][order]
            vi += 1
            if op == "add":
                if sgn is not None:
                    v = v * sgn.astype(v.dtype)
                out_vals.append(np.add.reduceat(v, bounds))
            elif op == "min":
                out_vals.append(np.minimum.reduceat(v, bounds))
            else:
                out_vals.append(np.maximum.reduceat(v, bounds))
        # per-slot summed signs (plain row count when unsigned): the
        # count word and the padding discriminator. Signed streams only
        # carry add phys (non-invertible aggregates replay host-side),
        # so a zero sum contributes zero everywhere — still correct.
        if sgn is not None:
            counts = np.add.reduceat(sgn, bounds)
        else:
            counts = np.diff(np.append(bounds, n))
        return uniq, out_vals, counts.astype(np.int64, copy=False)

    def _dispatch_rows(self, slots: np.ndarray, vals: List[np.ndarray],
                       signs: Optional[np.ndarray]):
        if self._exchange == "device":
            # GSPMD device-routed exchange: raw rows ship src-major, the
            # fused route+scatter+reduce program owns routing AND the
            # duplicate-slot reduction — no host combiner on this path
            self._dispatch_rows_device(slots, vals, signs)
            return
        slots, vals, signs = self._prereduce(slots, vals, signs)
        n = len(slots)
        S, R = self.n_shards, self.rows_per_shard
        owners, locals_ = self._decompose(slots)
        if self.salted:
            # balanced spread: every shard takes ~n/S rows of each group;
            # the cross-shard fold happens at gather
            owners = np.arange(n, dtype=np.int64) % S
        order = np.argsort(owners, kind="stable")
        so = owners[order]
        starts = np.searchsorted(so, so, side="left")
        pos = np.arange(n, dtype=np.int64) - starts   # rank within owner
        if self.host_fed:
            # dst-major [S, R] direct layout: the host already sees every
            # row, so the key shuffle happens at packing time and the
            # sharded host->device transfer IS the routing.
            r_cap = self.rows_per_shard * S
            chunk = pos // r_cap
            for c in range(int(chunk.max()) + 1):
                in_chunk = chunk == c
                rows = order[in_chunk]
                pm = pos[in_chunk] - c * r_cap
                r_c = self._rung_direct.fit(int(pm.max()) + 1)
                flat = so[in_chunk] * r_c + pm
                self._note_traffic(len(rows), S * r_c,
                                   "mesh.step_direct", r_c)
                self._dispatch(self._direct_step(), (S, r_c), rows, flat,
                               locals_, vals, signs)
            return
        # Balanced packing into the [src, dst, row] all_to_all layout:
        # each destination shard's rows are dealt round-robin across the
        # S source positions, so every (src, dst) cell carries
        # ceil(count_dst / S) rows and the per-cell row budget R shrinks
        # to the sticky-bucketed max — the buffer is sized to the batch
        # (plus skew), not to the configured ceiling. Splits into
        # multiple steps only when the hottest destination overflows
        # S * rows_per_shard rows.
        srcs = pos % S
        cell = pos // S                               # row within cell
        chunk = cell // R
        for c in range(int(chunk.max()) + 1):
            in_chunk = chunk == c
            rows = order[in_chunk]
            cm = cell[in_chunk] - c * R
            r_c = self._rung_a2a.fit(int(cm.max()) + 1)
            flat = (srcs[in_chunk] * S + so[in_chunk]) * r_c + cm
            self._note_traffic(len(rows), S * S * r_c, "mesh.step", r_c)
            self._dispatch(self._step(), (S, S, r_c), rows, flat, locals_,
                           vals, signs)

    def _dispatch_rows_device(self, slots: np.ndarray,
                              vals: List[np.ndarray],
                              signs: Optional[np.ndarray]):
        """Device-routed exchange: pack RAW rows src-major (a positional
        [S, C] chop — no argsort, no combiner, no per-owner layout) and
        let the fused route+scatter+reduce program derive owners, build
        the all_to_all cells and reduce duplicates on device. Host work
        per flush: one pad + one bincount (cell-rung sizing)."""
        n = len(slots)
        S = self.n_shards
        cap = self.capacity
        C = self._rung_chunk.fit(-(-n // S))      # rows per source shard
        N = C * S
        # padding rows: owner spread evenly, local = scratch, valid 0
        enc = np.empty(N, dtype=np.int64)
        enc[:n] = slots
        pad_pos = np.arange(n, N, dtype=np.int64)
        enc[n:] = (pad_pos % S) * STRIDE + (cap - 1)
        valid = np.zeros(N, dtype=np.int64)
        valid[:n] = 1 if signs is None else signs
        if self.salted:
            # positional round-robin spread: every (src, dst) cell holds
            # exactly ceil(C / S) rows — no skew, no bincount
            R = -(-C // S)
        else:
            owners = enc // STRIDE
            srcs = np.arange(N, dtype=np.int64) // C
            R = self._rung_cell.fit(
                int(np.bincount(srcs * S + owners,
                                minlength=S * S).max())
            )
            R = min(R, C)
        inputs = []
        vi = 0
        for op, dt, src, si in self.phys:
            if src == "one":
                continue
            v = np.full(
                N,
                0 if op == "add" else self._neutral(op, dt),
                dtype=self._dt(dt),
            )
            v[:n] = vals[vi]
            vi += 1
            inputs.append(self._to_dev(v.reshape(S, C), True))
        MESH_STATS["dispatches"] += 1
        # exchange-layer filler: rung padding (N - n) plus all_to_all
        # cell padding (S*S*R - N); both ride the collective
        self._note_traffic(n, max(S * S * R, N), "mesh.route", R)
        self.state = self._route_step(C, R)(
            self.state,
            self._to_dev(enc.reshape(S, C), True),
            self._to_dev(valid.reshape(S, C), True),
            *inputs,
            rung=R,
        )

    def _note_traffic(self, sent: int, shipped: int,
                      program: str = "mesh.step", rung: int = 0):
        self.rows_sent += sent
        self.rows_padded += shipped - sent
        MESH_STATS["rows_sent"] += sent
        MESH_STATS["rows_padded"] += shipped - sent
        # per-(program, rung) waste gauge: which packing rungs the
        # exchange actually hits and how much filler each ships
        obs_device.note_padding(program, rung, sent, shipped)

    def _dispatch(self, step, shape, rows, flat, locals_, vals, signs):
        """Pack (slots, valid, per-source values) buffers of `shape` and
        run one jitted step. Buffers enter the device sharded on dim 0
        (the destination-shard dimension in both layouts). `vals` holds
        one value array per non-count physical accumulator, pre-extracted
        at update() time so buffered flushes just concatenate."""
        MESH_STATS["dispatches"] += 1
        total = int(np.prod(shape))
        slots_l = np.full(total, self.capacity - 1, dtype=np.int64)
        slots_l[flat] = locals_[rows]
        valid = np.zeros(total, dtype=np.int64)
        valid[flat] = 1 if signs is None else signs[rows]
        inputs = []
        vi = 0
        for op, dt, src, si in self.phys:
            if src == "one":
                continue
            v = np.full(
                total,
                0 if op == "add" else self._neutral(op, dt),
                dtype=self._dt(dt),
            )
            # sign application happens in-kernel: add-sources multiply by
            # valid (0 padding / ±1 append-retract)
            v[flat] = vals[vi][rows]
            vi += 1
            inputs.append(self._to_dev(v.reshape(shape), True))
        self.state = step(
            self.state,
            self._to_dev(slots_l.reshape(shape), True),
            self._to_dev(valid.reshape(shape), True),
            *inputs,
            rung=shape[-1],
        )

    def _step(self):
        return self._program("step", self._make_step)

    def _direct_step(self):
        return self._program("step_direct", self._make_direct_step)

    def _route_step(self, C: int, R: int):
        return self._program("route", lambda: self._make_route_step(C, R),
                             C, R)

    def _make_step(self):
        import jax

        from .mesh import _get_jnp

        jnp = _get_jnp()
        phys = list(self.phys)
        axis = self.axis

        scatter = _scatter_body(phys, jnp, self._neutral)

        def local_update(state_shards, slots, valid, *vals):
            # local views: state [1, cap]; slots/valid/vals [1, S, R] where
            # dim1 indexes the destination shard. all_to_all over the mesh
            # axis exchanges those blocks (the ICI shuffle): afterwards
            # [S, R] holds the rows every source shard sent to THIS shard.
            def exchange(x):
                return jax.lax.all_to_all(x[0], axis, 0, 0, tiled=True)

            valid_r = exchange(valid).reshape(-1)
            flat_slots = exchange(slots).reshape(-1)
            vals_r = [exchange(v).reshape(-1) for v in vals]
            return scatter(state_shards, flat_slots, valid_r, vals_r)

        n_state = len(self.phys)

        @partial(jax.jit, donate_argnums=_donate_state(), static_argnums=())
        def step(state, slots, valid, *vals):
            from jax.sharding import PartitionSpec as P

            f = _get_shard_map()(
                local_update,
                mesh=self.mesh,
                in_specs=(
                    tuple(P(axis, None) for _ in range(n_state)),
                    P(axis, None),
                    P(axis, None),
                )
                + tuple(P(axis, None) for _ in vals),
                out_specs=tuple(P(axis, None) for _ in range(n_state)),
            )
            return list(f(tuple(state), slots, valid, *vals))

        return obs_device.InstrumentedJit("mesh.step", step, exchange=True)

    def _make_direct_step(self):
        """Step for host-fed dst-major [S, R] batches: rows were routed to
        their owner shard at packing time, so each shard scatters its own
        block — no collective in the program at all."""
        import jax

        from .mesh import _get_jnp

        jnp = _get_jnp()
        phys = list(self.phys)
        axis = self.axis
        scatter = _scatter_body(phys, jnp, self._neutral)

        def local_update(state_shards, slots, valid, *vals):
            # local views: state [1, cap]; slots/valid/vals [1, R] — this
            # shard's rows, already in place after the sharded transfer
            return scatter(
                state_shards, slots[0], valid[0], [v[0] for v in vals]
            )

        n_state = len(self.phys)

        @partial(jax.jit, donate_argnums=_donate_state(), static_argnums=())
        def step(state, slots, valid, *vals):
            from jax.sharding import PartitionSpec as P

            f = _get_shard_map()(
                local_update,
                mesh=self.mesh,
                in_specs=(
                    tuple(P(axis, None) for _ in range(n_state)),
                    P(axis),
                    P(axis),
                )
                + tuple(P(axis) for _ in vals),
                out_specs=tuple(P(axis, None) for _ in range(n_state)),
            )
            return list(f(tuple(state), slots, valid, *vals))

        return obs_device.InstrumentedJit("mesh.step_direct", step,
                                          exchange=True)

    def _make_route_step(self, C: int, R: int):
        """The fused route+scatter+reduce program of the device-resident
        keyed exchange. Input rows arrive RAW and src-major ([S, C]: a
        positional chop of the flush, NamedSharding over the key mesh);
        per shard the program

          1. routes: derives each row's owner from its global slot
             (shard = slot // STRIDE — the splitmix64 hash assigned at
             directory time; device_owners_for is the equivalent for
             raw key words) — salted layouts spread positionally,
          2. positions: ranks rows within their (src, owner) cell via a
             one-hot running count and scatters them into the [S, R]
             send cells (padding cells carry scratch-slot/neutral rows),
          3. exchanges: `jax.lax.all_to_all` over the mesh axis — the
             collective XLA compiles into the step, riding ICI on real
             chip meshes,
          4. scatter-reduces the received rows into the local state
             shard; duplicate slots reduce IN the scatter (.add/.min/
             .max), which is what replaces the host combiner.

        Signs apply in-kernel (add-sources multiply by the valid word;
        min/max sources replace invalid rows with the op's neutral), so
        raw retraction rows need no host preprocessing either."""
        import jax

        from .mesh import _get_jnp

        jnp = _get_jnp()
        phys = list(self.phys)
        axis = self.axis
        S = self.n_shards
        cap = self.capacity
        salted = self.salted
        neutral, dtype = self._neutral, self._dt

        def local_route(state_shards, enc, valid, *vals):
            enc, valid = enc[0], valid[0]
            vals = [v[0] for v in vals]
            if salted:
                pos = jnp.arange(C, dtype=jnp.int64)
                owner = (pos % S).astype(jnp.int64)
                rank = pos // S
            else:
                owner = enc // STRIDE
                # rank within (this src chunk, owner): one-hot running
                # count — dense [C, S] work that vectorizes, where a
                # per-row scatter-count would serialize
                oh = owner[:, None] == jnp.arange(S, dtype=enc.dtype)[None, :]
                rank = jnp.take_along_axis(
                    jnp.cumsum(oh.astype(jnp.int32), axis=0) - 1,
                    owner[:, None].astype(jnp.int32), axis=1,
                )[:, 0].astype(jnp.int64)
            loc = enc % STRIDE
            sidx = (owner * R + rank).astype(jnp.int32)

            def exchange(send):
                return jax.lax.all_to_all(
                    send.reshape(S, R), axis, 0, 0, tiled=True
                ).reshape(-1)

            # send cells: padding rows target the scratch slot with
            # valid 0 / neutral values, so they reduce to no-ops
            recv_loc = exchange(
                jnp.full(S * R, cap - 1, dtype=enc.dtype).at[sidx].set(loc)
            )
            recv_valid = exchange(
                jnp.zeros(S * R, dtype=valid.dtype).at[sidx].set(valid)
            )
            recv_vals = []
            vi = 0
            for op, dt, src, si in phys:
                if src == "one":
                    continue
                fill = 0 if op == "add" else neutral(op, dt)
                recv_vals.append(exchange(
                    jnp.full(S * R, fill, dtype=dtype(dt)).at[sidx].set(
                        vals[vi]
                    )
                ))
                vi += 1
            # scatter-reduce; duplicate slots fold here (the device-side
            # combiner): .add sums sign-weighted rows, .min/.max take
            # extremes over neutral-masked rows
            out = []
            vi = 0
            for (op, dt, src, si), s in zip(phys, state_shards):
                row = s[0]
                if src == "one":
                    v = recv_valid.astype(row.dtype)
                else:
                    v = recv_vals[vi]
                    vi += 1
                    if op == "add":
                        v = (v * recv_valid.astype(v.dtype)).astype(
                            row.dtype
                        )
                    else:
                        v = jnp.where(
                            recv_valid != 0, v, neutral(op, dt)
                        ).astype(row.dtype)
                if op == "add":
                    row = row.at[recv_loc].add(v)
                elif op == "min":
                    row = row.at[recv_loc].min(v)
                else:
                    row = row.at[recv_loc].max(v)
                out.append(row[None, :])
            return tuple(out)

        n_state = len(self.phys)

        @partial(jax.jit, donate_argnums=_donate_state(), static_argnums=())
        def step(state, enc, valid, *vals):
            from jax.sharding import PartitionSpec as P

            f = _get_shard_map()(
                local_route,
                mesh=self.mesh,
                in_specs=(
                    tuple(P(axis, None) for _ in range(n_state)),
                    P(axis, None),
                    P(axis, None),
                )
                + tuple(P(axis, None) for _ in vals),
                out_specs=tuple(P(axis, None) for _ in range(n_state)),
            )
            return list(f(tuple(state), enc, valid, *vals))

        return obs_device.InstrumentedJit("mesh.route", step, exchange=True)

    # -- drain --------------------------------------------------------------
    #
    # Emission-side programs (gather / fused gather+reset / reset /
    # restore) are shared process-wide like the steps, their slot
    # buffers are padded on sticky emission rungs, and every read is
    # CHUNKED at tpu.mesh_emission_chunk: a 30k-slot end-of-stream
    # drain re-dispatches the full-chunk program eight times instead of
    # specializing a fresh XLA program for one 32768-wide wave (the
    # round-11 ledger counted 16 gather signatures in a single child,
    # almost all hit exactly once by ramp/drain waves).

    def _emit_rung(self, n: int) -> int:
        # plain arithmetic-ladder bucket (no hysteresis): emission waves
        # are the big stable reads, so quantum rungs keep their padding
        # under ~5% while the signature count stays hard-bounded
        return min(_bucket(n, self._buckets), self._emission_chunk)

    def _chunk_bounds(self, n: int):
        step = self._emission_chunk
        return [(lo, min(lo + step, n)) for lo in range(0, max(n, 1), step)]

    def _pad_slots(self, sh, loc, lo, hi, rung):
        sh_p = np.zeros(rung, dtype=np.int64)
        loc_p = np.full(rung, self.capacity - 1, dtype=np.int64)
        sh_p[: hi - lo] = sh[lo:hi]
        loc_p[: hi - lo] = loc[lo:hi]
        return sh_p, loc_p

    # -- owner-sliced emission ------------------------------------------------
    #
    # The replicated-index emission programs (plain jit, state sharded,
    # indices replicated) make EVERY shard scan EVERY index — the SPMD
    # partitioner's scatter/gather strategy — so a 16k-slot wave costs
    # S x 16k serial index ops on a virtual mesh (measured: 7ms per
    # gather_free dispatch, the single largest mesh cost at 1M events).
    # The owner-sliced path sorts the wave's slots by owner shard ON THE
    # HOST (one argsort per wave — the routing information is free in
    # the slot encoding) and hands each shard ONLY its own [1, L] slice
    # through shard_map, cutting device work back to ~n + padding. Host
    # reorders the gathered block back to union order with one fancy
    # index. Salted accumulators keep the replicated programs (the
    # cross-shard fold genuinely needs every shard per slot), as do
    # multi-process meshes (outputs must land replicated on every host).

    def _sliced_ok(self) -> bool:
        return not self.salted and not self._multiproc

    def _slice_rung(self, n_max: int) -> int:
        return self._rung_slice.fit(max(n_max, 1))

    def _slice_pack(self, slots: np.ndarray, extras=(), fills=()):
        """Sort the wave by owner shard and pack per-shard [S, L] index
        buffers (padding rows target the scratch slot). `extras` are
        row-aligned companion arrays (masks, restore values) packed the
        same way with their `fills`. Returns (loc_sl, extra_sls,
        flat_pos, L) where flat_pos[i] is row i's position in the
        flattened [S*L] device output."""
        S = self.n_shards
        slots = np.asarray(slots)
        sh, loc = self._decompose(slots)
        order = np.argsort(sh, kind="stable")
        sh_s = sh[order]
        counts = np.bincount(sh_s, minlength=S)
        L = self._slice_rung(int(counts.max()))
        starts = np.zeros(S, dtype=np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        rank = np.arange(len(slots), dtype=np.int64) - starts[sh_s]
        flat_sorted = sh_s * L + rank
        loc_sl = np.full(S * L, self.capacity - 1, dtype=np.int64)
        loc_sl[flat_sorted] = loc[order]
        extra_sls = []
        for arr, fill in zip(extras, fills):
            arr = np.asarray(arr)
            e = np.full(S * L, fill, dtype=arr.dtype)
            e[flat_sorted] = arr[order]
            extra_sls.append(e.reshape(S, L))
        flat_pos = np.empty(len(slots), dtype=np.int64)
        flat_pos[order] = flat_sorted
        return loc_sl.reshape(S, L), extra_sls, flat_pos, L

    def _sliced_gather_program(self):
        def build():
            import jax

            axis = self.axis
            n_state = len(self.phys)

            def local(state_shards, loc):
                return tuple(s[0][loc[0]][None, :] for s in state_shards)

            @jax.jit
            def fn(state, loc):
                from jax.sharding import PartitionSpec as P

                f = _get_shard_map()(
                    local,
                    mesh=self.mesh,
                    in_specs=(
                        tuple(P(axis, None) for _ in range(n_state)),
                        P(axis),
                    ),
                    out_specs=tuple(P(axis) for _ in range(n_state)),
                )
                return list(f(tuple(state), loc))

            return obs_device.InstrumentedJit("mesh.sgather", fn)

        return self._program("sgather", build)

    def _sliced_take_program(self):
        """Fused sliced gather + masked reset: serves gather_and_reset
        (mask all-ones) and the sliding drain's gather+free (mask =
        freed-bin rows) with ONE program per slice rung."""
        def build():
            import jax

            axis = self.axis
            phys = list(self.phys)
            neutral = self._neutral
            cap = self.capacity
            n_state = len(self.phys)

            def local(state_shards, loc, free):
                outs, new = [], []
                loc_r = None
                for (op, dt, _, _), s in zip(phys, state_shards):
                    row = s[0]
                    outs.append(row[loc[0]][None, :])
                    if loc_r is None:
                        from .mesh import _get_jnp

                        jnp = _get_jnp()
                        loc_r = jnp.where(free[0] != 0, loc[0], cap - 1)
                    new.append(row.at[loc_r].set(neutral(op, dt))[None, :])
                return tuple(outs), tuple(new)

            @partial(jax.jit, donate_argnums=_donate_state())
            def fn(state, loc, free):
                from jax.sharding import PartitionSpec as P

                f = _get_shard_map()(
                    local,
                    mesh=self.mesh,
                    in_specs=(
                        tuple(P(axis, None) for _ in range(n_state)),
                        P(axis),
                        P(axis),
                    ),
                    out_specs=(
                        tuple(P(axis) for _ in range(n_state)),
                        tuple(P(axis, None) for _ in range(n_state)),
                    ),
                )
                outs, new = f(tuple(state), loc, free)
                return list(outs), list(new)

            return obs_device.InstrumentedJit("mesh.stake", fn)

        return self._program("stake", build)

    def _sliced_reset_program(self):
        def build():
            import jax

            axis = self.axis
            phys = list(self.phys)
            neutral = self._neutral
            n_state = len(self.phys)

            def local(state_shards, loc):
                return tuple(
                    s[0].at[loc[0]].set(neutral(op, dt))[None, :]
                    for (op, dt, _, _), s in zip(phys, state_shards)
                )

            @partial(jax.jit, donate_argnums=_donate_state())
            def fn(state, loc):
                from jax.sharding import PartitionSpec as P

                f = _get_shard_map()(
                    local,
                    mesh=self.mesh,
                    in_specs=(
                        tuple(P(axis, None) for _ in range(n_state)),
                        P(axis),
                    ),
                    out_specs=tuple(P(axis, None) for _ in range(n_state)),
                )
                return list(f(tuple(state), loc))

            return obs_device.InstrumentedJit("mesh.sreset", fn)

        return self._program("sreset", build)

    def _sliced_restore_program(self):
        def build():
            import jax

            axis = self.axis
            n_state = len(self.phys)

            def local(state_shards, loc, *vals):
                return tuple(
                    s[0].at[loc[0]].set(v[0])[None, :]
                    for s, v in zip(state_shards, vals)
                )

            @partial(jax.jit, donate_argnums=_donate_state())
            def fn(state, loc, *vals):
                from jax.sharding import PartitionSpec as P

                f = _get_shard_map()(
                    local,
                    mesh=self.mesh,
                    in_specs=(
                        tuple(P(axis, None) for _ in range(n_state)),
                        P(axis),
                    )
                    + tuple(P(axis) for _ in vals),
                    out_specs=tuple(P(axis, None) for _ in range(n_state)),
                )
                return list(f(tuple(state), loc, *vals))

            return obs_device.InstrumentedJit("mesh.srestore", fn)

        return self._program("srestore", build)

    def _sliced_read(self, slots: np.ndarray,
                     free: Optional[np.ndarray]) -> List[np.ndarray]:
        """Owner-sliced gather (free=None) or fused gather+masked-reset,
        returning host arrays in the wave's original order."""
        n = len(slots)
        if free is None:
            loc_sl, _, flat_pos, L = self._slice_pack(slots)
            obs_device.note_padding("mesh.sgather", L, n,
                                    self.n_shards * L)
            outs = self._sliced_gather_program()(
                self.state, self._to_dev(loc_sl, True), rung=L,
            )
        else:
            loc_sl, (free_sl,), flat_pos, L = self._slice_pack(
                slots, (np.asarray(free, dtype=np.int64),), (0,)
            )
            obs_device.note_padding("mesh.stake", L, n,
                                    self.n_shards * L)
            outs, self.state = self._sliced_take_program()(
                self.state, self._to_dev(loc_sl, True),
                self._to_dev(free_sl, True), rung=L,
            )
        return [np.asarray(o).reshape(-1)[flat_pos] for o in outs]

    def _gather_program(self):
        def build():
            import jax

            phys = list(self.phys)

            if self.salted:

                def gather_fn(state, sh, loc):
                    # fold across the shard axis; padding rows point at
                    # the scratch slot, neutral on every shard
                    out = []
                    for (op, dt, _, _), s in zip(phys, state):
                        cols = s[:, loc]
                        if op == "add":
                            out.append(cols.sum(axis=0))
                        elif op == "min":
                            out.append(cols.min(axis=0))
                        else:
                            out.append(cols.max(axis=0))
                    return out
            else:

                def gather_fn(state, sh, loc):
                    return [s[sh, loc] for s in state]

            if self._multiproc:
                # emission values must be readable on EVERY process:
                # pin the outputs replicated so each host reads its
                # local copy (multihost.to_host)
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                gather_fn = jax.jit(
                    gather_fn,
                    out_shardings=NamedSharding(self.mesh, P()),
                )
            else:
                gather_fn = jax.jit(gather_fn)
            return obs_device.InstrumentedJit("mesh.gather", gather_fn)

        return self._program("gather", build)

    def _take_program(self):
        def build():
            import jax

            phys = list(self.phys)
            salted = self.salted
            neutral = self._neutral

            def take_fn(state, sh, loc):
                outs, new = [], []
                for (op, dt, _, _), s in zip(phys, state):
                    if salted:
                        cols = s[:, loc]
                        if op == "add":
                            outs.append(cols.sum(axis=0))
                        elif op == "min":
                            outs.append(cols.min(axis=0))
                        else:
                            outs.append(cols.max(axis=0))
                        # a salted slot's state lives on EVERY shard
                        new.append(s.at[:, loc].set(neutral(op, dt)))
                    else:
                        outs.append(s[sh, loc])
                        new.append(s.at[sh, loc].set(neutral(op, dt)))
                return outs, new

            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            return obs_device.InstrumentedJit(
                "mesh.take",
                jax.jit(
                    take_fn,
                    donate_argnums=_donate_state(),
                    # outs replicated (each process reads its local
                    # copy), state stays row-sharded
                    out_shardings=(
                        [NamedSharding(self.mesh, P())] * len(self.phys),
                        [self._sharding] * len(self.phys),
                    ),
                ),
            )

        return self._program("take", build)

    def _reset_program(self):
        def build():
            import jax

            phys = list(self.phys)
            salted = self.salted
            neutral = self._neutral

            @partial(jax.jit, donate_argnums=_donate_state(),
                     out_shardings=self._sharding)
            def reset_fn(state, sh, loc):
                if salted:
                    # a salted slot's state lives on EVERY shard
                    return [
                        s.at[:, loc].set(neutral(op, dt))
                        for s, (op, dt, _, _) in zip(state, phys)
                    ]
                return [
                    s.at[sh, loc].set(neutral(op, dt))
                    for s, (op, dt, _, _) in zip(state, phys)
                ]

            return obs_device.InstrumentedJit("mesh.reset", reset_fn)

        return self._program("reset", build)

    def _restore_program(self):
        def build():
            import jax

            phys = list(self.phys)
            salted = self.salted
            neutral = self._neutral

            @partial(jax.jit, donate_argnums=_donate_state(),
                     out_shardings=self._sharding)
            def restore_fn(state, sh, loc, *vals):
                if salted:
                    # restored value lands whole on the nominal shard;
                    # the other shards go neutral so the cross-shard
                    # fold reproduces it
                    return [
                        s.at[:, loc].set(neutral(op, dt))
                        .at[sh, loc].set(v)
                        for (op, dt, _, _), s, v in zip(phys, state, vals)
                    ]
                return [
                    s.at[sh, loc].set(v) for s, v in zip(state, vals)
                ]

            return obs_device.InstrumentedJit("mesh.restore", restore_fn)

        return self._program("restore", build)

    def gather(self, slots: np.ndarray,
               materialize: bool = True) -> List[np.ndarray]:
        self._flush_if_touches(slots)
        self._gather_slots = np.asarray(slots)
        self._segment_udaf = None
        self._segment_multiset = None
        n = len(slots)
        if n == 0:
            return [
                np.empty(0, dtype=self._dt(dt))
                for _, dt, _, _ in self.phys
            ]
        if self._sliced_ok():
            return self._sliced_read(np.asarray(slots), None)
        from .multihost import to_host

        prog = self._gather_program()
        sh, loc = self._decompose(np.asarray(slots))
        chunks = self._chunk_bounds(n)
        pieces = []
        for lo, hi in chunks:
            rung = self._emit_rung(hi - lo)
            sh_p, loc_p = self._pad_slots(sh, loc, lo, hi, rung)
            obs_device.note_padding("mesh.gather", rung, hi - lo, rung)
            outs = prog(
                self.state, self._to_dev(sh_p, False),
                self._to_dev(loc_p, False), rung=rung,
            )
            if len(chunks) == 1 and not materialize:
                if self._multiproc:
                    # replicated outputs span remote devices; hand back
                    # this process's local copy so later slicing /
                    # np.asarray work
                    outs = [o.addressable_data(0) for o in outs]
                return [o[:n] for o in outs]
            pieces.append([to_host(o)[: hi - lo] for o in outs])
        if len(pieces) == 1:
            return pieces[0]
        return [
            np.concatenate([p[i] for p in pieces])
            for i in range(len(self.phys))
        ]

    def gather_and_reset(self, slots: np.ndarray,
                         materialize: bool = True) -> List[np.ndarray]:
        """Fused drain: ONE jitted program gathers the slots' values and
        writes them back to neutral — the tumbling/session emission path
        otherwise pays two device dispatches per watermark wave, and on
        the CPU mesh every dispatch costs milliseconds of XLA launch.
        Host-side per-slot state is NOT dropped here: the caller
        finalizes first (finalize reads the stores), then calls
        drop_host_state."""
        self._flush_if_touches(slots)
        self._gather_slots = np.asarray(slots)
        self._segment_udaf = None
        self._segment_multiset = None
        n = len(slots)
        if n == 0 or not self.phys:
            return [
                np.empty(0, dtype=self._dt(dt))
                for _, dt, _, _ in self.phys
            ]
        if self._sliced_ok():
            return self._sliced_read(
                np.asarray(slots), np.ones(n, dtype=np.int64)
            )
        from .multihost import to_host

        prog = self._take_program()
        sh, loc = self._decompose(np.asarray(slots))
        chunks = self._chunk_bounds(n)
        pieces = []
        for lo, hi in chunks:
            rung = self._emit_rung(hi - lo)
            sh_p, loc_p = self._pad_slots(sh, loc, lo, hi, rung)
            obs_device.note_padding("mesh.take", rung, hi - lo, rung)
            outs, self.state = prog(
                self.state, self._to_dev(sh_p, False),
                self._to_dev(loc_p, False), rung=rung,
            )
            if len(chunks) == 1 and not materialize:
                if self._multiproc:
                    outs = [o.addressable_data(0) for o in outs]
                return [o[:n] for o in outs]
            pieces.append([to_host(o)[: hi - lo] for o in outs])
        if len(pieces) == 1:
            return pieces[0]
        return [
            np.concatenate([p[i] for p in pieces])
            for i in range(len(self.phys))
        ]

    def _gather_free_program(self):
        """Fused sliding drain: gather the window union AND reset the
        freed-bin subset (a 0/1 mask over the same padded slot buffer)
        in ONE jitted dispatch — the per-wave gather + reset pair
        otherwise costs two sharded-program launches, and the mask rides
        the gather's rung so the fusion adds NO shape signatures."""
        def build():
            import jax

            phys = list(self.phys)
            salted = self.salted
            neutral = self._neutral

            def gf_fn(state, sh, loc, free):
                outs, new = [], []
                # masked-out rows redirect their reset to the scratch
                # slot (already neutral), so one program serves every
                # (gather rung, freed count) combination
                loc_r = jax.numpy.where(free != 0, loc,
                                        state[0].shape[1] - 1)
                sh_r = jax.numpy.where(free != 0, sh, 0)
                for (op, dt, _, _), s in zip(phys, state):
                    if salted:
                        cols = s[:, loc]
                        if op == "add":
                            outs.append(cols.sum(axis=0))
                        elif op == "min":
                            outs.append(cols.min(axis=0))
                        else:
                            outs.append(cols.max(axis=0))
                        # a salted slot's state lives on EVERY shard
                        new.append(s.at[:, loc_r].set(neutral(op, dt)))
                    else:
                        outs.append(s[sh, loc])
                        new.append(s.at[sh_r, loc_r].set(neutral(op, dt)))
                return outs, new

            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            return obs_device.InstrumentedJit(
                "mesh.gather_free",
                jax.jit(
                    gf_fn,
                    donate_argnums=_donate_state(),
                    out_shardings=(
                        [NamedSharding(self.mesh, P())] * len(self.phys),
                        [self._sharding] * len(self.phys),
                    ),
                ),
            )

        return self._program("gather_free", build)

    def combine_for_segments_and_free(
        self, slots: np.ndarray, seg_ids: np.ndarray, n_segments: int,
        free_n: int = 0,
    ) -> List[np.ndarray]:
        if free_n == 0 or not self.phys:
            return super().combine_for_segments_and_free(
                slots, seg_ids, n_segments, free_n
            )
        slots = np.asarray(slots)
        n = len(slots)
        self._flush_if_touches(slots)
        self._gather_slots = slots
        self._segment_udaf = None
        self._segment_multiset = None
        free = np.zeros(n, dtype=np.int64)
        free[:free_n] = 1
        if self._sliced_ok():
            gathered = self._sliced_read(slots, free)
        else:
            from .multihost import to_host

            prog = self._gather_free_program()
            sh, loc = self._decompose(slots)
            pieces = []
            for lo, hi in self._chunk_bounds(n):
                rung = self._emit_rung(hi - lo)
                sh_p, loc_p = self._pad_slots(sh, loc, lo, hi, rung)
                free_p = np.zeros(rung, dtype=np.int64)
                free_p[: hi - lo] = free[lo:hi]
                obs_device.note_padding("mesh.gather_free", rung,
                                        hi - lo, rung)
                outs, self.state = prog(
                    self.state, self._to_dev(sh_p, False),
                    self._to_dev(loc_p, False),
                    self._to_dev(free_p, False),
                    rung=rung,
                )
                pieces.append([to_host(o)[: hi - lo] for o in outs])
            gathered = (
                pieces[0] if len(pieces) == 1
                else [
                    np.concatenate([p[i] for p in pieces])
                    for i in range(len(self.phys))
                ]
            )
        combined = self._combine_gathered(gathered, slots, seg_ids,
                                          n_segments)
        # host-side per-slot state of the freed bin drops AFTER the
        # segment maps above captured it (reset_slots would do the same)
        self._drop_udaf_slots(slots[:free_n])
        return combined

    def reset_slots(self, slots: np.ndarray):
        self._flush_if_touches(slots)
        self._drop_udaf_slots(slots)
        n = len(slots)
        if n == 0 or not self.phys:
            return
        if self._sliced_ok():
            loc_sl, _, _, L = self._slice_pack(np.asarray(slots))
            self.state = self._sliced_reset_program()(
                self.state, self._to_dev(loc_sl, True), rung=L,
            )
            return
        prog = self._reset_program()
        sh, loc = self._decompose(np.asarray(slots))
        for lo, hi in self._chunk_bounds(n):
            rung = self._emit_rung(hi - lo)
            sh_p, loc_p = self._pad_slots(sh, loc, lo, hi, rung)
            self.state = prog(
                self.state, self._to_dev(sh_p, False),
                self._to_dev(loc_p, False), rung=rung,
            )

    def restore(self, slots: np.ndarray, values: List[np.ndarray]):
        self._flush_if_touches(slots)
        values = self._restore_udaf_cols(slots, values)
        n = len(slots)
        if n == 0 or not self.phys:
            return
        if self._sliced_ok():
            vals = [
                np.asarray(v).astype(self._dt(dt), copy=False)
                for (op, dt, _, _), v in zip(self.phys, values)
            ]
            loc_sl, val_sls, _, L = self._slice_pack(
                np.asarray(slots), tuple(vals),
                tuple(self._neutral(op, dt)
                      for op, dt, _, _ in self.phys),
            )
            self.state = self._sliced_restore_program()(
                self.state, self._to_dev(loc_sl, True),
                *[self._to_dev(v, True) for v in val_sls], rung=L,
            )
            return
        prog = self._restore_program()
        sh, loc = self._decompose(np.asarray(slots))
        # pad on the emission rungs like gather/reset so restore chunk
        # sizes don't each specialize the jitted scatter; padding rows
        # write the neutral value into the scratch slot
        for lo, hi in self._chunk_bounds(n):
            rung = self._emit_rung(hi - lo)
            sh_p, loc_p = self._pad_slots(sh, loc, lo, hi, rung)
            vals_p = []
            for (op, dt, _, _), v in zip(self.phys, values):
                vp = np.full(rung, self._neutral(op, dt),
                             dtype=self._dt(dt))
                vp[: hi - lo] = np.asarray(v)[lo:hi]
                vals_p.append(vp)
            self.state = prog(
                self.state,
                self._to_dev(sh_p, False),
                self._to_dev(loc_p, False),
                *[self._to_dev(v, False) for v in vals_p],
                rung=rung,
            )
