CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (
  mn BIGINT, mx BIGINT, s BIGINT, cnt BIGINT, mean DOUBLE
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT min(counter), max(counter), sum(counter), count(*), avg(counter) FROM (
  SELECT counter, tumble(interval '10 second') as w FROM impulse GROUP BY counter, w
) GROUP BY w;
