"""Watchtower (ISSUE 13): the per-job SLO engine + breach actions.

Evaluates declarative SLO rules over the retained metric history
(`obs/history.py`) for every non-terminal job on the controller, with
hysteresis (breach/clear thresholds + sustain windows — the
`ActuationGate` warmup/cooldown pattern applied to alerting) so a
signal wobbling on a threshold cannot flap an alert. Built-in rules:

  freshness        max subtask watermark lag (the "is data flowing"
                   SLO — a stalled tenant's lag grows unboundedly);
  e2e_p99          end-to-end latency-marker p99 over the window
                   (the PR 6 Flink-style markers, windowed);
  throughput       processed/emitted rate ratio (sustained backlog);
  checkpoint       seconds since the published epoch last advanced
                   (epoch stall on a durable job);
  serve_p99        serve-gateway read latency p99 over the window;
  loop_lag         event-loop lag p99 (shared-worker contention);
  trace_drops      flight-recorder span-drop rate (the recording of
                   the NEXT incident is silently incomplete);
  conservation     exactly-once conservation breaches recorded by the
                   audit ledger (obs/audit.py) — any count above zero
                   means rows were duplicated, lost, or re-emitted.

Per-tenant / per-job threshold overrides ride `watch.overrides`.

Breach actions: every firing/cleared transition lands in a bounded
alert ledger (with the cause series' recent history attached) and in
`arroyo_watch_alerts_total`; the FIRING transition additionally
captures a diagnostic bundle — doctor verdict + flight-recorder dump +
Perfetto timeline + the metric-history window around the breach —
into a bounded on-disk spool, downloadable via
`GET /api/v1/jobs/{id}/bundles[/{n}]`. The 3am question "what was
happening when it broke" is answered by an artifact captured at the
moment the SLO engine noticed, not by whatever survived until morning.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import re
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..config import config
from ..utils.logging import get_logger
from .history import HISTORY, MetricHistory

logger = get_logger("watchtower")

_WM_LAG = "arroyo_worker_watermark_lag_seconds"
_E2E = "arroyo_worker_e2e_latency_seconds"
_RECV = "arroyo_worker_messages_recv"
_SENT = "arroyo_worker_messages_sent"
_EPOCH = "arroyo_job_published_epoch"
_SERVE = "arroyo_serve_request_seconds"
_LOOP_LAG = "arroyo_worker_loop_lag_seconds"
_TRACE_DROPS = "arroyo_trace_dropped_spans_total"
_AUDIT_BREACHES = "arroyo_audit_breaches_total"
_REPLICA_LAG = "arroyo_replica_lag_epochs"


@dataclasses.dataclass
class SLOContext:
    """What a rule signal may read: one job's identity + the history."""

    job_id: str
    tenant: str
    history: MetricHistory
    window: float
    now: float
    job: object = None  # JobHandle when evaluated on a controller


def _merge_hist_windows(series: List, window: float,
                        now: float) -> Optional[dict]:
    """Union several series' windowed histograms (e.g. every terminal
    subtask's e2e marker histogram) into one snapshot for a job-level
    quantile."""
    merged: Optional[dict] = None
    for s in series:
        h = s.hist_window(window, now)
        if not h:
            continue
        if merged is None:
            merged = {"sum": 0.0, "count": 0, "buckets": {}}
        merged["sum"] += h["sum"]
        merged["count"] += h["count"]
        for le, c in h["buckets"].items():
            merged["buckets"][le] = merged["buckets"].get(le, 0) + c
    return merged


def _windowed_p99(ctx: SLOContext, family: str, **labels) -> Optional[float]:
    from ..metrics import hist_quantiles

    series = ctx.history.get(family, **labels)
    h = _merge_hist_windows(series, ctx.window, ctx.now)
    q = hist_quantiles(h, (0.99,)) if h else {}
    return q.get("p99")


# -- built-in rule signals ----------------------------------------------------


def sig_freshness(ctx: SLOContext) -> Optional[float]:
    vals = [
        s.latest() for s in ctx.history.get(_WM_LAG, job=ctx.job_id)
    ]
    vals = [float(v) for v in vals if v is not None]
    return max(vals) if vals else None


def sig_e2e_p99(ctx: SLOContext) -> Optional[float]:
    return _windowed_p99(ctx, _E2E, job=ctx.job_id)


def sig_throughput(ctx: SLOContext) -> Optional[float]:
    """Processed-vs-produced ratio: windowed recv rate of the job's
    non-source tasks over the windowed sent rate of its source tasks.
    ~1 in steady state; sustained <1 means the pipeline consumes slower
    than the sources emit (backlog). Abstains below the source-rate
    floor or without a graph to split sources from."""
    job = ctx.job
    if job is None or getattr(job, "graph", None) is None:
        return None
    graph = job.graph
    dsts = {e.dst for e in graph.edges}
    sources = {str(nid) for nid in graph.nodes if nid not in dsts}
    if not sources or len(sources) == len(graph.nodes):
        return None

    def node_of(series) -> str:
        task = series.label("task")
        node, _, _sub = task.rpartition("-")
        return node

    sent = [
        r for r in (
            s.rate(ctx.window, ctx.now)
            for s in ctx.history.get(_SENT, job=ctx.job_id)
            if node_of(s) in sources
        ) if r is not None
    ]
    recv = [
        r for r in (
            s.rate(ctx.window, ctx.now)
            for s in ctx.history.get(_RECV, job=ctx.job_id)
            if node_of(s) not in sources
        ) if r is not None
    ]
    src_rate = sum(sent)
    if not sent or src_rate < float(config().watch.throughput_min_eps):
        return None
    # normalize by the source fan-out: each source row is received once
    # per outgoing edge of the source tier
    fan = max(1, len({e.dst for e in graph.edges
                      if str(e.src) in sources}))
    return (sum(recv) / fan) / src_rate


def sig_checkpoint_age(ctx: SLOContext) -> Optional[float]:
    job = ctx.job
    if job is not None and getattr(job, "backend", None) is None:
        return None  # non-durable jobs have no epochs to stall
    series = ctx.history.get(_EPOCH, job=ctx.job_id)
    ages = [a for a in (s.last_change_age(ctx.now) for s in series)
            if a is not None]
    return max(ages) if ages else None


def sig_serve_p99(ctx: SLOContext) -> Optional[float]:
    return _windowed_p99(ctx, _SERVE, job=ctx.job_id)


def sig_loop_lag(ctx: SLOContext) -> Optional[float]:
    return _windowed_p99(ctx, _LOOP_LAG)


def sig_conservation(ctx: SLOContext) -> Optional[float]:
    """Conservation-ledger breach count for the job (obs/audit.py):
    any recorded breach — digest/count mismatch, flow violation, rewind
    behind commit, zombie append — fires the rule. Abstains until the
    job's reconciler exists (no attested epoch yet)."""
    from . import audit

    return audit.breach_count(ctx.job_id)


def sig_replica_staleness(ctx: SLOContext) -> Optional[float]:
    """Follower read-replica lag (ISSUE 20): epochs the job's follower
    trails publication (arroyo_replica_lag_epochs). Transiently 1 while
    a tail is in flight — the threshold defaults above that so only a
    STUCK follower (storage trouble, death/reattach loop) pages, with
    the rule's sustain window supplying the time dimension. Abstains
    for jobs with no mounted follower (no series)."""
    vals = [
        s.latest() for s in ctx.history.get(_REPLICA_LAG, job=ctx.job_id)
    ]
    vals = [float(v) for v in vals if v is not None]
    return max(vals) if vals else None


def sig_trace_drops(ctx: SLOContext) -> Optional[float]:
    rates = [
        r for r in (
            s.rate(ctx.window, ctx.now)
            for s in ctx.history.get(_TRACE_DROPS)
        ) if r is not None
    ]
    return max(rates) if rates else None


@dataclasses.dataclass
class RuleSpec:
    """One resolved SLO rule: signal + hysteresis parameters. `kind`
    is 'above' (breach when value > threshold) or 'below'."""

    name: str
    description: str
    signal: Callable[[SLOContext], Optional[float]]
    kind: str
    threshold: float
    clear: float
    sustain: float
    clear_sustain: float
    cause_family: str
    unit: str = "s"

    def breached(self, value: float) -> bool:
        return value > self.threshold if self.kind == "above" \
            else value < self.threshold

    def cleared(self, value: float) -> bool:
        return value <= self.clear if self.kind == "above" \
            else value >= self.clear

    def describe(self) -> dict:
        return {
            "name": self.name, "description": self.description,
            "kind": self.kind, "threshold": self.threshold,
            "clear": self.clear, "sustain": self.sustain,
            "clear_sustain": self.clear_sustain, "unit": self.unit,
        }


# (name, description, signal, kind, config threshold attr, cause family,
# unit) — thresholds resolve from watch.* at evaluation time so config
# overrides and tests see live values
BUILTIN_RULES: Tuple[tuple, ...] = (
    ("freshness", "max subtask watermark lag", sig_freshness, "above",
     "freshness_lag_s", _WM_LAG, "s"),
    ("e2e_p99", "end-to-end latency-marker p99 over the window",
     sig_e2e_p99, "above", "e2e_p99_s", _E2E, "s"),
    ("throughput", "processed/emitted rate ratio vs the sources",
     sig_throughput, "below", "throughput_ratio", _RECV, "ratio"),
    ("checkpoint", "seconds since the published epoch advanced",
     sig_checkpoint_age, "above", "checkpoint_age_s", _EPOCH, "s"),
    ("serve_p99", "serve-gateway read latency p99 over the window",
     sig_serve_p99, "above", "serve_p99_s", _SERVE, "s"),
    ("loop_lag", "event-loop lag p99 over the window", sig_loop_lag,
     "above", "loop_lag_s", _LOOP_LAG, "s"),
    ("trace_drops", "flight-recorder span-drop rate", sig_trace_drops,
     "above", "trace_drop_rate", _TRACE_DROPS, "/s"),
    ("conservation", "exactly-once conservation breaches (audit ledger)",
     sig_conservation, "above", "conservation_breaches", _AUDIT_BREACHES,
     "count"),
    ("replica_staleness", "follower epochs behind publication",
     sig_replica_staleness, "above", "replica_lag_epochs", _REPLICA_LAG,
     "epochs"),
)


def _load_overrides(raw: str) -> dict:
    """watch.overrides: inline JSON or a JSON file path; {} on empty.
    Raises on malformed input at evaluation setup (config error, not a
    silent no-op)."""
    raw = (raw or "").strip()
    if not raw:
        return {}
    if not raw.startswith("{"):
        with open(raw) as f:
            raw = f.read()
    obj = json.loads(raw)
    if not isinstance(obj, dict):
        raise ValueError("watch.overrides must be a JSON object")
    return obj


def build_rules(tenant: str = "", job_id: str = "") -> List[RuleSpec]:
    """Resolve the built-in rules against watch.* plus any per-tenant /
    per-job overrides (`job:<id>` wins over `tenant:<t>` wins over the
    section defaults). A rule overridden with {"disabled": true} is
    omitted."""
    cfg = config().watch
    overrides = _load_overrides(cfg.overrides)
    layered: Dict[str, dict] = {}
    for scope in (f"tenant:{tenant}", f"job:{job_id}"):
        for rule, ov in (overrides.get(scope) or {}).items():
            layered.setdefault(rule, {}).update(ov or {})
    out: List[RuleSpec] = []
    for name, desc, signal, kind, attr, cause, unit in BUILTIN_RULES:
        ov = layered.get(name, {})
        if ov.get("disabled"):
            continue
        threshold = float(ov.get("threshold", getattr(cfg, attr)))
        ratio = float(cfg.clear_ratio)
        default_clear = (threshold * ratio if kind == "above"
                         else threshold / max(ratio, 1e-9))
        out.append(RuleSpec(
            name=name, description=desc, signal=signal, kind=kind,
            threshold=threshold,
            clear=float(ov.get("clear", default_clear)),
            sustain=float(ov.get("sustain", cfg.sustain)),
            clear_sustain=float(ov.get("clear_sustain",
                                       cfg.clear_sustain)),
            cause_family=cause, unit=unit,
        ))
    return out


class AlertState:
    """Hysteresis state for one (job, rule): ok -> pending (breached,
    sustaining) -> firing -> clearing (below clear threshold,
    sustaining) -> ok."""

    __slots__ = ("state", "since", "value", "fired_at", "generation")

    def __init__(self):
        self.state = "ok"
        self.since = 0.0
        self.value: Optional[float] = None
        self.fired_at: Optional[float] = None
        self.generation = 0  # firing episodes seen

    def summary(self) -> dict:
        return {
            "state": self.state,
            "value": self.value,
            "since": round(self.since, 3),
            "fired_at": self.fired_at,
            "episodes": self.generation,
        }


def _safe_name(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(s))[:80]


class Watchtower:
    """Controller-resident SLO evaluator + alert ledger + bundle spool.

    Also usable standalone (controller=None) over synthetic history in
    tests — evaluation then takes explicit (job_id, tenant) pairs."""

    def __init__(self, controller=None,
                 history: Optional[MetricHistory] = None):
        self.controller = controller
        self.history = history or HISTORY
        self.ledger: deque = deque(maxlen=int(config().watch.ledger_events))
        self.alerts: Dict[Tuple[str, str], AlertState] = {}
        self.bundle_index: List[dict] = []
        self._bundle_seq = 0
        self._spool_dir: Optional[str] = None
        self._task: Optional[asyncio.Task] = None
        self._last_remote: Tuple[float, Optional[dict]] = (0.0, None)
        self.false_positive_jobs: set = set()  # set by harness asserts

    # -- lifecycle -----------------------------------------------------------

    def maybe_start(self) -> bool:
        if not config().watch.enabled or self._task is not None:
            return False
        self._task = asyncio.ensure_future(self._loop())
        logger.info(
            "watchtower on: eval=%.1fs window=%.0fs rules=%s",
            config().watch.eval_interval, config().watch.window,
            [r[0] for r in BUILTIN_RULES],
        )
        return True

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
            self._task = None

    async def _loop(self):
        while True:
            await asyncio.sleep(float(config().watch.eval_interval))
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - the watch must survive
                logger.exception("watchtower tick failed")

    # -- the scrape pump (controller side) -----------------------------------

    def _set_job_gauges(self) -> None:
        """Controller-state gauges the SLO engine watches (published
        epoch per durable job) — set before the sample so the history
        sees them at this tick's timestamp."""
        from ..metrics import JOB_PUBLISHED_EPOCH

        if self.controller is None:
            return
        for job in self.controller.jobs.values():
            if job.backend is None or job.state.is_terminal():
                continue
            JOB_PUBLISHED_EPOCH.labels(job=job.job_id).set(
                float(job.published_epoch)
            )

    async def _scrape_remote(self, now: float) -> None:
        """Multi-process deployments: merge the pool workers' GetMetrics
        snapshots into the controller's history (embedded workers share
        this process's registry — the local sample already covers them,
        so the rpc round is skipped)."""
        ctrl = self.controller
        if ctrl is None:
            return
        try:
            from ..controller.scheduler import EmbeddedScheduler

            if isinstance(ctrl.scheduler, EmbeddedScheduler):
                return
        except Exception:  # noqa: BLE001 - scheduler import is advisory
            pass
        interval = float(config().watch.sample_interval)
        if now - self._last_remote[0] < interval:
            return
        from ..autoscale.signals import merge_snapshots

        seen: Dict[int, object] = {}
        for job in ctrl.jobs.values():
            if job.state.is_terminal():
                continue
            for w in job.workers:
                seen.setdefault(w.worker_id, w)
        snaps = []
        for w in seen.values():
            try:
                resp = await asyncio.wait_for(
                    w.client.call("WorkerGrpc", "GetMetrics", {}), 2.0
                )
                snaps.append(resp.get("snapshot") or {})
            except Exception as e:  # noqa: BLE001 - dead/slow worker
                logger.debug("watch scrape from worker %s failed: %s",
                             getattr(w, "worker_id", "?"), e)
        merged = merge_snapshots(snaps) if snaps else None
        self._last_remote = (now, merged)
        if merged:
            self.history.ingest(merged, now=now)

    def fresh_remote_snapshot(self, max_age: float) -> Optional[dict]:
        """The last remote merged snapshot if younger than `max_age` —
        lets the autoscaler reuse the watchtower's scrape instead of a
        second GetMetrics round per control period."""
        t, snap = self._last_remote
        if snap is not None and time.monotonic() - t <= max_age:
            return snap
        return None

    async def tick(self, now: Optional[float] = None) -> None:
        if not config().watch.enabled:
            return
        now = time.monotonic() if now is None else now
        self._set_job_gauges()
        self.history.sample_registry(now=now)
        await self._scrape_remote(now)
        self.evaluate(now)

    # -- evaluation ----------------------------------------------------------

    def _jobs(self) -> List[tuple]:
        """(job_id, tenant, JobHandle) for every non-terminal job."""
        if self.controller is None:
            return []
        return [
            (j.job_id, j.tenant, j)
            for j in list(self.controller.jobs.values())
            if not j.state.is_terminal()
        ]

    def evaluate(self, now: Optional[float] = None,
                 jobs: Optional[List[tuple]] = None) -> None:
        now = time.monotonic() if now is None else now
        window = float(config().watch.window)
        for job_id, tenant, job in (jobs if jobs is not None
                                    else self._jobs()):
            ctx = SLOContext(job_id=job_id, tenant=tenant,
                             history=self.history, window=window,
                             now=now, job=job)
            try:
                specs = build_rules(tenant=tenant, job_id=job_id)
            except (ValueError, OSError, json.JSONDecodeError) as e:
                logger.warning("watch.overrides invalid: %s", e)
                specs = []
            for spec in specs:
                try:
                    value = spec.signal(ctx)
                except Exception:  # noqa: BLE001 - one signal must not
                    logger.exception("watch signal %s failed", spec.name)
                    continue
                self._step(job_id, tenant, job, spec, value, now)

    # rules a hot-standby promotion legitimately blips (ISSUE 17): the
    # promoted incarnation's watermarks and latency markers start from
    # its tailed state and catch up within the failover.grace window —
    # paging on that would page on every successful sub-second failover.
    # replica_staleness joins them (ISSUE 20): the promoted generation
    # publishes under a fresh manifest lineage the follower re-tails,
    # so its lag legitimately spikes for the same bounded window.
    _FAILOVER_GRACE_RULES = ("freshness", "e2e_p99", "replica_staleness")

    def _in_failover_grace(self, job_id: str) -> bool:
        fo = getattr(self.controller, "failover", None)
        return fo is not None and fo.in_grace(job_id)

    def _step(self, job_id: str, tenant: str, job, spec: RuleSpec,
              value: Optional[float], now: float) -> None:
        st = self.alerts.setdefault((job_id, spec.name), AlertState())
        st.value = value
        if (spec.name in self._FAILOVER_GRACE_RULES
                and self._in_failover_grace(job_id)):
            # suppress NEW pages only: a pre-existing firing alert keeps
            # firing (the promotion did not fix it), but breach time
            # must not accrue against the catch-up blip
            if st.state == "pending":
                st.state = "ok"
            if st.state == "ok":
                return
        breached = value is not None and spec.breached(value)
        cleared = value is not None and spec.cleared(value)
        if st.state == "ok":
            if breached:
                st.state, st.since = "pending", now
        elif st.state == "pending":
            if not breached:
                st.state = "ok"
            elif now - st.since >= spec.sustain:
                self._fire(job_id, tenant, job, spec, st, value, now)
        elif st.state == "firing":
            if cleared:
                st.state, st.since = "clearing", now
        elif st.state == "clearing":
            if value is None:
                # no evidence either way: hold, but do not accrue clear
                # time on silence — clearing needs positive data
                st.since = now
            elif breached:
                st.state = "firing"
            elif cleared and now - st.since >= spec.clear_sustain:
                self._clear(job_id, tenant, spec, st, value, now)

    def _cause_series(self, job_id: str, spec: RuleSpec) -> List[dict]:
        window = float(config().watch.window)
        return self.history.export_job(job_id, window=window,
                                       series=spec.cause_family)

    def _ledger_event(self, event: str, job_id: str, tenant: str,
                      spec: RuleSpec, value, now: float,
                      **extra) -> dict:
        from ..metrics import WATCH_ALERTS

        ev = {
            "ts": time.time(),
            "event": event,
            "job": job_id,
            "tenant": tenant,
            "rule": spec.name,
            "value": value,
            "threshold": spec.threshold,
            "unit": spec.unit,
            "cause": self._cause_series(job_id, spec),
            **extra,
        }
        self.ledger.append(ev)
        WATCH_ALERTS.labels(job=job_id, rule=spec.name, event=event).inc()
        return ev

    def _fire(self, job_id: str, tenant: str, job, spec: RuleSpec,
              st: AlertState, value: float, now: float) -> None:
        st.state = "firing"
        st.fired_at = time.time()
        st.generation += 1
        ev = self._ledger_event(
            "firing", job_id, tenant, spec, value, now,
            sustained_s=round(now - st.since, 3), episode=st.generation,
        )
        logger.warning(
            "SLO breach: job=%s rule=%s value=%s threshold=%s (%s)",
            job_id, spec.name, value, spec.threshold, spec.unit,
        )
        try:
            self._capture_bundle(job_id, tenant, spec, ev)
        except Exception:  # noqa: BLE001 - a failed bundle must not
            logger.exception("bundle capture for %s/%s failed",
                             job_id, spec.name)

    def _clear(self, job_id: str, tenant: str, spec: RuleSpec,
               st: AlertState, value: float, now: float) -> None:
        st.state = "ok"
        fired_for = (time.time() - st.fired_at) if st.fired_at else None
        self._ledger_event(
            "cleared", job_id, tenant, spec, value, now,
            fired_for_s=round(fired_for, 3) if fired_for else None,
        )
        logger.info("SLO cleared: job=%s rule=%s value=%s", job_id,
                    spec.name, value)

    # -- diagnostic bundles --------------------------------------------------

    def spool_dir(self) -> str:
        if self._spool_dir is None:
            cfg_dir = str(config().watch.spool_dir or "").strip()
            if cfg_dir:
                self._spool_dir = cfg_dir
            else:
                import tempfile

                self._spool_dir = tempfile.mkdtemp(
                    prefix="arroyo-watch-bundles-")
            os.makedirs(self._spool_dir, exist_ok=True)
        return self._spool_dir

    def _capture_bundle(self, job_id: str, tenant: str, spec: RuleSpec,
                        alert_event: dict) -> dict:
        """The breach-triggered diagnostic bundle: everything a 3am
        responder needs, captured while the evidence is still in the
        rings."""
        from . import doctor
        from . import perfetto_trace, recorder

        n = self._bundle_seq
        self._bundle_seq += 1
        spans = recorder().snapshot(trace_prefix=f"{job_id}/")
        try:
            verdict = doctor.report(job_id)
        except Exception as e:  # noqa: BLE001 - diagnosis is best effort
            verdict = {"error": repr(e)}
        bundle = {
            "n": n,
            "job": job_id,
            "tenant": tenant,
            "rule": spec.name,
            "captured_at": time.time(),
            "alert": {k: v for k, v in alert_event.items() if k != "cause"},
            "cause": alert_event.get("cause"),
            "doctor": verdict,
            "flight_recorder": spans,
            "perfetto": perfetto_trace(spans, job=job_id),
            "history": self.history.export_job(
                job_id, window=float(config().watch.bundle_window_s),
            ),
            "ledger": [e for e in self.ledger if e.get("job") == job_id
                       and e.get("event") != "firing"]
            + [{k: v for k, v in alert_event.items() if k != "cause"}],
        }
        path = os.path.join(
            self.spool_dir(),
            f"bundle-{n:05d}-{_safe_name(job_id)}-{spec.name}.json",
        )
        with open(path, "w") as f:
            json.dump(bundle, f, default=str)
        meta = {
            "n": n, "job": job_id, "tenant": tenant, "rule": spec.name,
            "captured_at": bundle["captured_at"], "path": path,
            "bytes": os.path.getsize(path),
            "spans": len(spans),
        }
        self.bundle_index.append(meta)
        cap = int(config().watch.spool_bundles)
        while len(self.bundle_index) > cap:
            old = self.bundle_index.pop(0)
            try:
                os.unlink(old["path"])
            except OSError:
                pass
        return meta

    def bundles_for(self, job_id: Optional[str] = None) -> List[dict]:
        return [m for m in self.bundle_index
                if job_id is None or m["job"] == job_id]

    def bundle(self, n: int) -> Optional[dict]:
        for m in self.bundle_index:
            if m["n"] == n:
                try:
                    with open(m["path"]) as f:
                        return json.load(f)
                except (OSError, json.JSONDecodeError) as e:
                    return {"error": f"bundle unreadable: {e}", "meta": m}
        return None

    # -- surfaces ------------------------------------------------------------

    def alerts_for(self, job_id: str) -> dict:
        """The REST alerts payload: current rule states + the job's
        slice of the ledger."""
        return {
            "job": job_id,
            "alerts": {
                rule: st.summary()
                for (jid, rule), st in sorted(self.alerts.items())
                if jid == job_id
            },
            "firing": sorted(
                rule for (jid, rule), st in self.alerts.items()
                if jid == job_id and st.state == "firing"
            ),
            "ledger": [e for e in self.ledger if e["job"] == job_id],
        }

    def status(self, job_id: Optional[str] = None) -> dict:
        cfg = config().watch
        doc = {
            "enabled": bool(cfg.enabled and self._task is not None),
            "eval_interval": float(cfg.eval_interval),
            "window": float(cfg.window),
            "history": self.history.stats(),
            "rules": [
                {"name": r[0], "description": r[1], "kind": r[3],
                 "threshold": getattr(cfg, r[4]), "unit": r[6]}
                for r in BUILTIN_RULES
            ],
            "alerts": [
                {"job": jid, "rule": rule, **st.summary()}
                for (jid, rule), st in sorted(self.alerts.items())
                if st.state != "ok" and (job_id is None or jid == job_id)
            ],
            "firing": sum(1 for st in self.alerts.values()
                          if st.state == "firing"),
            "ledger": [
                {k: v for k, v in e.items() if k != "cause"}
                for e in self.ledger
                if job_id is None or e["job"] == job_id
            ][-64:],
            "bundles": self.bundles_for(job_id),
        }
        return doc

    def expunge_job(self, job_id: str) -> None:
        """Job-scoped GC beside Registry.drop_job: alert state machines
        of a released job are dropped (ledger events and captured
        bundles are diagnostics of the past and stay until their own
        bounds evict them)."""
        for key in [k for k in self.alerts if k[0] == job_id]:
            del self.alerts[key]

    def reset(self) -> None:
        self.alerts.clear()
        self.ledger.clear()
        self.bundle_index.clear()
        self._bundle_seq = 0
