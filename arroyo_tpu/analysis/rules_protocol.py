"""Protocol conformance rules.

Project-scope rules that cross-check the three protocol registries against
their users: the ControlMsg variants vs the runner select loop, the job
state machine's declared transitions vs the controller's actual moves, and
the chaos fault-point registry vs its call sites (generalizing the
bijection test in tests/test_chaos.py into an always-on lint).

Anchor files are located by path suffix so the same rules run against the
real tree and the miniature trees under tests/lint_fixtures/.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .core import (
    FileContext,
    Finding,
    Project,
    Rule,
    dotted_name,
    iter_functions,
    last_attr,
    register,
    str_const,
)

CONTROL_PATH = "operators/control.py"
RUNNER_PATH = "operators/runner.py"
STATE_MACHINE_PATH = "controller/state_machine.py"
CHAOS_PLAN_PATH = "chaos/plan.py"

# the runner functions that must dispatch every control-message variant
_HANDLER_FUNCS = ("_handle_control", "source_handle_control")


def _control_variants(ctx: FileContext) -> List[ast.ClassDef]:
    """Request-direction control messages: dataclasses named *Msg (the
    *Resp classes flow subtask -> controller and are dispatched there)."""
    return [
        node for node in ctx.tree.body
        if isinstance(node, ast.ClassDef) and node.name.endswith("Msg")
    ]


def _isinstance_targets(fn: ast.AST) -> Set[str]:
    """Class names tested via isinstance(_, X) anywhere in `fn`."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "isinstance"
            and len(node.args) == 2
        ):
            t = node.args[1]
            for el in t.elts if isinstance(t, ast.Tuple) else [t]:
                name = last_attr(el)
                if name:
                    out.add(name)
    return out


@register
class ControlMsgExhaustiveRule(Rule):
    id = "PRO001"
    name = "protocol-control-exhaustive"
    description = (
        "every ControlMsg variant declared in operators/control.py must be "
        "isinstance-dispatched in BOTH runner control handlers "
        "(_handle_control and source_handle_control) — an unhandled variant "
        "is silently dropped by the select loop"
    )
    scope = "project"

    def check_project(self, project: Project) -> Iterable[Finding]:
        control = project.find(CONTROL_PATH)
        runner = project.find(RUNNER_PATH)
        if control is None or runner is None:
            return ()
        variants = _control_variants(control)
        if not variants:
            return ()
        out: List[Finding] = []
        handlers = {
            fn.name: fn
            for fn in iter_functions(runner.tree)
            if fn.name in _HANDLER_FUNCS
        }
        for name in _HANDLER_FUNCS:
            if name not in handlers:
                out.append(
                    runner.finding(
                        self, runner.tree,
                        f"control handler {name}() not found in "
                        f"{runner.path} — the exhaustiveness contract has "
                        "no anchor",
                    )
                )
        for fn_name, fn in handlers.items():
            handled = _isinstance_targets(fn)
            for variant in variants:
                if variant.name not in handled:
                    out.append(
                        runner.finding(
                            self, fn,
                            f"{fn_name}() does not handle control message "
                            f"{variant.name} (declared at "
                            f"{control.path}:{variant.lineno})",
                        )
                    )
        return out


def _jobstate_members(ctx: FileContext) -> Dict[str, int]:
    """JobState enum member -> lineno."""
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "JobState":
            return {
                t.id: stmt.lineno
                for stmt in node.body
                if isinstance(stmt, ast.Assign)
                for t in stmt.targets
                if isinstance(t, ast.Name)
            }
    return {}


def _state_ref(node: ast.AST) -> Optional[str]:
    """'X' for a JobState.X attribute reference."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "JobState"
    ):
        return node.attr
    return None


def _transitions_table(ctx: FileContext):
    """Parse TRANSITIONS = {JobState.A: {JobState.B, ...}, ...}."""
    for node in ctx.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "TRANSITIONS"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        table: Dict[str, Set[str]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            key = _state_ref(k)
            if key is None:
                continue
            vals: Set[str] = set()
            if isinstance(v, (ast.Set, ast.Tuple, ast.List)):
                for el in v.elts:
                    ref = _state_ref(el)
                    if ref:
                        vals.add(ref)
            table[key] = vals
        return node, table
    return None


def _terminal_states(ctx: FileContext) -> Set[str]:
    """States named inside JobState.is_terminal()'s body."""
    for fn in iter_functions(ctx.tree):
        if fn.name == "is_terminal":
            return {
                ref for node in ast.walk(fn)
                if (ref := _state_ref(node)) is not None
            }
    return set()


@register
class StateTransitionRule(Rule):
    id = "PRO002"
    name = "protocol-state-transitions"
    description = (
        "job state moves must conform to controller/state_machine.py: every "
        "`.transition(JobState.X)` target must be declared reachable in "
        "TRANSITIONS, every non-terminal state needs an outgoing entry, and "
        "`.state = JobState.X` assignments outside the state machine bypass "
        "check_transition entirely"
    )
    scope = "project"

    def check_project(self, project: Project) -> Iterable[Finding]:
        sm = project.find(STATE_MACHINE_PATH)
        if sm is None:
            return ()
        members = _jobstate_members(sm)
        parsed = _transitions_table(sm)
        if not members or parsed is None:
            return ()
        table_node, table = parsed
        terminals = _terminal_states(sm)
        # a legal transition TARGET is one that appears in some value set;
        # being a key (having outgoing moves) does not make a state enterable
        reachable = {s for vals in table.values() for s in vals}
        out: List[Finding] = []

        # registry self-consistency
        for state in sorted(members):
            if state not in terminals and state not in table:
                out.append(
                    sm.finding(
                        self, table_node,
                        f"non-terminal state {state} has no outgoing "
                        "TRANSITIONS entry (unreachable-from or stuck state)",
                    )
                )
        for state in sorted(set(table) | reachable):
            if state not in members:
                out.append(
                    sm.finding(
                        self, table_node,
                        f"TRANSITIONS names {state}, which is not a "
                        "declared JobState member",
                    )
                )

        # users: transition() targets + direct .state assignments
        for ctx in project:
            if ctx is sm:
                continue
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call):
                    if last_attr(node.func) != "transition" or not node.args:
                        continue
                    target = _state_ref(node.args[0])
                    if target is None:
                        continue
                    if target not in members:
                        out.append(
                            ctx.finding(
                                self, node,
                                f"transition target JobState.{target} is "
                                "not a declared member",
                            )
                        )
                    elif target not in reachable:
                        out.append(
                            ctx.finding(
                                self, node,
                                f"transition to JobState.{target} is not "
                                "declared legal anywhere in TRANSITIONS",
                            )
                        )
                elif isinstance(node, ast.Assign):
                    if _state_ref(node.value) is None:
                        continue
                    if not any(
                        isinstance(t, ast.Attribute) and t.attr == "state"
                        for t in node.targets
                    ):
                        continue
                    fn = ctx.enclosing_function(node)
                    # the state machine owner itself: __init__ seeds the
                    # initial state; transition() is the checked setter
                    if fn is not None and fn.name in ("__init__", "transition"):
                        continue
                    out.append(
                        ctx.finding(
                            self, node,
                            "direct `.state = JobState.…` assignment "
                            "bypasses check_transition — use .transition()",
                        )
                    )
        return out


# PRO004: epoch/flush bookkeeping the model checker owns. Every mutation
# of these attributes must be reachable from a @protocol_effect-annotated
# handler (analysis/model/effects.py) — ad-hoc bookkeeping outside the
# modeled transitions is exactly the drift the model checker cannot see.
_EPOCH_STATE_ATTRS = ("pending_epochs", "_inflight_flushes", "_last_flush")
_MUTATING_METHODS = (
    "clear", "append", "pop", "popitem", "setdefault", "update", "extend",
    "remove", "insert",
)


def _protocol_effect_functions(ctx: FileContext) -> Set[str]:
    """Function names carrying a @protocol_effect("...") decorator."""
    out: Set[str] = set()
    for node in iter_functions(ctx.tree):
        for dec in node.decorator_list:
            if (
                isinstance(dec, ast.Call)
                and last_attr(dec.func) == "protocol_effect"
                and dec.args
                and str_const(dec.args[0]) is not None
            ):
                out.add(node.name)
    return out


def _called_names(fn: ast.AST) -> Set[str]:
    """Function names `fn` calls (self.x(...) or x(...))."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = last_attr(node.func)
            if name:
                out.add(name)
    return out


def _reachable_from_handlers(ctx: FileContext) -> Set[str]:
    """Annotated handlers plus everything they transitively call within
    this file (simple name-based call graph — the dispatch code keeps its
    epoch bookkeeping in methods of one class per file)."""
    graph: Dict[str, Set[str]] = {
        fn.name: _called_names(fn) for fn in iter_functions(ctx.tree)
    }
    reach = set(_protocol_effect_functions(ctx))
    work = list(reach)
    while work:
        cur = work.pop()
        for callee in graph.get(cur, ()):
            if callee in graph and callee not in reach:
                reach.add(callee)
                work.append(callee)
    return reach


def _watched_attr(node: ast.AST) -> Optional[str]:
    """The watched attribute name when `node` is (or indexes) one."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _EPOCH_STATE_ATTRS:
        return node.attr
    return None


def _flatten_targets(targets) -> List[ast.AST]:
    out: List[ast.AST] = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            out.extend(_flatten_targets(t.elts))
        else:
            out.append(t)
    return out


@register
class EpochBookkeepingRule(Rule):
    id = "PRO004"
    name = "protocol-epoch-bookkeeping"
    description = (
        "every mutation of pending_epochs / _inflight_flushes / "
        "_last_flush must be reachable from a @protocol_effect-annotated "
        "state-machine handler (or __init__ seeding) — ad-hoc epoch "
        "bookkeeping outside the modeled transitions cannot be verified "
        "by the protocol model checker (analysis/model/)"
    )
    scope = "file"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        # cheap pre-filter: most files never touch the watched attrs
        if not any(a in ctx.source for a in _EPOCH_STATE_ATTRS):
            return ()
        reachable = _reachable_from_handlers(ctx)
        out: List[Finding] = []

        def site_ok(node: ast.AST) -> bool:
            fn = ctx.enclosing_function(node)
            if fn is None:
                return False
            return fn.name == "__init__" or fn.name in reachable

        def flag(node: ast.AST, attr: str, how: str):
            if not site_ok(node):
                fn = ctx.enclosing_function(node)
                where = fn.name + "()" if fn is not None else "module scope"
                out.append(ctx.finding(
                    self, node,
                    f"{how} of {attr} in {where}, which is not reachable "
                    "from any @protocol_effect-annotated handler — the "
                    "model checker cannot account for this epoch "
                    "bookkeeping",
                ))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in _flatten_targets(targets):
                    attr = _watched_attr(t)
                    if attr:
                        flag(node, attr, "assignment")
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = _watched_attr(t)
                    if attr:
                        flag(node, attr, "deletion")
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATING_METHODS
                ):
                    attr = _watched_attr(node.func.value)
                    if attr:
                        flag(node, attr, f".{node.func.attr}() mutation")
        return out


def _fault_points(ctx: FileContext):
    """Parse FAULT_POINTS = {"name": ..., ...} -> {name: lineno}."""
    for node in ctx.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
                for t in targets
            ):
                continue
            value = node.value
            if not isinstance(value, ast.Dict):
                return None
            return node, {
                s: k.lineno
                for k in value.keys
                if (s := str_const(k)) is not None
            }
    return None


@register
class ChaosRegistryRule(Rule):
    id = "PRO003"
    name = "protocol-chaos-registry"
    description = (
        "chaos.fire() call sites and the FAULT_POINTS registry must stay a "
        "bijection: every fired point literal registered, every registered "
        "point fired somewhere, and fault-point names passed as literals so "
        "the mapping is statically checkable"
    )
    scope = "project"

    def check_project(self, project: Project) -> Iterable[Finding]:
        plan = project.find(CHAOS_PLAN_PATH)
        if plan is None:
            return ()
        parsed = _fault_points(plan)
        if parsed is None:
            return ()
        reg_node, points = parsed
        out: List[Finding] = []
        seen: Set[str] = set()
        plan_dir = plan.path.rsplit("/", 1)[0] if "/" in plan.path else ""
        for ctx in project:
            # the chaos package itself (plan/drill/__init__) manipulates
            # points generically; the bijection is about engine seams
            if plan_dir and ctx.path.startswith(plan_dir + "/"):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None or (
                    name != "chaos.fire" and not name.endswith(".chaos.fire")
                ):
                    continue
                point = str_const(node.args[0]) if node.args else None
                if point is None:
                    out.append(
                        ctx.finding(
                            self, node,
                            "chaos.fire() point must be a string literal "
                            "(static registry check is impossible otherwise)",
                        )
                    )
                    continue
                seen.add(point)
                if point not in points:
                    out.append(
                        ctx.finding(
                            self, node,
                            f"chaos.fire({point!r}) is not registered in "
                            f"FAULT_POINTS ({plan.path})",
                        )
                    )
        for point in sorted(set(points) - seen):
            out.append(
                plan.finding(
                    self, reg_node,
                    f"fault point {point!r} is registered but has no "
                    "chaos.fire() call site — dead registry entry",
                )
            )
        return out
