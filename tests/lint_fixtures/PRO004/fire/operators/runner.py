"""PRO004 firing fixture: epoch bookkeeping outside annotated handlers."""


def protocol_effect(name):
    def deco(fn):
        return fn
    return deco


class SubtaskRunner:
    def __init__(self):
        self._inflight_flushes = []  # seeding in __init__ is fine
        self.pending_epochs = {}

    @protocol_effect("worker.capture")
    async def _checkpoint_chain(self, barrier):
        self._inflight_flushes.append(barrier)  # annotated: fine

    async def _sneaky_cleanup(self):
        # NOT annotated and not called from any annotated handler:
        # the model checker cannot account for this mutation
        self._inflight_flushes = []
        self.pending_epochs.clear()

    async def _drop_epoch(self, epoch):
        del self.pending_epochs[epoch]  # same: ad-hoc deletion
