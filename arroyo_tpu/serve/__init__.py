"""StateServe: the queryable-state serving tier (ISSUE 12, ROADMAP 2).

A partition-aware read path from HTTP request to worker-resident state
and back — Flink queryable state (Carbone et al., VLDB'17) built on this
engine's own epoch machinery, with a dash of Noria (Gjengset et al.,
OSDI'18): reads are served from the dataflow's keyed views, not from
sink output files.

  * `store.py` — worker-side epoch-consistent views. Keyed operators
    (windowed aggregates, updating aggregates) stage each emitted
    (key -> aggregate) row into a per-operator `ServeView`; the runner
    SEALS the staged rows at every checkpoint capture, stamping them
    with the barrier's epoch (reusing PR 8's epoch-stamped capture
    machinery), and reads fold sealed epochs up to the last *published*
    epoch — so a read never observes a half-captured checkpoint and
    needs no barrier coordination.
  * `gateway.py` — the controller-resident router: key -> owning
    worker/subtask via the same splitmix64 hash-range ownership map the
    shuffle and rescale re-read use, bulk fan-out, a read-through cache
    invalidated by published epoch, per-tenant QPS admission (wired to
    the PR 11 doctor's noisy-neighbor verdict), and incarnation
    fencing across rescale/recovery (PR 10's `{job}@{schedules}` route
    namespaces).

Surfaces: `GET/POST /api/v1/jobs/{id}/state[/{table}]` REST routes,
`/debug/serve` on the controller admin server, and the `arroyo_serve_*`
metric families (request latency, cache hit ratio, per-tenant QPS)
flowing into the per-tenant attribution pump.
"""

from .store import (  # noqa: F401 - public surface
    META_KEY,
    SERVE_TABLE,
    ServeView,
    owner_subtask,
    register_op,
    seal_op,
    serve_mirror_tables,
    stage_batch,
    worker_read,
)
from .gateway import StateGateway  # noqa: F401
