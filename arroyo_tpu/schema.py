"""StreamSchema — the engine's schema wrapper.

Capability parity with the reference's `ArroyoSchema`
(/root/reference/crates/arroyo-rpc/src/df.rs:24): a pyarrow schema plus the
index of the mandatory `_timestamp` column (TimestampNanosecond) and the
routing-key column indices used for hash shuffles and state sharding.
Every batch flowing through the engine conforms to a StreamSchema.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence

import numpy as np
import pyarrow as pa

from .types import hash_arrays, hash_column, server_for_hash_array

TIMESTAMP_FIELD = "_timestamp"
TIMESTAMP_TYPE = pa.timestamp("ns")

# Metadata column carried on updating (retract) streams; mirrors the
# reference's `__updating_meta` struct column (arroyo-rpc/src/lib.rs:333).
UPDATING_META_FIELD = "__updating_meta"
UPDATING_META_TYPE = pa.struct(
    [pa.field("is_retract", pa.bool_()), pa.field("id", pa.binary(16))]
)


def updating_meta_array(n: int, is_retract: bool) -> "pa.StructArray":
    """__updating_meta column for n rows (random ids, shared by the
    updating aggregate and updating join)."""
    import os

    blob = os.urandom(16 * n)
    return pa.StructArray.from_arrays(
        [
            pa.array([is_retract] * n),
            pa.array(
                [blob[16 * i: 16 * (i + 1)] for i in range(n)],
                type=pa.binary(16),
            ),
        ],
        names=["is_retract", "id"],
    )


def add_timestamp_field(schema: pa.Schema) -> pa.Schema:
    """Append `_timestamp` if absent (reference: planner schemas.rs
    add_timestamp_field)."""
    if TIMESTAMP_FIELD in schema.names:
        return schema
    return schema.append(pa.field(TIMESTAMP_FIELD, TIMESTAMP_TYPE, nullable=False))


@dataclasses.dataclass(frozen=True)
class StreamSchema:
    schema: pa.Schema
    key_indices: tuple[int, ...] = ()  # routing key columns (hash shuffle)

    def __post_init__(self):
        if TIMESTAMP_FIELD not in self.schema.names:
            object.__setattr__(self, "schema", add_timestamp_field(self.schema))

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_fields(
        fields: Sequence[tuple[str, pa.DataType]],
        key_names: Iterable[str] = (),
    ) -> "StreamSchema":
        schema = add_timestamp_field(pa.schema([pa.field(n, t) for n, t in fields]))
        keys = tuple(schema.names.index(k) for k in key_names)
        return StreamSchema(schema, keys)

    def with_keys(self, key_names: Iterable[str]) -> "StreamSchema":
        return StreamSchema(
            self.schema, tuple(self.schema.names.index(k) for k in key_names)
        )

    def without_keys(self) -> "StreamSchema":
        return StreamSchema(self.schema, ())

    # -- accessors ----------------------------------------------------------

    @property
    def timestamp_index(self) -> int:
        return self.schema.names.index(TIMESTAMP_FIELD)

    @property
    def names(self) -> list[str]:
        return list(self.schema.names)

    @property
    def key_names(self) -> list[str]:
        return [self.schema.names[i] for i in self.key_indices]

    def field_index(self, name: str) -> int:
        idx = self.schema.names.index(name)
        return idx

    def is_updating(self) -> bool:
        return UPDATING_META_FIELD in self.schema.names

    # -- batch helpers ------------------------------------------------------

    def empty_batch(self) -> pa.RecordBatch:
        return pa.RecordBatch.from_arrays(
            [pa.array([], type=f.type) for f in self.schema], schema=self.schema
        )

    def timestamps(self, batch: pa.RecordBatch) -> np.ndarray:
        """int64 nanos view of the _timestamp column."""
        col = batch.column(self.timestamp_index)
        return np.asarray(col.cast(pa.int64()))

    def hash_keys(self, batch: pa.RecordBatch) -> np.ndarray:
        """uint64 hash of the routing-key columns, the canonical hash used by
        shuffle + state sharding. Unkeyed schemas hash to zeros. Struct
        columns (e.g. window structs) hash their children in order."""
        if not self.key_indices:
            return np.zeros(batch.num_rows, dtype=np.uint64)
        cols = []
        for i in self.key_indices:
            col = batch.column(i)
            if pa.types.is_struct(col.type):
                for j in range(col.type.num_fields):
                    cols.append(_hash_one(col.field(j)))
                continue
            cols.append(_hash_one(col))
        return hash_arrays(cols)

    def partition(self, batch: pa.RecordBatch, n: int) -> list[Optional[pa.RecordBatch]]:
        """Split a batch into n per-partition sub-batches by key hash range
        (reference: arroyo-operator context.rs repartition). Returns None for
        empty partitions to avoid allocating empty batches."""
        if n == 1:
            return [batch]
        parts = server_for_hash_array(self.hash_keys(batch), n)
        order = np.argsort(parts, kind="stable")
        sorted_parts = parts[order]
        boundaries = np.searchsorted(sorted_parts, np.arange(n + 1))
        indices = pa.array(order)
        taken = batch.take(indices)
        out: list[Optional[pa.RecordBatch]] = []
        for i in range(n):
            lo, hi = int(boundaries[i]), int(boundaries[i + 1])
            out.append(taken.slice(lo, hi - lo) if hi > lo else None)
        return out


def _hash_one(col: pa.Array) -> np.ndarray:
    if col.null_count:
        # nulls hash as a fixed sentinel: substitute before hashing
        col = col.fill_null(_null_sentinel(col.type))
    return hash_column(_to_numpy(col))


def _to_numpy(col: pa.Array) -> np.ndarray:
    try:
        return col.to_numpy(zero_copy_only=False)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        return np.array(col.to_pylist(), dtype=object)


def _null_sentinel(t: pa.DataType):
    if pa.types.is_integer(t):
        return -(1 << 62) + 12345
    if pa.types.is_floating(t):
        return float("-1.797e308")
    if pa.types.is_boolean(t):
        return False
    if pa.types.is_timestamp(t):
        return 0
    return "\x00__null__"
