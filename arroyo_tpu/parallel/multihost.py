"""Multi-host mesh runtime: `jax.distributed` across worker processes.

A real TPU pod slice spans HOSTS — each process addresses only its local
chips (4 on v5e), and the global mesh exists only after every process
calls `jax.distributed.initialize` with a shared coordinator. The
reference's multi-worker scale-out is its TCP shuffle
(/root/reference/crates/arroyo-worker/src/network_manager.rs:551-605);
the TPU-native replacement keeps the shuffle INSIDE the jitted step as
XLA collectives over ICI, which requires this process-spanning mesh.

Wiring (SURVEY.md §5.8): the controller assigns
(coordinator address, process count, process id) at scheduling time —
`controller/scheduler.py` injects them into each spawned worker's env as
`ARROYO__TPU__MESH_*` config overrides — and `worker_main` calls
`ensure_initialized()` BEFORE any jax backend init. Operators then build
meshes from the global device list exactly as in single-host mode.

Execution model: mesh-mode operators run SPMD — every mesh process packs
the SAME batch (the host data plane broadcasts batches to mesh peers)
and executes the same jitted step in lockstep; each process materializes
only its addressable shards (`put_global`) and reads back replicated
outputs from its local copy (`to_host`).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

from ..utils.logging import get_logger

logger = get_logger("multihost")

_lock = threading.Lock()
_initialized: Optional[Tuple[int, int]] = None  # (num_processes, process_id)


def _settings() -> Tuple[str, int, int]:
    from ..config import config

    tpu = config().tpu
    return tpu.mesh_coordinator, int(tpu.mesh_processes), int(
        tpu.mesh_process_id)


def ensure_initialized() -> Tuple[int, int]:
    """Idempotently initialize `jax.distributed` when this process is
    part of a multi-process mesh (tpu.mesh_processes >= 2, assigned by
    the controller). Returns (num_processes, process_id) — (1, 0) in
    single-process deployments. Must run before the first jax backend
    init in the process."""
    global _initialized
    with _lock:
        if _initialized is not None:
            return _initialized
        coord, n_proc, pid = _settings()
        if n_proc < 2:
            _initialized = (1, 0)
            return _initialized
        if not coord or pid < 0:
            raise ValueError(
                f"tpu.mesh_processes={n_proc} requires mesh_coordinator "
                f"and mesh_process_id (got {coord!r}, {pid})"
            )
        import jax

        logger.info(
            "joining %d-process mesh as rank %d (coordinator %s)",
            n_proc, pid, coord,
        )
        try:
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=n_proc,
                process_id=pid,
            )
        except Exception as e:
            # the most common cause: the controller auto-picked the
            # coordinator port (bind-then-close in controller/scheduler.py
            # pick_coordinator) and something else bound it before rank 0's
            # jax coordinator service came up — name the address and the
            # fix instead of surfacing jax's bare connect error
            raise RuntimeError(
                f"worker rank {pid}/{n_proc} failed to join the "
                f"jax.distributed mesh at coordinator {coord!r}: {e!r}. "
                "If the coordinator address was auto-picked by the "
                "controller, the bind-then-close port reservation may have "
                "been lost to a race; pin a stable address with "
                "tpu.mesh_coordinator (env ARROYO__TPU__MESH_COORDINATOR), "
                "reachable from every worker — rank 0 binds it."
            ) from e
        _initialized = (n_proc, pid)
        return _initialized


def process_info() -> Tuple[int, int]:
    """(num_processes, process_id) as initialized; (1, 0) before/without
    multi-process init."""
    return _initialized if _initialized is not None else (1, 0)


def is_multiprocess_mesh(mesh) -> bool:
    """Does this mesh span devices owned by more than one process?"""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def put_global(np_arr, mesh, spec):
    """Place a host array onto a (possibly multi-process) mesh sharding.

    Every mesh process passes the SAME global value (lockstep SPMD — the
    data plane broadcast guarantees it); only locally-addressable shards
    are materialized. Single-process meshes take the direct device_put
    fast path."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if not is_multiprocess_mesh(mesh):
        return jax.device_put(np_arr, sharding)
    return jax.make_array_from_callback(
        np_arr.shape, sharding, lambda idx: np_arr[idx]
    )


def to_host(arr):
    """Read a device array back to numpy. Fully-addressable arrays (all
    single-process cases) convert directly; a replicated output on a
    multi-process mesh is read from this process's local copy."""
    import numpy as np

    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    return np.asarray(arr.addressable_data(0))


def env_overrides(coordinator: str, num_processes: int,
                  process_id: int) -> dict:
    """Config-layer env vars the scheduler injects into a spawned
    worker so its `ensure_initialized()` joins the job's mesh."""
    return {
        "ARROYO__TPU__MESH_COORDINATOR": coordinator,
        "ARROYO__TPU__MESH_PROCESSES": str(num_processes),
        "ARROYO__TPU__MESH_PROCESS_ID": str(process_id),
    }
