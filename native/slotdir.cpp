// Native slot directory: the host-side (bin, key) -> accumulator-slot hash
// table on the window operators' per-batch path.
//
// The reference engine's equivalent hot structure is the per-bin DataFusion
// hash-aggregation state (/root/reference/crates/arroyo-worker/src/arrow/
// tumbling_aggregating_window.rs) maintained in native Rust; here the
// directory is the piece of per-row work that stays on the host next to the
// XLA scatter-reduce, so it gets the native treatment: an open-addressing
// table over (bin i64, key i64) pairs with splitmix64 probing, a slot free
// list, and per-bin entry chains for O(bin size) emission.
//
// Exposed to Python via the raw CPython API (no pybind11 in this image);
// arrays cross the boundary through the buffer protocol (numpy int64).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Entry {
    int64_t bin;
    int64_t slot;
    int32_t next_in_bin;  // index of next entry of the same bin, -1 = end
    uint8_t live;
    // key words live in SlotDir::keypool at [idx*stride, (idx+1)*stride):
    // entry indices are recycled, so the pool space recycles with them
};

struct BinHead {
    int64_t bin;
    int32_t head;   // first entry index
    int32_t count;  // live entries in this bin
    uint8_t used;
};

static inline uint64_t splitmix64(uint64_t x) {
    x += 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    return x ^ (x >> 31);
}

static inline uint64_t hash_row(int64_t bin, const int64_t* keys,
                                int stride) {
    uint64_t h = splitmix64((uint64_t)bin);
    for (int j = 0; j < stride; j++) h = splitmix64(h ^ (uint64_t)keys[j]);
    return h;
}

struct SlotDir {
    PyObject_HEAD
    // open-addressing index: maps hash(bin,keys) -> entry idx (+1, 0=empty)
    std::vector<int32_t>* index;
    std::vector<Entry>* entries;
    std::vector<int64_t>* keypool;       // stride words per entry index
    std::vector<int32_t>* free_entries;  // recycled entry indices
    std::vector<int64_t>* free_slots;
    // slot id -> entry idx + 1 (0 = slot free): slots are dense
    // (counter + free list), so a flat vector serves the reverse
    // lookups the updating aggregate's dirty tracking needs
    std::vector<int32_t>* slot_owner;
    std::vector<BinHead>* bin_index;  // open addressing over bins
    int64_t next_slot;
    int64_t n_live;
    int64_t n_used;      // index slots holding a ref (live or dead)
    int64_t n_bins_used; // bin heads marked used (live or emptied)
    size_t mask;
    size_t bin_mask;
    int stride;          // int64 key words per entry (>= 1)
};

static inline const int64_t* entry_keys(const SlotDir* self, size_t idx) {
    return self->keypool->data() + idx * self->stride;
}

static void rehash(SlotDir* self, size_t new_size) {
    std::vector<int32_t> fresh(new_size, 0);
    size_t mask = new_size - 1;
    for (size_t i = 0; i < self->entries->size(); i++) {
        const Entry& e = (*self->entries)[i];
        if (!e.live) continue;
        size_t h = hash_row(e.bin, entry_keys(self, i), self->stride) & mask;
        while (fresh[h] != 0) h = (h + 1) & mask;
        fresh[h] = (int32_t)i + 1;
    }
    self->index->swap(fresh);
    self->mask = mask;
    self->n_used = self->n_live;  // dead refs dropped by the rebuild
}

static void bin_rehash(SlotDir* self, size_t new_size) {
    std::vector<BinHead> fresh(new_size);
    size_t mask = new_size - 1;
    int64_t used = 0;
    for (const BinHead& b : *self->bin_index) {
        if (!b.used || b.count == 0) continue;  // emptied heads drop here
        size_t h = splitmix64((uint64_t)b.bin) & mask;
        while (fresh[h].used) h = (h + 1) & mask;
        fresh[h] = b;
        used++;
    }
    self->bin_index->swap(fresh);
    self->bin_mask = mask;
    self->n_bins_used = used;
}

static BinHead* bin_lookup(SlotDir* self, int64_t bin, bool create) {
    // occupancy counts USED heads (incl. emptied bins, which only a rehash
    // reclaims) so the probe loops below always find a free stop slot
    if (create && (self->n_bins_used + 1) * 2 > (int64_t)self->bin_index->size()) {
        size_t size = self->bin_index->size();
        // grow only if live bins actually need the room
        int64_t live_bins = 0;
        for (const BinHead& b : *self->bin_index)
            if (b.used && b.count > 0) live_bins++;
        if ((live_bins + 1) * 2 > (int64_t)size) size *= 2;
        bin_rehash(self, size);
    }
    size_t h = splitmix64((uint64_t)bin) & self->bin_mask;
    for (;;) {
        BinHead& b = (*self->bin_index)[h];
        if (!b.used) {
            if (!create) return nullptr;
            b.used = 1;
            b.bin = bin;
            b.head = -1;
            b.count = 0;
            self->n_bins_used += 1;
            return &b;
        }
        if (b.bin == bin && b.count >= 0) return &b;
        h = (h + 1) & self->bin_mask;
    }
}

static PyObject* SlotDir_new(PyTypeObject* type, PyObject* args, PyObject*) {
    int n_keys = 1;
    if (args && !PyArg_ParseTuple(args, "|i", &n_keys)) return nullptr;
    SlotDir* self = (SlotDir*)type->tp_alloc(type, 0);
    if (!self) return nullptr;
    self->index = new std::vector<int32_t>(4096, 0);
    self->entries = new std::vector<Entry>();
    self->keypool = new std::vector<int64_t>();
    self->free_entries = new std::vector<int32_t>();
    self->free_slots = new std::vector<int64_t>();
    self->slot_owner = new std::vector<int32_t>();
    self->bin_index = new std::vector<BinHead>(1024);
    self->next_slot = 0;
    self->n_live = 0;
    self->n_used = 0;
    self->n_bins_used = 0;
    self->mask = 4095;
    self->bin_mask = 1023;
    self->stride = n_keys < 1 ? 1 : n_keys;
    return (PyObject*)self;
}

static void SlotDir_dealloc(SlotDir* self) {
    delete self->index;
    delete self->entries;
    delete self->keypool;
    delete self->free_entries;
    delete self->free_slots;
    delete self->slot_owner;
    delete self->bin_index;
    Py_TYPE(self)->tp_free((PyObject*)self);
}

static int get_i64_buffer(PyObject* obj, Py_buffer* view) {
    if (PyObject_GetBuffer(obj, view, PyBUF_CONTIG_RO | PyBUF_FORMAT) != 0)
        return -1;
    if (view->itemsize != 8) {
        PyBuffer_Release(view);
        PyErr_SetString(PyExc_TypeError, "expected int64 array");
        return -1;
    }
    return 0;
}

// assign(bins, keys) -> bytes holding int64 slots. keys is row-major
// int64 with `stride` words per row (n_rows * stride total).
static PyObject* SlotDir_assign(SlotDir* self, PyObject* args) {
    PyObject *bins_obj, *keys_obj;
    if (!PyArg_ParseTuple(args, "OO", &bins_obj, &keys_obj)) return nullptr;
    Py_buffer bins, keys;
    if (get_i64_buffer(bins_obj, &bins) != 0) return nullptr;
    if (get_i64_buffer(keys_obj, &keys) != 0) {
        PyBuffer_Release(&bins);
        return nullptr;
    }
    Py_ssize_t n = bins.len / 8;
    const int stride = self->stride;
    if (keys.len / 8 != n * stride) {
        PyBuffer_Release(&bins);
        PyBuffer_Release(&keys);
        PyErr_SetString(PyExc_ValueError,
                        "keys length != n_rows * stride");
        return nullptr;
    }
    PyObject* out = PyBytes_FromStringAndSize(nullptr, n * 8);
    if (!out) {
        PyBuffer_Release(&bins);
        PyBuffer_Release(&keys);
        return nullptr;
    }
    int64_t* slots = (int64_t*)PyBytes_AS_STRING(out);
    const int64_t* b = (const int64_t*)bins.buf;
    const int64_t* k = (const int64_t*)keys.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
        const int64_t* krow = k + i * stride;
        // occupancy (live + tombstoned refs) drives the load factor; a
        // rehash drops tombstones, growing only when live entries need it
        if ((self->n_used + 1) * 4 > (int64_t)self->index->size() * 3) {
            size_t size = self->index->size();
            if ((self->n_live + 1) * 4 > (int64_t)size * 3) size *= 2;
            rehash(self, size);
        }
        size_t h = hash_row(b[i], krow, stride) & self->mask;
        int32_t entry_idx = -1;
        int64_t first_dead = -1;
        for (;;) {
            int32_t slot_ref = (*self->index)[h];
            if (slot_ref == 0) break;
            Entry& e = (*self->entries)[slot_ref - 1];
            if (e.live && e.bin == b[i] &&
                memcmp(entry_keys(self, slot_ref - 1), krow,
                       stride * sizeof(int64_t)) == 0) {
                entry_idx = slot_ref - 1;
                break;
            }
            if (!e.live && first_dead < 0) first_dead = (int64_t)h;
            h = (h + 1) & self->mask;
        }
        if (entry_idx >= 0) {
            slots[i] = (*self->entries)[entry_idx].slot;
            continue;
        }
        if (first_dead >= 0) {
            h = (size_t)first_dead;  // reuse a tombstoned index slot
            self->n_used -= 1;       // net zero after the insert below
        }
        int64_t slot;
        if (!self->free_slots->empty()) {
            slot = self->free_slots->back();
            self->free_slots->pop_back();
        } else {
            slot = self->next_slot++;
        }
        int32_t idx;
        if (!self->free_entries->empty()) {
            idx = self->free_entries->back();
            self->free_entries->pop_back();
        } else {
            idx = (int32_t)self->entries->size();
            self->entries->push_back(Entry());
            self->keypool->resize(self->entries->size() * stride);
        }
        BinHead* bh = bin_lookup(self, b[i], true);
        Entry& e = (*self->entries)[idx];
        e.bin = b[i];
        memcpy(self->keypool->data() + (size_t)idx * stride, krow,
               stride * sizeof(int64_t));
        e.slot = slot;
        e.live = 1;
        e.next_in_bin = bh->head;
        bh->head = idx;
        bh->count += 1;
        (*self->index)[h] = idx + 1;
        if ((size_t)slot >= self->slot_owner->size())
            self->slot_owner->resize((size_t)slot + 1, 0);
        (*self->slot_owner)[(size_t)slot] = idx + 1;
        self->n_live += 1;
        self->n_used += 1;
        slots[i] = slot;
    }
    PyBuffer_Release(&bins);
    PyBuffer_Release(&keys);
    return out;
}

// take_bin(bin) -> (keys_bytes, slots_bytes); removes the bin. keys carry
// stride int64 words per entry, row-major.
static PyObject* SlotDir_take_bin(SlotDir* self, PyObject* args) {
    int64_t bin;
    if (!PyArg_ParseTuple(args, "L", &bin)) return nullptr;
    BinHead* bh = bin_lookup(self, bin, false);
    int32_t count = bh ? bh->count : 0;
    const int stride = self->stride;
    PyObject* keys = PyBytes_FromStringAndSize(
        nullptr, (Py_ssize_t)count * 8 * stride);
    PyObject* slots = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)count * 8);
    if (!keys || !slots) return nullptr;
    int64_t* kout = (int64_t*)PyBytes_AS_STRING(keys);
    int64_t* sout = (int64_t*)PyBytes_AS_STRING(slots);
    if (bh) {
        int32_t idx = bh->head;
        int32_t i = 0;
        while (idx >= 0) {
            Entry& e = (*self->entries)[idx];
            memcpy(kout + (size_t)i * stride, entry_keys(self, idx),
                   stride * sizeof(int64_t));
            sout[i] = e.slot;
            i++;
            // remove from the open-addressing index lazily: mark dead and
            // reinsert cost is avoided by tombstone-free probing on rehash
            e.live = 0;
            self->free_entries->push_back(idx);
            self->free_slots->push_back(e.slot);
            (*self->slot_owner)[(size_t)e.slot] = 0;
            idx = e.next_in_bin;
        }
        self->n_live -= bh->count;
        bh->count = 0;
        bh->head = -1;
        // rebuild the index when dead entries dominate (keeps probes short)
        if ((int64_t)self->free_entries->size() > self->n_live + 1024)
            rehash(self, self->index->size());
    }
    return Py_BuildValue("(NN)", keys, slots);
}

// get_bin(bin) -> (keys_bytes, slots_bytes) WITHOUT removing (sliding merge)
static PyObject* SlotDir_get_bin(SlotDir* self, PyObject* args) {
    int64_t bin;
    if (!PyArg_ParseTuple(args, "L", &bin)) return nullptr;
    BinHead* bh = bin_lookup(self, bin, false);
    int32_t count = bh ? bh->count : 0;
    const int stride = self->stride;
    PyObject* keys = PyBytes_FromStringAndSize(
        nullptr, (Py_ssize_t)count * 8 * stride);
    PyObject* slots = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)count * 8);
    if (!keys || !slots) return nullptr;
    int64_t* kout = (int64_t*)PyBytes_AS_STRING(keys);
    int64_t* sout = (int64_t*)PyBytes_AS_STRING(slots);
    if (bh) {
        int32_t idx = bh->head;
        int32_t i = 0;
        while (idx >= 0) {
            const Entry& e = (*self->entries)[idx];
            memcpy(kout + (size_t)i * stride, entry_keys(self, idx),
                   stride * sizeof(int64_t));
            sout[i] = e.slot;
            i++;
            idx = e.next_in_bin;
        }
    }
    return Py_BuildValue("(NN)", keys, slots);
}

// get_bins(bins_i64) -> (keys_bytes, slots_bytes) concatenated over the
// requested bins, WITHOUT removing — the sliding-window merge reads
// width/slide bins per emission and only ever concatenates them, so one
// batched crossing replaces k get_bin calls (and k python-side concats).
static PyObject* SlotDir_get_bins(SlotDir* self, PyObject* args) {
    PyObject* bins_obj;
    if (!PyArg_ParseTuple(args, "O", &bins_obj)) return nullptr;
    Py_buffer bins;
    if (get_i64_buffer(bins_obj, &bins) != 0) return nullptr;
    Py_ssize_t nb = bins.len / 8;
    const int64_t* bq = (const int64_t*)bins.buf;
    const int stride = self->stride;
    // size pass: total live entries across the requested bins
    Py_ssize_t total = 0;
    for (Py_ssize_t i = 0; i < nb; i++) {
        BinHead* bh = bin_lookup(self, bq[i], false);
        if (bh) total += bh->count;
    }
    PyObject* keys = PyBytes_FromStringAndSize(
        nullptr, total * 8 * stride);
    PyObject* slots = PyBytes_FromStringAndSize(nullptr, total * 8);
    if (!keys || !slots) {
        PyBuffer_Release(&bins);
        Py_XDECREF(keys);
        Py_XDECREF(slots);
        return nullptr;
    }
    int64_t* kout = (int64_t*)PyBytes_AS_STRING(keys);
    int64_t* sout = (int64_t*)PyBytes_AS_STRING(slots);
    Py_ssize_t i_out = 0;
    for (Py_ssize_t i = 0; i < nb; i++) {
        BinHead* bh = bin_lookup(self, bq[i], false);
        if (!bh) continue;
        int32_t idx = bh->head;
        while (idx >= 0) {
            const Entry& e = (*self->entries)[idx];
            memcpy(kout + (size_t)i_out * stride, entry_keys(self, idx),
                   stride * sizeof(int64_t));
            sout[i_out] = e.slot;
            i_out++;
            idx = e.next_in_bin;
        }
    }
    PyBuffer_Release(&bins);
    return Py_BuildValue("(NN)", keys, slots);
}

// keys_for_slots(slots_bytes) -> (present_bytes u8, bins_bytes, keys_bytes):
// resolve slots back to their live (bin, key) via the reverse index —
// O(len(slots)), the updating aggregate's per-batch dirty tracking.
static PyObject* SlotDir_keys_for_slots(SlotDir* self, PyObject* args) {
    PyObject* slots_obj;
    if (!PyArg_ParseTuple(args, "O", &slots_obj)) return nullptr;
    Py_buffer slots;
    if (get_i64_buffer(slots_obj, &slots) != 0) return nullptr;
    Py_ssize_t n = slots.len / 8;
    const int stride = self->stride;
    PyObject* present = PyBytes_FromStringAndSize(nullptr, n);
    PyObject* bins = PyBytes_FromStringAndSize(nullptr, n * 8);
    PyObject* keys = PyBytes_FromStringAndSize(
        nullptr, (Py_ssize_t)n * 8 * stride);
    if (!present || !bins || !keys) {
        PyBuffer_Release(&slots);
        Py_XDECREF(present);
        Py_XDECREF(bins);
        Py_XDECREF(keys);
        return nullptr;
    }
    uint8_t* pout = (uint8_t*)PyBytes_AS_STRING(present);
    int64_t* bout = (int64_t*)PyBytes_AS_STRING(bins);
    int64_t* kout = (int64_t*)PyBytes_AS_STRING(keys);
    const int64_t* s = (const int64_t*)slots.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t ref = 0;
        if (s[i] >= 0 && (size_t)s[i] < self->slot_owner->size())
            ref = (*self->slot_owner)[(size_t)s[i]];
        if (ref == 0) {
            pout[i] = 0;
            bout[i] = 0;
            memset(kout + (size_t)i * stride, 0,
                   stride * sizeof(int64_t));
            continue;
        }
        const Entry& e = (*self->entries)[ref - 1];
        pout[i] = 1;
        bout[i] = e.bin;
        memcpy(kout + (size_t)i * stride, entry_keys(self, ref - 1),
               stride * sizeof(int64_t));
    }
    PyBuffer_Release(&slots);
    return Py_BuildValue("(NNN)", present, bins, keys);
}

// lookup(bin, keys) -> (present u8 bytes, slots bytes): point lookups
// for a small key set (the updating aggregate's dirty keys) without
// materializing the whole bin.
static PyObject* SlotDir_lookup(SlotDir* self, PyObject* args) {
    int64_t bin;
    PyObject* keys_obj;
    if (!PyArg_ParseTuple(args, "LO", &bin, &keys_obj)) return nullptr;
    Py_buffer keys;
    if (get_i64_buffer(keys_obj, &keys) != 0) return nullptr;
    const int stride = self->stride;
    if ((keys.len / 8) % stride != 0) {
        PyBuffer_Release(&keys);
        PyErr_SetString(PyExc_ValueError,
                        "keys length != n_rows * stride");
        return nullptr;
    }
    Py_ssize_t n = keys.len / 8 / stride;
    const int64_t* k = (const int64_t*)keys.buf;
    PyObject* present = PyBytes_FromStringAndSize(nullptr, n);
    PyObject* slots = PyBytes_FromStringAndSize(nullptr, n * 8);
    if (!present || !slots) {
        PyBuffer_Release(&keys);
        Py_XDECREF(present);
        Py_XDECREF(slots);
        return nullptr;
    }
    uint8_t* pout = (uint8_t*)PyBytes_AS_STRING(present);
    int64_t* sout = (int64_t*)PyBytes_AS_STRING(slots);
    for (Py_ssize_t i = 0; i < n; i++) {
        const int64_t* krow = k + i * stride;
        pout[i] = 0;
        sout[i] = -1;
        size_t h = hash_row(bin, krow, stride) & self->mask;
        for (;;) {
            int32_t ref = (*self->index)[h];
            if (ref == 0) break;
            const Entry& e = (*self->entries)[ref - 1];
            if (e.live && e.bin == bin &&
                memcmp(entry_keys(self, ref - 1), krow,
                       stride * sizeof(int64_t)) == 0) {
                pout[i] = 1;
                sout[i] = e.slot;
                break;
            }
            h = (h + 1) & self->mask;
        }
    }
    PyBuffer_Release(&keys);
    return Py_BuildValue("(NN)", present, slots);
}

// remove(bin, keys) -> freed slots bytes: remove specific keys from one
// bin (TTL eviction, retract-deleted keys). Marks entries dead via the
// index probe, then unlinks every dead entry in ONE chain sweep.
static PyObject* SlotDir_remove(SlotDir* self, PyObject* args) {
    int64_t bin;
    PyObject* keys_obj;
    if (!PyArg_ParseTuple(args, "LO", &bin, &keys_obj)) return nullptr;
    Py_buffer keys;
    if (get_i64_buffer(keys_obj, &keys) != 0) return nullptr;
    const int stride = self->stride;
    if ((keys.len / 8) % stride != 0) {
        PyBuffer_Release(&keys);
        PyErr_SetString(PyExc_ValueError,
                        "keys length != n_rows * stride");
        return nullptr;
    }
    Py_ssize_t n = keys.len / 8 / stride;
    const int64_t* k = (const int64_t*)keys.buf;
    BinHead* bh = bin_lookup(self, bin, false);
    std::vector<int64_t> freed;
    if (bh) {
        for (Py_ssize_t i = 0; i < n; i++) {
            const int64_t* krow = k + i * stride;
            size_t h = hash_row(bin, krow, stride) & self->mask;
            for (;;) {
                int32_t ref = (*self->index)[h];
                if (ref == 0) break;
                Entry& e = (*self->entries)[ref - 1];
                if (e.live && e.bin == bin &&
                    memcmp(entry_keys(self, ref - 1), krow,
                           stride * sizeof(int64_t)) == 0) {
                    e.live = 0;  // unlinked in the sweep below
                    freed.push_back(e.slot);
                    break;
                }
                h = (h + 1) & self->mask;
            }
        }
        if (!freed.empty()) {
            int32_t idx = bh->head;
            int32_t* link = &bh->head;
            while (idx >= 0) {
                Entry& e = (*self->entries)[idx];
                int32_t nxt = e.next_in_bin;
                if (!e.live) {
                    *link = nxt;
                    self->free_entries->push_back(idx);
                    self->free_slots->push_back(e.slot);
                    (*self->slot_owner)[(size_t)e.slot] = 0;
                } else {
                    link = &e.next_in_bin;
                }
                idx = nxt;
            }
            bh->count -= (int32_t)freed.size();
            self->n_live -= (int64_t)freed.size();
        }
    }
    PyBuffer_Release(&keys);
    PyObject* out = PyBytes_FromStringAndSize(
        (const char*)freed.data(), (Py_ssize_t)freed.size() * 8);
    return out;
}

// entries() -> (bins_bytes, keys_bytes, slots_bytes) over all live entries
static PyObject* SlotDir_entries(SlotDir* self, PyObject*) {
    int64_t count = self->n_live;
    const int stride = self->stride;
    PyObject* bins = PyBytes_FromStringAndSize(nullptr, count * 8);
    PyObject* keys = PyBytes_FromStringAndSize(
        nullptr, (Py_ssize_t)count * 8 * stride);
    PyObject* slots = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)count * 8);
    if (!bins || !keys || !slots) return nullptr;
    int64_t* bout = (int64_t*)PyBytes_AS_STRING(bins);
    int64_t* kout = (int64_t*)PyBytes_AS_STRING(keys);
    int64_t* sout = (int64_t*)PyBytes_AS_STRING(slots);
    int64_t i = 0;
    for (size_t idx = 0; idx < self->entries->size(); idx++) {
        const Entry& e = (*self->entries)[idx];
        if (!e.live) continue;
        bout[i] = e.bin;
        memcpy(kout + (size_t)i * stride, entry_keys(self, idx),
               stride * sizeof(int64_t));
        sout[i] = e.slot;
        i++;
    }
    return Py_BuildValue("(NNN)", bins, keys, slots);
}

static PyObject* SlotDir_live_bins(SlotDir* self, PyObject*) {
    PyObject* out = PyList_New(0);
    for (const BinHead& b : *self->bin_index) {
        if (b.used && b.count > 0) {
            PyObject* v = PyLong_FromLongLong(b.bin);
            PyList_Append(out, v);
            Py_DECREF(v);
        }
    }
    return out;
}

static PyObject* SlotDir_required_capacity(SlotDir* self, PyObject*) {
    return PyLong_FromLongLong(self->next_slot + 1);
}

static PyObject* SlotDir_n_live(SlotDir* self, PyObject*) {
    return PyLong_FromLongLong(self->n_live);
}

static PyMethodDef SlotDir_methods[] = {
    {"assign", (PyCFunction)SlotDir_assign, METH_VARARGS,
     "assign(bins_i64, keys_i64) -> slots bytes"},
    {"take_bin", (PyCFunction)SlotDir_take_bin, METH_VARARGS,
     "take_bin(bin) -> (keys bytes, slots bytes)"},
    {"get_bin", (PyCFunction)SlotDir_get_bin, METH_VARARGS,
     "get_bin(bin) -> (keys bytes, slots bytes) without removing"},
    {"get_bins", (PyCFunction)SlotDir_get_bins, METH_VARARGS,
     "get_bins(bins_i64) -> concatenated (keys, slots) bytes, no removal"},
    {"lookup", (PyCFunction)SlotDir_lookup, METH_VARARGS,
     "lookup(bin, keys_i64) -> (present u8, slots) bytes"},
    {"remove", (PyCFunction)SlotDir_remove, METH_VARARGS,
     "remove(bin, keys_i64) -> freed slots bytes"},
    {"keys_for_slots", (PyCFunction)SlotDir_keys_for_slots, METH_VARARGS,
     "keys_for_slots(slots_i64) -> (present u8, bins, keys) bytes"},
    {"entries", (PyCFunction)SlotDir_entries, METH_NOARGS,
     "entries() -> (bins bytes, keys bytes, slots bytes)"},
    {"live_bins", (PyCFunction)SlotDir_live_bins, METH_NOARGS, ""},
    {"required_capacity", (PyCFunction)SlotDir_required_capacity,
     METH_NOARGS, ""},
    {"n_live", (PyCFunction)SlotDir_n_live, METH_NOARGS, ""},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject SlotDirType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

static PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "arroyo_native",
    "native slot directory for arroyo_tpu window operators", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_arroyo_native(void) {
    SlotDirType.tp_name = "arroyo_native.SlotDir";
    SlotDirType.tp_basicsize = sizeof(SlotDir);
    SlotDirType.tp_flags = Py_TPFLAGS_DEFAULT;
    SlotDirType.tp_new = SlotDir_new;
    SlotDirType.tp_dealloc = (destructor)SlotDir_dealloc;
    SlotDirType.tp_methods = SlotDir_methods;
    if (PyType_Ready(&SlotDirType) < 0) return nullptr;
    PyObject* m = PyModule_Create(&moduledef);
    if (!m) return nullptr;
    Py_INCREF(&SlotDirType);
    PyModule_AddObject(m, "SlotDir", (PyObject*)&SlotDirType);
    return m;
}
