"""Updating (non-windowed) joins with retractions.

Capability parity with the reference's updating join support
(/root/reference/crates/arroyo-sql-testing/src/test/queries/
updating_{inner,left,right,full}_join.sql + planner plan/join.rs updating
path): both sides materialize per join key; every arriving append/retract
incrementally emits the delta of the join result as append/retract rows
tagged with __updating_meta, including the null-padded transitions of
outer joins (a side's first match retracts its null-padded row; losing the
last match re-emits it).

Streams reaching this operator are post-shuffle (keyed on the equi keys),
so each subtask owns its key range. Rates here are typically
post-aggregation, so the per-row host loop favors correctness; state
checkpoints as msgpack'd row lists per key.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from ..schema import StreamSchema, TIMESTAMP_FIELD, UPDATING_META_FIELD
from .base import Operator


class UpdatingJoinOperator(Operator):
    flow_class = "buffering"  # retract/append streams decouple in/out counts

    def __init__(self, config: dict):
        super().__init__("updating_join")
        self.n_keys = int(config["n_keys"])
        self.join_type = config["join_type"]  # inner | left | right | full
        self.out_schema: StreamSchema = config["schema"]
        key_names = {f"__key{i}" for i in range(self.n_keys)}
        skip = key_names | {TIMESTAMP_FIELD, UPDATING_META_FIELD}
        # SOURCE payload column names per side (input batch names) and the
        # OUTPUT names they map to (right side may be _right-renamed,
        # positionally aligned with the source order)
        self.left_src: List[str] = [
            f.name for f in config["left_schema"].schema
            if f.name not in skip
        ]
        self.left_out: List[str] = self.left_src
        self.right_src: List[str] = [
            f.name for f in config["right_schema"].schema
            if f.name not in skip
        ]
        self.right_out: List[str] = config["right_fields"]
        self.residual = config.get("residual_py")
        from ..config import config as get_config

        ttl = config.get(
            "ttl_nanos", int(get_config().pipeline.update_aggregate_ttl * 1e9)
        )
        self.ttl_nanos: Optional[int] = int(ttl) if ttl else None
        # key -> list of payload tuples (may contain duplicates)
        self.state: List[Dict[tuple, List[tuple]]] = [{}, {}]
        self.last_seen: Dict[tuple, int] = {}
        # columnar mirror of one side's store for the device-probe bulk
        # path: (key pa arrays, payload python column lists); rebuilt
        # lazily when that side's state has mutated
        self._col_cache: List[Optional[tuple]] = [None, None]
        # per side: list (per key col) of arrow chunks mirroring the
        # python key lists, plus the types they were built with
        self._key_arr_cache: List[Optional[list]] = [None, None]
        self._key_arr_types: List[Optional[list]] = [None, None]
        # sticky per-side flag: a null join key ever stored disables the
        # bulk path (per-row null semantics are authoritative) without
        # paying a store scan per batch; conservatively never cleared
        self._store_has_null_key: List[bool] = [False, False]
        self._lmap = {f: i for i, f in enumerate(self.left_out)}
        self._rmap = {f: i for i, f in enumerate(self.right_out)}
        self._kmap = {f"__key{i}": i for i in range(self.n_keys)}

    def tables(self):
        from ..state.table_config import global_table

        return {"uj": global_table("uj")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("uj")
            for snap in table.all_values():
                for side in (0, 1):
                    for key_vals, rows in snap[str(side)]:
                        key = tuple(key_vals)
                        if self._owns(key, ctx):
                            self.state[side].setdefault(key, []).extend(
                                tuple(r) for r in rows
                            )
                            if any(k is None for k in key):
                                self._store_has_null_key[side] = True
        self._col_cache = [None, None]

    def _owns(self, key: tuple, ctx) -> bool:
        p = ctx.task_info.parallelism
        if p <= 1:
            return True
        from ..types import hash_arrays, hash_column, server_for_hash_array

        cols = [
            hash_column(np.asarray([k])) for k in key
        ]
        owner = server_for_hash_array(hash_arrays(cols), p)[0]
        return owner == ctx.task_info.task_index

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("uj")
            table.put(
                ctx.task_info.task_index,
                {
                    "subtask": ctx.task_info.task_index,
                    "0": [
                        [list(k), [list(r) for r in rows]]
                        for k, rows in self.state[0].items()
                    ],
                    "1": [
                        [list(k), [list(r) for r in rows]]
                        for k, rows in self.state[1].items()
                    ],
                },
            )

    # -- processing ---------------------------------------------------------

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        side = input_index
        schema_names = batch.schema.names
        src_fields = self.left_src if side == 0 else self.right_src
        ts = int(
            np.asarray(
                batch.column(schema_names.index(TIMESTAMP_FIELD)).cast(
                    pa.int64()
                )
            ).max()
        )
        out = self._inner_bulk(batch, side, ts)
        if out is not None:
            if out.num_rows:
                await collector.collect(out)
            return
        rows = batch.to_pylist()
        # deltas accumulate IN INPUT ORDER as (is_retract, row) so a
        # retract never overtakes the append it cancels within a batch
        deltas: List[Tuple[bool, tuple]] = []
        for row in rows:
            key = tuple(
                _norm(row[f"__key{i}"]) for i in range(self.n_keys)
            )
            payload = tuple(_norm(row[f]) for f in src_fields)
            meta = row.get(UPDATING_META_FIELD)
            self.last_seen[key] = ts
            if meta and meta.get("is_retract"):
                self._retract_row(side, key, payload, deltas)
            else:
                self._append_row(side, key, payload, deltas)
        # emit maximal same-kind runs as batches, preserving order
        i = 0
        while i < len(deltas):
            j = i
            while j < len(deltas) and deltas[j][0] == deltas[i][0]:
                j += 1
            batch_out = self._build(
                [d[1] for d in deltas[i:j]], deltas[i][0], ts
            )
            if batch_out is not None and batch_out.num_rows:
                await collector.collect(batch_out)
            i = j

    # -- device-probe bulk path (inner, append-only batches) ----------------

    def _inner_bulk(self, batch, side: int, ts: int):
        """Bulk inner-join delta for an all-append batch via the device
        merge-join probe (VERDICT r3 item 4: updating join's inner core
        rides ops/device_join.py): batch rows x the OTHER side's stored
        rows matched in one probe, output assembled columnar, state
        bulk-appended. Returns None when ineligible — per-row path.

        Sequential-equivalence: an append-only single-side batch only
        ever joins against the other side's STORE (same-side and
        same-batch rows never pair), and inner joins emit no outer
        transitions, so the bulk result equals the per-row loop's."""
        if self.join_type != "inner" or self.n_keys == 0:
            return None
        from ..config import config as get_config

        cfg = get_config().tpu
        from ..ops._jax import device_join_active

        if not device_join_active():
            return None
        # cheap per-batch disqualifiers BEFORE any O(store) work (key
        # scan, mirror rebuild): jax availability, key-type codability,
        # null keys anywhere (per-row dict-equality semantics are
        # authoritative for nulls), retracts in the batch
        from ..ops import device_join

        if not device_join.available():
            return None
        names = batch.schema.names
        kcols = [f"__key{i}" for i in range(self.n_keys)]
        from ..ops.device_join import _codable

        if not all(
            _codable(batch.schema.field(names.index(k)).type)
            for k in kcols
        ):
            return None
        if any(
            batch.column(names.index(k)).null_count for k in kcols
        ) or self._store_has_null_key[0] or self._store_has_null_key[1]:
            return None
        if UPDATING_META_FIELD in names:
            retracts = batch.column(
                names.index(UPDATING_META_FIELD)
            ).field("is_retract")
            import pyarrow.compute as pc

            if pc.any(retracts).as_py():
                return None
        other_rows = sum(
            len(v) for v in self.state[1 - side].values()
        )
        if batch.num_rows + other_rows < cfg.device_join_min_rows:
            return None
        try:
            other_tab, other_payload_cols = self._other_side_cache(
                1 - side, batch
            )
        except (pa.ArrowInvalid, pa.ArrowTypeError, TypeError):
            return None
        bt = pa.table({k: batch.column(names.index(k)) for k in kcols})
        prep = device_join.prepare_join_keys(bt, other_tab, kcols)
        if prep is None:
            return None
        lcols, rcols, lsel, rsel = prep
        if lsel is not None or rsel is not None:
            # null join keys present: the per-row path's dict-equality
            # semantics (None == None matches) stay authoritative
            return None
        bi, si = device_join.probe(lcols, rcols)
        out = self._assemble_bulk(batch, side, bi, si,
                                  other_payload_cols, ts)
        self._bulk_append_state(batch, side, ts)
        return out

    def _other_side_cache(self, other: int, batch):
        """(key table, payload column lists) mirror of state[other].
        The mirror is plain python column lists: rebuilt with one
        O(store) pass after per-row mutations, EXTENDED in place by the
        bulk path's own appends (the common all-append stream never
        rebuilds). Arrow key arrays are cached as CHUNKS alongside the
        lists — the steady all-append state appends one chunk per batch
        instead of reconverting the whole store every call (ADVICE r4:
        the O(store) pa.array conversion dominated large stores)."""
        if self._col_cache[other] is None:
            store = self.state[other]
            n_fields = len(
                self.left_src if other == 0 else self.right_src
            )
            key_cols: List[list] = [[] for _ in range(self.n_keys)]
            pay_cols: List[list] = [[] for _ in range(n_fields)]
            for key, rows in store.items():
                for r in rows:
                    for i in range(self.n_keys):
                        key_cols[i].append(key[i])
                    for j in range(n_fields):
                        pay_cols[j].append(r[j])
            self._col_cache[other] = (key_cols, pay_cols)
            self._key_arr_cache[other] = None  # chunks rebuild below
        key_cols, pay_cols = self._col_cache[other]
        # key column types from the batch's key columns so the probe
        # compares like with like (ints stay ints, strings strings)
        names = batch.schema.names
        types = []
        for i in range(self.n_keys):
            t = batch.schema.field(names.index(f"__key{i}")).type
            if pa.types.is_timestamp(t):
                t = pa.int64()  # _norm stores int nanos
            types.append(t)
        if (self._key_arr_cache[other] is None
                or self._key_arr_types[other] != types):
            self._key_arr_cache[other] = [
                [pa.array(key_cols[i], type=types[i])]
                for i in range(self.n_keys)
            ]
            self._key_arr_types[other] = types
        arrays = {
            f"__key{i}": pa.chunked_array(self._key_arr_cache[other][i],
                                          type=types[i])
            for i in range(self.n_keys)
        }
        return pa.table(arrays), pay_cols

    def _assemble_bulk(self, batch, side, bi, si, other_payload_cols, ts):
        names = batch.schema.names
        n = len(bi)
        bi_a = pa.array(bi)
        lmap, rmap, kmap = self._lmap, self._rmap, self._kmap
        my_src = self.left_src if side == 0 else self.right_src
        my_map = lmap if side == 0 else rmap
        other_map = rmap if side == 0 else lmap
        arrays = []
        for f in self.out_schema.schema:
            if f.name in kmap:
                col = batch.column(
                    names.index(f"__key{kmap[f.name]}")
                )
                arrays.append(col.take(bi_a).cast(f.type))
            elif f.name == TIMESTAMP_FIELD:
                arrays.append(
                    pa.array(np.full(n, ts, dtype=np.int64)).cast(f.type)
                )
            elif f.name == UPDATING_META_FIELD:
                from ..schema import updating_meta_array

                arrays.append(updating_meta_array(n, False))
            elif f.name in my_map:
                src_name = my_src[my_map[f.name]]
                arrays.append(
                    batch.column(names.index(src_name))
                    .take(bi_a).cast(f.type)
                )
            elif f.name in other_map:
                vals = other_payload_cols[other_map[f.name]]
                arrays.append(
                    _col(vals, f.type).take(pa.array(si))
                )
            else:
                raise KeyError(f"updating join output missing {f.name}")
        out = pa.RecordBatch.from_arrays(
            arrays, schema=self.out_schema.schema
        )
        if self.residual is not None:
            out = out.filter(self.residual(out))
        return out

    def _bulk_append_state(self, batch, side, ts):
        names = batch.schema.names
        src = self.left_src if side == 0 else self.right_src
        key_lists = [
            [_norm(v) for v in
             batch.column(names.index(f"__key{i}")).to_pylist()]
            for i in range(self.n_keys)
        ]
        pay_lists = [
            [_norm(v) for v in batch.column(names.index(f)).to_pylist()]
            for f in src
        ]
        store = self.state[side]
        for r in range(batch.num_rows):
            key = tuple(kl[r] for kl in key_lists)
            payload = tuple(c[r] for c in pay_lists)
            store.setdefault(key, []).append(payload)
            self.last_seen[key] = ts
        # extend this side's mirror in place instead of invalidating it:
        # alternating left/right append streams would otherwise rebuild
        # the full opposite-side mirror every batch
        cache = self._col_cache[side]
        if cache is not None:
            ck, cp = cache
            for i in range(self.n_keys):
                ck[i].extend(key_lists[i])
            for j in range(len(pay_lists)):
                cp[j].extend(pay_lists[j])
            kac = self._key_arr_cache[side]
            if kac is not None:
                # one appended arrow chunk per batch keeps the chunked
                # key arrays in lockstep with the python lists; a
                # cross-side type mismatch (no key coercion between
                # sides) must degrade to a rebuild, not kill the task
                try:
                    for i in range(self.n_keys):
                        kac[i].append(pa.array(
                            key_lists[i], type=self._key_arr_types[side][i]
                        ))
                        if len(kac[i]) > 64:
                            # bound chunk count (and the per-probe concat
                            # cost) on long all-append streams
                            kac[i] = [
                                pa.chunked_array(kac[i]).combine_chunks()
                            ]
                except (pa.ArrowInvalid, pa.ArrowTypeError):
                    self._key_arr_cache[side] = None

    # join-delta helpers: rows are (key, left_payload|None, right_payload|None)

    def _null_padded(self, side: int, key: tuple, payload: tuple) -> tuple:
        return (key, payload, None) if side == 0 else (key, None, payload)

    def _joined(self, key: tuple, l: tuple, r: tuple) -> tuple:
        return (key, l, r)

    def _append_row(self, side, key, payload, deltas):
        out_append = _DeltaSink(deltas, False)
        out_retract = _DeltaSink(deltas, True)
        mine = self.state[side].setdefault(key, [])
        other = self.state[1 - side].get(key, [])
        other_outer = (
            self.join_type in ("left", "full") if side == 1
            else self.join_type in ("right", "full")
        )
        my_outer = (
            self.join_type in ("left", "full") if side == 0
            else self.join_type in ("right", "full")
        )
        if other:
            for o in other:
                l, r = (payload, o) if side == 0 else (o, payload)
                out_append.append(self._joined(key, l, r))
            # first row on MY side: the other side's null-padded rows retract
            if not mine and other_outer:
                for o in other:
                    out_retract.append(self._null_padded(1 - side, key, o))
        elif my_outer:
            out_append.append(self._null_padded(side, key, payload))
        mine.append(payload)
        self._col_cache[side] = None
        if any(k is None for k in key):
            self._store_has_null_key[side] = True

    def _retract_row(self, side, key, payload, deltas):
        out_append = _DeltaSink(deltas, False)
        out_retract = _DeltaSink(deltas, True)
        mine = self.state[side].get(key, [])
        try:
            mine.remove(payload)
        except ValueError:
            return  # retraction for an unknown row: drop
        self._col_cache[side] = None
        other = self.state[1 - side].get(key, [])
        other_outer = (
            self.join_type in ("left", "full") if side == 1
            else self.join_type in ("right", "full")
        )
        my_outer = (
            self.join_type in ("left", "full") if side == 0
            else self.join_type in ("right", "full")
        )
        if other:
            for o in other:
                l, r = (payload, o) if side == 0 else (o, payload)
                out_retract.append(self._joined(key, l, r))
            # last row on MY side gone: other side's rows become null-padded
            if not mine and other_outer:
                for o in other:
                    out_append.append(self._null_padded(1 - side, key, o))
        elif my_outer:
            out_retract.append(self._null_padded(side, key, payload))
        if not mine:
            self.state[side].pop(key, None)

    async def handle_watermark(self, watermark, ctx, collector):
        """TTL eviction of idle keys (the reference bounds updating state
        with updating_cache.rs the same way). Evicted keys silently drop
        their materialized rows — late retractions for them are ignored."""
        from ..types import WATERMARK_END, WatermarkKind

        if (
            watermark.kind == WatermarkKind.EVENT_TIME
            and self.ttl_nanos
            and watermark.timestamp < WATERMARK_END
        ):
            cutoff = watermark.timestamp - self.ttl_nanos
            stale = [k for k, seen in self.last_seen.items() if seen < cutoff]
            for k in stale:
                self.state[0].pop(k, None)
                self.state[1].pop(k, None)
                self.last_seen.pop(k, None)
            if stale:
                self._col_cache = [None, None]
        return watermark

    def serve_stage_snapshot(self, view) -> None:
        """Serve the join's current row set per key (ISSUE 20
        satellite). Called by seal_op at checkpoint capture: each key's
        joined rows — cross product when both sides match, null-padded
        per outer semantics otherwise — stage as `{"rows": [...]}`
        with output field names, the same shape a sink would
        accumulate. Snapshot cost is O(state), which is already this
        operator's per-checkpoint norm (handle_checkpoint puts the
        whole store). Keys whose row set vanished since the last
        capture are tombstoned; null-component keys are skipped (null
        never equals anything, so no row can join on it). register_op
        refuses residual joins a view entirely (see _view_plan)."""
        from ..serve.store import _plain

        left_outer = self.join_type in ("left", "full")
        right_outer = self.join_type in ("right", "full")
        prev = getattr(self, "_serve_join_keys", set())
        cur: set = set()
        for key in set(self.state[0]) | set(self.state[1]):
            if any(k is None for k in key):
                continue
            l_rows = self.state[0].get(key, [])
            r_rows = self.state[1].get(key, [])
            rows: List[dict] = []
            if l_rows and r_rows:
                for l in l_rows:
                    for r in r_rows:
                        row = dict(zip(self.left_out, l))
                        row.update(zip(self.right_out, r))
                        rows.append(row)
            elif l_rows and left_outer:
                pad = dict.fromkeys(self.right_out)
                for l in l_rows:
                    rows.append({**dict(zip(self.left_out, l)), **pad})
            elif r_rows and right_outer:
                pad = dict.fromkeys(self.left_out)
                for r in r_rows:
                    rows.append({**pad, **dict(zip(self.right_out, r))})
            if not rows:
                continue  # inner join with a lone side: nothing visible
            ck = view.canon_key(key)
            view.stage(
                ck,
                {"rows": [{f: _plain(v) for f, v in r.items()}
                          for r in rows]},
            )
            cur.add(ck)
        for ck in prev - cur:
            view.stage_tomb(ck)
        self._serve_join_keys = cur

    # -- output -------------------------------------------------------------

    def _build(self, rows: List[tuple], is_retract: bool, ts: int):
        n = len(rows)
        lmap, rmap, kmap = self._lmap, self._rmap, self._kmap
        arrays = []
        for f in self.out_schema.schema:
            if f.name in kmap:
                ki = kmap[f.name]
                arrays.append(
                    pa.array([r[0][ki] for r in rows], type=f.type)
                )
            elif f.name == TIMESTAMP_FIELD:
                arrays.append(
                    pa.array(np.full(n, ts, dtype=np.int64)).cast(f.type)
                )
            elif f.name == UPDATING_META_FIELD:
                from ..schema import updating_meta_array

                arrays.append(updating_meta_array(n, is_retract))
            elif f.name in lmap:
                li = lmap[f.name]
                arrays.append(_col(
                    [r[1][li] if r[1] is not None else None for r in rows],
                    f.type,
                ))
            elif f.name in rmap:
                ri = rmap[f.name]
                arrays.append(_col(
                    [r[2][ri] if r[2] is not None else None for r in rows],
                    f.type,
                ))
            else:
                raise KeyError(f"updating join output missing {f.name}")
        batch = pa.RecordBatch.from_arrays(
            arrays, schema=self.out_schema.schema
        )
        if self.residual is not None:
            mask = self.residual(batch)
            batch = batch.filter(mask)
        return batch


def _norm(v):
    """State values must be msgpack-serializable and hashable; pandas
    Timestamps become int nanos."""
    if isinstance(v, pd.Timestamp):
        return v.value
    return v


class _DeltaSink:
    """Appends (is_retract, row) onto the shared in-order delta list."""

    __slots__ = ("deltas", "is_retract")

    def __init__(self, deltas, is_retract):
        self.deltas = deltas
        self.is_retract = is_retract

    def append(self, row):
        self.deltas.append((self.is_retract, row))


def _col(vals, t: pa.DataType) -> pa.Array:
    if pa.types.is_timestamp(t):
        return pa.array(vals, type=pa.int64()).cast(t)
    return pa.array(vals, type=t)


def make_updating_join(config: dict) -> Operator:
    return UpdatingJoinOperator(config)
