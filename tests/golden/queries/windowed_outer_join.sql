CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE minute_aggregates (
  minute TIMESTAMP,
  dropoff_drivers BIGINT,
  pickup_drivers BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO minute_aggregates
SELECT window.start as minute, dropoff_drivers, pickup_drivers FROM (
  SELECT dropoffs.window as window, dropoff_drivers, pickup_drivers
  FROM (
    SELECT tumble(interval '1 minute') as window,
           count(DISTINCT driver_id) as dropoff_drivers
    FROM cars WHERE event_type = 'dropoff'
    GROUP BY 1
  ) dropoffs
  FULL OUTER JOIN (
    SELECT tumble(interval '1 minute') as window,
           count(DISTINCT driver_id) as pickup_drivers
    FROM cars WHERE event_type = 'pickup'
    GROUP BY 1
  ) pickups
  ON dropoffs.window = pickups.window
);
