"""Lint engine: file collection, the per-file/project rule pipeline, and
baseline application. `run_lint` is the single entry point used by the CLI
(tools/lint.py) and the tier-1 test (tests/test_lint.py)."""

from __future__ import annotations

import dataclasses
import subprocess
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

from .baseline import Baseline
from .core import (
    FileContext,
    Finding,
    Project,
    Rule,
    all_rules,
    sorted_findings,
)

# what a default run covers, relative to the lint root
DEFAULT_ROOTS = ("arroyo_tpu", "tools", "bench.py")
EXCLUDED_PARTS = {"__pycache__", "lint_fixtures", ".git", "node_modules"}


def collect_files(root: Path, roots: Sequence[str] = DEFAULT_ROOTS) -> List[Path]:
    root = Path(root)
    out: List[Path] = []
    for entry in roots:
        p = root / entry
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                # exclusions apply below the lint root only (a fixture tree
                # lives UNDER an excluded dir but lints fine as a root)
                if not EXCLUDED_PARTS.intersection(f.relative_to(root).parts):
                    out.append(f)
    return out


def parse_project(root: Path, files: Iterable[Path]) -> Project:
    root = Path(root)
    ctxs: Dict[str, FileContext] = {}
    errors: List[Finding] = []
    for f in files:
        rel = f.relative_to(root).as_posix()
        try:
            source = f.read_text()
            ctxs[rel] = FileContext(root, rel, source)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(
                Finding(
                    rule="LINT000",
                    path=rel,
                    line=getattr(e, "lineno", 1) or 1,
                    col=0,
                    message=f"file does not parse: {e.msg if hasattr(e, 'msg') else e}",
                )
            )
    return Project(root, ctxs, errors)


def changed_paths(root: Path) -> Optional[set]:
    """Repo-relative paths touched vs HEAD (staged, unstaged, untracked).
    None when git is unavailable — callers fall back to a full run."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        status = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if diff.returncode != 0 or status.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    out = {l.strip() for l in diff.stdout.splitlines() if l.strip()}
    for line in status.stdout.splitlines():
        if len(line) > 3:
            out.add(line[3:].split(" -> ")[-1].strip())
    return out


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]       # new findings (not grandfathered)
    grandfathered: List[Finding]  # matched a baseline entry
    stale_baseline: List[dict]    # baseline entries matching nothing
    errors: List[Finding]         # unparseable files
    n_files: int
    n_rules: int

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def strict_ok(self, baseline: Baseline) -> bool:
        """--strict: no new findings, no parse errors, every grandfathered
        entry justified, and no stale entries rotting in the baseline."""
        return (
            self.clean
            and not self.stale_baseline
            and not baseline.unjustified()
        )


def run_lint(
    root,
    rules: Optional[Sequence[Rule]] = None,
    roots: Sequence[str] = DEFAULT_ROOTS,
    baseline: Optional[Baseline] = None,
    changed_only: bool = False,
) -> LintResult:
    root = Path(root)
    rules = list(rules) if rules is not None else all_rules()
    project = parse_project(root, collect_files(root, roots))
    findings: List[Finding] = []
    for rule in rules:
        if rule.scope == "project":
            found = rule.check_project(project)
            for f in found:
                ctx = project.get(f.path)
                if ctx is None or not ctx.suppressed(f.rule, f.line):
                    findings.append(f)
        else:
            for ctx in project:
                for f in rule.check_file(ctx):
                    if not ctx.suppressed(f.rule, f.line):
                        findings.append(f)
    errors = list(project.errors)
    if changed_only:
        changed = changed_paths(root)
        if changed is not None:
            findings = [f for f in findings if f.path in changed]
            errors = [f for f in errors if f.path in changed]
    baseline = baseline or Baseline()
    new, old, stale = baseline.split(sorted_findings(findings))
    return LintResult(
        findings=new,
        grandfathered=old,
        stale_baseline=stale,
        errors=sorted_findings(errors),
        n_files=len(project.files),
        n_rules=len(rules),
    )
