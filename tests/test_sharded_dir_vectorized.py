"""Vectorized ShardedDirectory batch ops (the mesh hot path): every
MeshSlotDirectory batch operation must cross into the native table at
most ONCE per shard (no per-key python iteration), native and python
shard tiers must agree semantically, and the packing rung ladder must
bound padding overshoot. Also covers the micro-flush read-elision of
ShardedAccumulator and the batch free_slots tier the session operator
rides."""

from collections import Counter

import numpy as np
import pytest

from arroyo_tpu.ops.aggregates import AggSpec
from arroyo_tpu.parallel.sharded_state import (
    MESH_STATS,
    STRIDE,
    MeshSlotDirectory,
    _pow2_ladder,
)


@pytest.fixture(scope="module")
def mesh():
    import jax

    from arroyo_tpu.parallel import key_mesh

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs multiple devices")
    return key_mesh(devices)


class CountingSlotDir:
    """Delegating wrapper over the native C SlotDir that counts method
    calls — the unit-level proof that the mesh facade's batch ops are
    one-C-call-per-shard, not per-key loops."""

    def __init__(self, inner):
        self._inner = inner
        self.calls = Counter()

    def __getattr__(self, name):
        fn = getattr(self._inner, name)

        def wrapper(*a, **k):
            self.calls[name] += 1
            return fn(*a, **k)

        return wrapper


def _native_mesh(n_shards=4, n_keys=1):
    from arroyo_tpu.ops.native import load_native

    native = load_native()
    if native is None:
        pytest.skip("native slot directory unavailable")
    d = MeshSlotDirectory(n_shards)
    assert d.swap_to_native(native, n_keys)
    counters = []
    for shard_dir in d.dirs:
        shard_dir._d = CountingSlotDir(shard_dir._d)
        counters.append(shard_dir._d.calls)
    return d, counters


def _populate(d, n=200, bins_mod=3, seed=7):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 60, n)
    bins = rng.integers(0, bins_mod, n)
    slots = d.assign(bins, [keys])
    return bins, keys, slots


def _drain(counters):
    for c in counters:
        c.clear()


def test_batch_ops_one_native_call_per_shard():
    d, counters = _native_mesh()
    _populate(d)
    _drain(counters)

    # items: exactly one entries() crossing per shard, nothing else
    list(d.items())
    assert all(c["entries"] == 1 for c in counters)
    _drain(counters)

    # keys_for_slots over every live slot: one crossing per shard
    all_slots = np.asarray([s for _, _, s in d.items()], dtype=np.int64)
    _drain(counters)
    res = d.keys_for_slots(all_slots)
    assert all(c["keys_for_slots"] <= 1 for c in counters)
    assert sum(c["keys_for_slots"] for c in counters) >= 1
    assert all(r is not None for r in res)
    _drain(counters)

    # slots_for_keys: one lookup per shard for the whole key list
    keys = [k for _, k, _ in d.items()][:50]
    _drain(counters)
    m = d.slots_for_keys(0, keys)
    assert all(c["lookup"] == 1 for c in counters)
    for k, s in m.items():
        assert res[int(np.where(all_slots == s)[0][0])][1] == k
    _drain(counters)

    # bin_entries_multi: one get_bins per shard for all bins at once
    kmat, slots_m = d.bin_entries_multi(np.arange(3))
    assert all(c["get_bins"] == 1 for c in counters)
    assert len(slots_m) == len(all_slots)
    _drain(counters)

    # remove: one crossing per shard, keys matrix built once
    rm = keys[:10]
    freed = d.remove(0, rm)
    assert all(c["remove"] == 1 for c in counters)
    _drain(counters)

    # take_bin_arrays: one take_bin per shard
    cols, slots_t = d.take_bin_arrays(1)
    assert all(c["take_bin"] == 1 for c in counters)
    assert len(cols) == 1 and len(cols[0]) == len(slots_t)


def test_native_matches_python_shard_semantics():
    from arroyo_tpu.ops.native import load_native

    native = load_native()
    if native is None:
        pytest.skip("native slot directory unavailable")
    dp = MeshSlotDirectory(4)
    dn = MeshSlotDirectory(4)
    assert dn.swap_to_native(native, 1)
    bins, keys, _ = _populate(dp)
    _populate(dn)

    assert dp.n_live == dn.n_live
    assert sorted(dp.by_bin) == sorted(dn.by_bin)
    # items agree as sets of (bin, key) with consistent slot ownership
    ip = {(b, k) for b, k, _ in dp.items()}
    in_ = {(b, k) for b, k, _ in dn.items()}
    assert ip == in_

    some = [(int(b), (int(k),)) for b, k in zip(bins[:20], keys[:20])]
    for b, k in some:
        sp = dp.slots_for_keys(b, [k])
        sn = dn.slots_for_keys(b, [k])
        assert set(sp) == set(sn) == {k}
        # same shard ownership (same hash routing) on both tiers
        assert sp[k] // STRIDE == sn[k] // STRIDE

    # keys_for_slots round-trips on both tiers
    for d in (dp, dn):
        slots = np.asarray([s for _, _, s in d.items()], dtype=np.int64)
        back = d.keys_for_slots(slots)
        assert {(b, k) for b, k in back} == ip
        # unknown slot resolves to None on both tiers
        assert d.keys_for_slots(
            np.asarray([7 * STRIDE + 12345], dtype=np.int64)
        ) == [None]

    # remove frees the same (bin, key) population
    rm_keys = [(int(k),) for k in sorted({int(k) for k in keys[:30]})]
    fp = dp.remove(1, rm_keys)
    fn = dn.remove(1, rm_keys)
    assert len(fp) == len(fn)
    assert dp.n_live == dn.n_live


def test_bin_entries_multi_matches_per_bin():
    d, _ = _native_mesh()
    _populate(d, n=300, bins_mod=5)
    kmat, slots = d.bin_entries_multi(np.arange(5))
    per_bin = []
    for b in range(5):
        km, s = d.bin_entries(b)
        if len(s):
            per_bin.append((km, s))
    want_slots = np.concatenate([s for _, s in per_bin])
    assert sorted(slots.tolist()) == sorted(want_slots.tolist())
    want_keys = np.concatenate([k for k, _ in per_bin])
    assert sorted(map(tuple, kmat.tolist())) == sorted(
        map(tuple, want_keys.tolist())
    )


def test_pow2_ladder_overshoot_bounds():
    # eighth rungs from 512, quarters from 64, pure pow2 below: the
    # ladder is deliberately COARSER than the round-5 sixteenth ladder
    # (every distinct rung hit costs a python trace + XLA compile per
    # process; wander is absorbed by _StickyRung, not ladder density)
    ladder = _pow2_ladder(1 << 20, floor=2)
    from arroyo_tpu.ops.aggregates import _bucket

    assert ladder[0] == 2 and ladder[-1] == 1 << 20
    assert list(ladder) == sorted(set(ladder))
    for n in range(2, 50000, 7):
        b = _bucket(n, ladder)
        assert b >= n
        over = b / n
        if n >= 512:
            assert over <= 1.125 + 0.01
        elif n >= 64:
            assert over <= 1.25 + 0.01
        else:
            assert over <= 2.0


def test_sticky_rung_hysteresis():
    """The rung must not follow per-flush wander (each rung change is a
    fresh XLA trace): it climbs exactly on overflow, holds across
    in-rung wander, and decays one rung only after a sustained shrink."""
    from arroyo_tpu.parallel.sharded_state import _StickyRung

    ladder = _pow2_ladder(1 << 16, floor=16)
    r = _StickyRung(ladder, decay_after=4)
    assert r.fit(100) == 112  # first fit: exact bucket, no headroom
    # wander within the rung: no change
    for n in (90, 112, 60, 111):
        assert r.fit(n) == 112
    # overflow climbs to bucket(1.25 * n) — headroom so a ramp does not
    # walk (and trace) every rung on its way up
    assert r.fit(1000) == 1280
    # sizes above half the rung: sticky forever
    for n in (700, 800, 641) * 4:
        assert r.fit(n) == 1280
    # sustained shrink below half: decays ONE rung after decay_after
    for _ in range(3):
        assert r.fit(100) == 1280
    assert r.fit(100) == 1152  # 4th consecutive low fit steps down
    # a single low fit never decays (first fit is exact: bucket(1000))
    r2 = _StickyRung(ladder, decay_after=4)
    assert r2.fit(1000) == 1024
    r2.fit(100)
    assert r2.fit(900) == 1024


def test_free_slots_batch_recycles_per_shard():
    d = MeshSlotDirectory(4)
    slots = d.alloc_slots(32, shard_hint=0)
    d.free_slots(slots)
    assert sum(len(sd.free) for sd in d.dirs) == 32
    # recycled without advancing any shard's high-water mark
    marks = [sd.next_slot for sd in d.dirs]
    again = d.alloc_slots(32, shard_hint=0)
    assert [sd.next_slot for sd in d.dirs] == marks
    assert sorted(np.asarray(again) // STRIDE) == sorted(
        np.asarray(slots) // STRIDE
    )


def test_flush_elision_skips_disjoint_reads(mesh):
    from arroyo_tpu.parallel import ShardedAccumulator

    specs = [AggSpec("count", None, "cnt"), AggSpec("sum", 0, "total")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=64,
                             rows_per_shard=64, flush_rows=1 << 30)
    d = MeshSlotDirectory(acc.n_shards)
    slots_a = d.assign(np.zeros(32, dtype=np.int64),
                       [np.arange(32, dtype=np.int64)])
    vals = np.full(32, 3, dtype=np.int64)
    acc.update(slots_a, {0: vals})
    assert acc._pending, "flush_rows threshold should buffer the update"
    slots_b = d.assign(np.ones(8, dtype=np.int64),
                       [np.arange(8, dtype=np.int64)])
    before = MESH_STATS["flushes_elided"]
    out_b = acc.gather(slots_b)
    # disjoint read: buffered rows stay pending, elision counted
    assert acc._pending
    assert MESH_STATS["flushes_elided"] == before + 1
    assert np.asarray(out_b[0]).tolist() == [0] * 8
    # touching read flushes and observes every buffered row
    out_a = acc.gather(slots_a)
    assert not acc._pending
    assert np.asarray(out_a[0]).tolist() == [1] * 32
    assert np.asarray(out_a[1]).tolist() == [3] * 32
    # reset of disjoint slots also elides; of touched slots flushes
    acc.update(slots_a, {0: vals})
    before = MESH_STATS["flushes_elided"]
    acc.reset_slots(slots_b)
    assert acc._pending and MESH_STATS["flushes_elided"] == before + 1
    acc.reset_slots(slots_a)
    assert not acc._pending
    out_a = acc.gather(slots_a)
    assert np.asarray(out_a[0]).tolist() == [0] * 32


def test_session_pool_returned_at_checkpoint():
    import asyncio
    import types

    import pyarrow as pa

    from arroyo_tpu.operators.windows import SessionWindowOperator
    from arroyo_tpu.schema import StreamSchema

    op = SessionWindowOperator({
        "aggregates": [{"kind": "count", "name": "cnt"}],
        "schema": StreamSchema.from_fields(
            [("k", pa.int64()), ("cnt", pa.int64())]
        ),
        "gap_nanos": 1000,
        "key_cols": [0],
    })
    s = op._alloc_slot()
    assert len(op._slot_pool) == op._POOL_BLOCK - 1
    ctx = types.SimpleNamespace(table_manager=None)
    asyncio.run(op.handle_checkpoint(None, ctx, None))
    # pool drained back into the directory free list: a checkpoint can
    # no longer strand allocated-but-unused slots (ADVICE round 5)
    assert not op._slot_pool
    assert len(op.dir.free) == op._POOL_BLOCK - 1
    # the next refill recycles the returned slots: the block of 64 costs
    # one fresh slot (the one still held by the live session), not 64
    mark = op.dir.next_slot
    s2 = op._alloc_slot()
    assert op.dir.next_slot == mark + 1
    assert not op.dir.free
    assert s2 != s
