"""MUST fire PRO003: unregistered point, non-literal point, dead registry
entry (storage.dead_point in chaos/plan.py)."""
from .. import chaos


def pump():
    chaos.fire("network.drop")
    chaos.fire("network.not_registered")


def dynamic(point):
    chaos.fire(point)  # non-literal: statically uncheckable
