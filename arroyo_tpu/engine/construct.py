"""Operator construction registry.

Capability parity with the reference's construct_operator dispatch
(/root/reference/crates/arroyo-worker/src/engine.rs:805-900): maps each
OperatorName to a factory that decodes the node's config into a runnable
Operator. This is the single seam where execution backends are chosen — the
window/join factories consult config.tpu to pick device (JAX) or host
(numpy) kernels.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ..graph.logical import ChainedOp, LogicalNode, OperatorName
from ..operators.base import Operator

_REGISTRY: Dict[OperatorName, Callable[[dict], Operator]] = {}


def register_operator(name: OperatorName):
    def deco(factory: Callable[[dict], Operator]):
        _REGISTRY[name] = factory
        return factory

    return deco


def construct_operator(op: ChainedOp) -> Operator:
    _ensure_registered()
    if op.operator not in _REGISTRY:
        raise ValueError(f"no operator factory registered for {op.operator}")
    operator = _REGISTRY[op.operator](op.config)
    if op.description:
        operator.name = op.description
    return operator


def construct_chain(node: LogicalNode) -> List[Operator]:
    return [construct_operator(op) for op in node.chain]


_LOADED = False


def _ensure_registered():
    """Import the modules whose import side-effect registers factories."""
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    from ..operators import projection, watermark_generator, windows  # noqa: F401
    from ..operators import joins, updating, window_fn, async_udf  # noqa: F401
    from .. import connectors  # noqa: F401
    from . import segments  # noqa: F401  (FUSED_SEGMENT factory)
