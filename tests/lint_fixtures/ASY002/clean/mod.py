"""Must NOT fire ASY002: sleeps are awaited, sync work goes to a thread."""
import asyncio
import subprocess
import time


def sync_helper():
    time.sleep(0.5)  # fine: not inside async def
    subprocess.run(["true"], check=True)


async def go():
    await asyncio.sleep(0.5)
    await asyncio.to_thread(sync_helper)
