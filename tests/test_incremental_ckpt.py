"""Incremental window checkpoints: bytes per epoch scale with the delta
(slots touched since the last epoch), not total live state.

VERDICT round-1 item 4. Reference design being mirrored:
/root/reference/crates/arroyo-state/src/tables/expiring_time_key_map.rs:53
(incremental files + carried live-file list), flush at table_manager.rs:368.
"""

import asyncio
import glob
import json
import os

import pyarrow.parquet as pq

from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query


def test_window_checkpoint_bytes_scale_with_delta(tmp_path):
    n = 3000
    src = str(tmp_path / "in.json")
    with open(src, "w") as f:
        for i in range(n):
            # all rows inside ONE 1-hour window; every counter is a new key
            f.write(
                json.dumps(
                    {
                        "counter": i,
                        "timestamp": f"2023-03-01T00:00:{i % 50:02d}.000Z",
                    }
                )
                + "\n"
            )
    sink = str(tmp_path / "out.json")
    sql = f"""
    CREATE TABLE src (
      timestamp TIMESTAMP, counter BIGINT NOT NULL
    ) WITH (connector = 'single_file', path = '{src}', format = 'json',
            type = 'source', throttle_per_sec = '6000',
            event_time_field = 'timestamp');
    CREATE TABLE out (
      k BIGINT NOT NULL, cnt BIGINT NOT NULL
    ) WITH (connector = 'single_file', path = '{sink}', format = 'json',
            type = 'sink');
    INSERT INTO out
    SELECT counter as k, count(*) as cnt
    FROM src GROUP BY 1, tumble(interval '1 hour');
    """
    storage = str(tmp_path / "ckpt")

    async def run():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="inc", storage_url=storage).start()
        # progress-gated (not sleep-gated): each mid-stream checkpoint waits
        # until the window operator has received at least one new batch, so
        # every epoch's delta is non-empty regardless of machine speed
        win = next(
            s for s in eng.program.subtasks
            if not s.node.is_source and "window" in s.node.description
        )
        recv = win.runner._batches_recv
        import time as _time

        async def one_more_batch(last: float, timeout: float = 30.0):
            t0 = _time.monotonic()
            while recv.get() <= last and _time.monotonic() - t0 < timeout:
                await asyncio.sleep(0.01)
            return recv.get()

        seen = 0.0
        for _ in range(3):
            seen = await one_more_batch(seen)
            await eng.checkpoint_and_wait()
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(120)

    asyncio.run(run())

    files = sorted(
        glob.glob(os.path.join(storage, "**", "*.parquet"), recursive=True)
    )
    window_files = [f for f in files if "-ti-" in os.path.basename(f)]
    assert len(window_files) >= 3, (
        f"expected one delta file per epoch with new keys, got {files}"
    )
    rows_per_file = [pq.read_table(f).num_rows for f in window_files]
    total_rows = sum(rows_per_file)
    # each key is touched once, so the union of deltas covers each live key
    # about once; a full-snapshot design would rewrite all keys seen so far
    # at every epoch (sum >> n)
    assert total_rows <= int(n * 1.5), (
        f"deltas rewrote state: {rows_per_file} (n={n})"
    )
    # no single epoch rewrites (nearly) the whole key space
    assert max(rows_per_file) < n, rows_per_file
    # and later epochs don't grow with cumulative state: the biggest file
    # must not dwarf the per-epoch arrival volume
    assert min(rows_per_file) > 0


def test_incremental_restore_supersedes_older_rows(tmp_path):
    """A key updated across epochs appears in several delta files; restore
    must keep the newest values (checkpoint -> stop -> restore -> final
    output equals an uninterrupted run)."""
    n = 2000
    src = str(tmp_path / "in.json")
    with open(src, "w") as f:
        for i in range(n):
            f.write(
                json.dumps(
                    {
                        "counter": i % 7,  # every key updated every epoch
                        "timestamp": f"2023-03-01T00:00:{i % 40:02d}.000Z",
                    }
                )
                + "\n"
            )

    def sql_for(sink, throttled):
        throttle = "throttle_per_sec = '4000'," if throttled else ""
        return f"""
        CREATE TABLE src (
          timestamp TIMESTAMP, counter BIGINT NOT NULL
        ) WITH (connector = 'single_file', path = '{src}', format = 'json',
                type = 'source', {throttle}
                event_time_field = 'timestamp');
        CREATE TABLE out (
          k BIGINT NOT NULL, cnt BIGINT NOT NULL, total BIGINT NOT NULL
        ) WITH (connector = 'single_file', path = '{sink}', format = 'json',
                type = 'sink');
        INSERT INTO out
        SELECT counter as k, count(*) as cnt, sum(counter) as total
        FROM src GROUP BY 1, tumble(interval '1 hour');
        """

    # uninterrupted reference run
    sink_full = str(tmp_path / "full.json")

    async def run_full():
        plan = plan_query(sql_for(sink_full, False), parallelism=1)
        eng = Engine(plan.graph).start()
        await eng.join(120)

    asyncio.run(run_full())

    # checkpointed run: stop mid-stream, restore, finish
    sink_r = str(tmp_path / "restored.json")
    storage = str(tmp_path / "ckpt")

    async def phase1():
        plan = plan_query(sql_for(sink_r, True), parallelism=1)
        eng = Engine(plan.graph, job_id="sup", storage_url=storage).start()
        for _ in range(2):
            await asyncio.sleep(0.1)
            await eng.checkpoint_and_wait()
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(120)

    asyncio.run(phase1())

    async def phase2():
        plan = plan_query(sql_for(sink_r, False), parallelism=1)
        eng = Engine(plan.graph, job_id="sup", storage_url=storage).start()
        await eng.join(120)

    asyncio.run(phase2())

    read = lambda p: sorted(
        json.dumps(json.loads(x), sort_keys=True)
        for x in open(p)
        if x.strip()
    )
    assert read(sink_r) == read(sink_full)
