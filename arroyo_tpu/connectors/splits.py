"""Source split elasticity (ISSUE 15): repartitionable offset state.

A *split* is the unit of source repartitioning: a named, self-contained
slice of a source's assigned range whose progress ("offset state") is
checkpointed under the SPLIT's id instead of the consuming subtask's
index. That inversion is what makes source parallelism actuable by the
autoscaler: on restore at ANY parallelism every subtask sees the same
replicated union of split payloads (the global-table re-read the keyed
tables already rely on), derives the same deterministic subdivision, and
round-robins ownership — no gap, no overlap, no coordination.

Split algebra per connector:

  * impulse — a split is an arithmetic progression of counters
    `{emit, next, step, hi}` emitting rows {counter=next+k*step,
    subtask_index=emit}. Subdividing doubles the stride:
    (next, s) -> (next, 2s) + (next+s, 2s); the remaining set is
    conserved exactly, bounded or unbounded.
  * nexmark — a split is a residue class of the GLOBAL event sequence
    `{r, mod, i}` emitting n = r + j*mod for j >= i. Subdividing maps
    residue r (mod m) onto residues r and r+m (mod 2m) with the emitted
    prefix split index-exactly: (r, m, i) -> (r, 2m, ceil(i/2)) +
    (r+m, 2m, floor(i/2)).
  * kafka — a split is a topic partition `{partition, offset}`;
    partitions cannot subdivide (broker-side), so elasticity is
    reassignment only and automatic source scaling leaves kafka alone.

Subdivision supersedes the parent split: children are checkpointed (one
epoch's manifest is all-or-nothing, so they appear atomically) and
`load_splits` drops any split with a descendant present. A crash before
the first post-rescale checkpoint restores the parents and re-derives
the identical children — exactly-once holds because downstream state
rolled back to the same epoch.

Property-tested in tests/test_source_splits.py: offsets conserved, no
gap/overlap across 1 -> 4 -> 2 -> 3 repartitions, per connector.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

# global-table key namespace for split payloads (legacy per-subtask
# offset entries used bare int task-index keys; both coexist in a table)
SPLIT_PREFIX = "s:"

Payload = Dict[str, object]


def split_key(split_id: str) -> str:
    return SPLIT_PREFIX + split_id


def load_splits(table) -> Dict[str, Payload]:
    """Every split payload in the table's replicated union, with
    superseded parents dropped (a split any of whose descendants is
    present was subdivided at an earlier rescale boundary)."""
    splits: Dict[str, Payload] = {}
    for k, v in table.items():
        if isinstance(k, str) and k.startswith(SPLIT_PREFIX):
            splits[k[len(SPLIT_PREFIX):]] = dict(v)
    ids = sorted(splits)
    return {
        sid: p
        for sid, p in splits.items()
        if not any(o != sid and o.startswith(sid + ".") for o in ids)
    }


def ensure_splits(
    splits: Dict[str, Payload],
    parallelism: int,
    subdivide: Callable[[str, Payload], Optional[Dict[str, Payload]]],
) -> Dict[str, Payload]:
    """Deterministically subdivide until there are >= parallelism splits
    (or nothing subdivides — kafka partitions, exhausted ranges). The
    rule — repeatedly split the lexicographically-first subdividable
    split — is position-free, so every subtask computes the identical
    result from the identical restored union."""
    out = {sid: dict(p) for sid, p in splits.items()}
    while len(out) < parallelism:
        for sid in sorted(out):
            kids = subdivide(sid, out[sid])
            if kids:
                del out[sid]
                out.update(kids)
                break
        else:
            return out
    return out


def owned(splits: Dict[str, Payload], parallelism: int,
          task_index: int) -> Dict[str, Payload]:
    """Round-robin ownership by sorted-id rank: disjoint across
    subtasks, total over the split set."""
    return {
        sid: p
        for i, (sid, p) in enumerate(sorted(splits.items()))
        if i % max(1, parallelism) == task_index
    }


# -- impulse ------------------------------------------------------------------


def impulse_plan(parallelism: int,
                 message_count: Optional[int]) -> Dict[str, Payload]:
    """Initial splits replicate the classic impulse shape exactly: one
    counter stream 0..message_count per planned subtask, stamped with
    that subtask's index."""
    return {
        f"i{k}": {"emit": k, "next": 0, "step": 1, "hi": message_count}
        for k in range(max(1, parallelism))
    }


def impulse_subdivide(sid: str, p: Payload) -> Optional[Dict[str, Payload]]:
    s = int(p.get("step", 1))
    hi = p.get("hi")
    if hi is not None and int(p["next"]) >= int(hi):
        return None  # exhausted: nothing left to repartition
    return {
        f"{sid}.0": {**p, "step": 2 * s},
        f"{sid}.1": {**p, "next": int(p["next"]) + s, "step": 2 * s},
    }


def impulse_remaining(p: Payload) -> Optional[int]:
    """Events this split still owes (None = unbounded)."""
    hi = p.get("hi")
    if hi is None:
        return None
    nxt, step = int(p["next"]), int(p.get("step", 1))
    if nxt >= int(hi):
        return 0
    return (int(hi) - 1 - nxt) // step + 1


def impulse_counters(p: Payload):
    """Every counter this split will EVER emit, from position 0 (the
    property tests' conservation oracle). Bounded splits only."""
    hi = p.get("hi")
    assert hi is not None
    return range(int(p["next"]), int(hi), int(p.get("step", 1)))


# -- nexmark ------------------------------------------------------------------


def nexmark_plan(parallelism: int) -> Dict[str, Payload]:
    """Initial splits replicate the classic strided shape: subtask k of
    p generates global sequence numbers n ≡ k (mod p)."""
    return {
        f"n{k}": {"r": k, "mod": max(1, parallelism), "i": 0}
        for k in range(max(1, parallelism))
    }


def nexmark_subdivide(sid: str, p: Payload) -> Optional[Dict[str, Payload]]:
    r, m, i = int(p["r"]), int(p["mod"]), int(p["i"])
    return {
        f"{sid}.0": {"r": r, "mod": 2 * m, "i": (i + 1) // 2},
        f"{sid}.1": {"r": r + m, "mod": 2 * m, "i": i // 2},
    }


def nexmark_next_n(p: Payload) -> int:
    """The next global sequence number this split will emit."""
    return int(p["r"]) + int(p["i"]) * int(p["mod"])


def nexmark_remaining(p: Payload, message_count: Optional[int]) -> Optional[int]:
    if message_count is None:
        return None
    n0 = nexmark_next_n(p)
    if n0 >= message_count:
        return 0
    return (message_count - 1 - n0) // int(p["mod"]) + 1


def nexmark_sequence(p: Payload, message_count: int):
    """Every global sequence number this split will ever emit from its
    current position (property-test oracle)."""
    return range(nexmark_next_n(p), message_count, int(p["mod"]))
