"""Must NOT fire RACE003: every access to the guarded field happens with
`_lock` held (directly or in a callee whose every call site holds it);
constructor initialization is exempt."""
from arroyo_tpu.analysis.races import guarded_by


@guarded_by("_lock", "fired")
class Plan:
    def __init__(self):
        self.fired = []
        self._lock = None


class Driver:
    def touch(self, plan):
        with plan._lock:
            plan.fired.append(1)

    def drain(self, plan):
        with plan._lock:
            self._drain_locked(plan)

    def _drain_locked(self, plan):
        # entry lockset: every caller holds _lock
        plan.fired.clear()

    def peek(self, plan):
        with plan._lock:
            return len(plan.fired)
