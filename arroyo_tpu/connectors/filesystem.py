"""Filesystem connector: file source + rolling Parquet/JSON sink.

Capability parity with the reference's filesystem connector
(/root/reference/crates/arroyo-connectors/src/filesystem/, 12,086 LoC incl.
Delta/Iceberg): this round implements the core — a source that reads
json/parquet files under a path (positions checkpointed), and a sink that
writes rolling files (rotated on row-count/size/checkpoint) through the
two-phase pattern: data lands in `.tmp` files, files are finalized (renamed
visible) on `handle_commit` after the checkpoint that contains them is
durable. Delta Lake / Iceberg catalogs are future work tracked in
SURVEY.md §2.9.
"""

from __future__ import annotations

import json
import os
import uuid
from typing import List

import pyarrow as pa
import pyarrow.parquet as pq

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from .base import ConnectionSchema, Connector, register_connector


class FileSystemSource(SourceOperator):
    def __init__(self, path: str, schema, format: str, bad_data: str):
        super().__init__("filesystem_source")
        self.path = path
        self.out_schema = schema
        self.format = format or "json"
        self.deserializer = (
            Deserializer(schema, format=self.format, bad_data=bad_data)
            if self.format not in ("parquet",)
            else None
        )
        self.position = [0, 0]  # file index, row index

    def tables(self):
        from ..state.table_config import global_table

        return {"fs": global_table("fs")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("fs")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.position = list(stored)

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("fs")
            table.put(ctx.task_info.task_index, list(self.position))

    def _files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        out = []
        for root, _, names in os.walk(self.path):
            for n in sorted(names):
                if not n.startswith(".") and not n.endswith(".tmp"):
                    out.append(os.path.join(root, n))
        return sorted(out)

    async def run(self, ctx, collector) -> SourceFinishType:
        files = self._files()
        p = ctx.task_info.parallelism
        me = ctx.task_info.task_index
        for fi, fpath in enumerate(files):
            if fi % p != me or fi < self.position[0]:
                continue
            start_row = self.position[1] if fi == self.position[0] else 0
            row_idx = 0
            if fpath.endswith(".parquet") or self.format == "parquet":
                from ..schema import TIMESTAMP_FIELD
                from ..types import now_nanos

                table = pq.read_table(fpath)
                for batch in table.to_batches():
                    for row in batch.to_pylist():
                        if row_idx >= start_row:
                            finish = await ctx.check_control(collector)
                            if finish is not None:
                                return finish
                            if row.get(TIMESTAMP_FIELD) is None:
                                row[TIMESTAMP_FIELD] = now_nanos()
                            ctx.buffer_row(row)
                            self.position = [fi, row_idx + 1]
                            if ctx.should_flush():
                                await self.flush_buffer(ctx, collector)
                        row_idx += 1
            else:
                with open(fpath, "rb") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            row_idx += 1
                            continue
                        if row_idx >= start_row:
                            finish = await ctx.check_control(collector)
                            if finish is not None:
                                return finish
                            for row in self.deserializer.deserialize_slice(
                                line, error_reporter=ctx.error_reporter
                            ):
                                ctx.buffer_row(row)
                            self.position = [fi, row_idx + 1]
                            if ctx.should_flush():
                                await self.flush_buffer(ctx, collector)
                        row_idx += 1
            self.position = [fi + 1, 0]
        await self.flush_buffer(ctx, collector)
        return SourceFinishType.FINAL


class FileSystemSink(Operator):
    """Rolling file sink with two-phase commit: rows buffer into an open
    .tmp file; at checkpoint the open file is rolled and its name stashed as
    commit data; on commit the .tmp files are renamed visible (reference:
    filesystem/sink two_phase_committer.rs:40)."""

    def __init__(self, path: str, format: str, rollover_rows: int = 100_000):
        super().__init__("filesystem_sink")
        self.path = path
        self.format = format or "json"
        self.rollover_rows = rollover_rows
        self.serializer = Serializer(format="json") if self.format == "json" else None
        self._rows: List[pa.RecordBatch] = []
        self._n_rows = 0
        self._pending_tmp: List[str] = []  # rolled since the last barrier
        self._committing: dict = {}  # epoch -> files sealed at that barrier
        self._file_seq = 0

    def tables(self):
        from ..state.table_config import global_table

        return {"fsk": global_table("fsk")}

    async def on_start(self, ctx):
        os.makedirs(self.path, exist_ok=True)
        if ctx.table_manager is not None:
            table = await ctx.table("fsk")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self._file_seq = stored.get("file_seq", 0)
                # finalize files whose checkpoint committed but rename was
                # lost in the crash
                for tmp in stored.get("pending", []):
                    if os.path.exists(tmp):
                        os.replace(tmp, tmp[: -len(".tmp")])

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        self._rows.append(batch)
        self._n_rows += batch.num_rows
        if self._n_rows >= self.rollover_rows:
            self._roll(ctx)

    def _roll(self, ctx):
        if not self._rows:
            return
        ext = "parquet" if self.format == "parquet" else "json"
        name = (
            f"{ctx.task_info.task_index:03d}-{self._file_seq:05d}-"
            f"{uuid.uuid4().hex[:8]}.{ext}"
        )
        self._file_seq += 1
        tmp = os.path.join(self.path, name + ".tmp")
        table = pa.Table.from_batches(self._rows)
        if self.format == "parquet":
            pq.write_table(table, tmp)
        else:
            with open(tmp, "wb") as f:
                for b in self._rows:
                    for rec in self.serializer.serialize(b):
                        f.write(rec + b"\n")
        self._rows = []
        self._n_rows = 0
        self._pending_tmp.append(tmp)

    async def handle_checkpoint(self, barrier, ctx, collector):
        self._roll(ctx)
        # seal exactly the files rolled before this barrier; later rolls
        # belong to the next epoch and must not become visible on commit
        sealed, self._pending_tmp = self._pending_tmp, []
        self._committing[barrier.epoch] = sealed
        ctx.commit_data = json.dumps(sealed).encode()
        if ctx.table_manager is not None:
            table = await ctx.table("fsk")
            table.put(
                ctx.task_info.task_index,
                {
                    "file_seq": self._file_seq,
                    "pending": [
                        f for files in self._committing.values() for f in files
                    ],
                },
            )

    async def handle_commit(self, epoch, commit_data, ctx):
        sealed = self._committing.pop(epoch, None)
        if sealed is None:
            # recovery path: the manifest's commit payload names the files
            payload = (commit_data or {}).get("data", {}).get(
                ctx.task_info.task_index
            )
            if isinstance(payload, dict) and "__hex__" in payload:
                sealed = json.loads(bytes.fromhex(payload["__hex__"]))
            else:
                sealed = []
        finalized = self._finalize(sealed)
        await self._committed(finalized, ctx)
        return finalized

    @staticmethod
    def _finalize(tmps: List[str]) -> List[str]:
        """Rename committed .tmp files visible; returns the final paths."""
        out = []
        for tmp in tmps:
            if os.path.exists(tmp):
                os.replace(tmp, tmp[: -len(".tmp")])
                out.append(tmp[: -len(".tmp")])
        return out

    async def _committed(self, files: List[str], ctx):
        """Hook: files became visible under a durable commit (DeltaSink
        appends them to the transaction log)."""

    async def on_close(self, ctx, collector, is_eod: bool):
        # EOD without a final checkpoint: finalize remaining data directly
        if is_eod:
            self._roll(ctx)
            finalized = self._finalize(self._pending_tmp)
            self._pending_tmp = []
            await self._committed(finalized, ctx)
            for epoch in list(self._committing):
                await self.handle_commit(epoch, {}, ctx)
        return None


@register_connector
class FileSystemConnector(Connector):
    name = "filesystem"
    description = "reads/writes files (json, parquet) under a directory"
    source = True
    sink = True
    config_schema = {
        "path": {"type": "string", "required": True},
        "rollover_rows": {"type": "integer"},
    }

    def validate_options(self, options, schema):
        if "path" not in options:
            raise ValueError("filesystem requires a path option")
        out = {"path": options["path"]}
        if "rollover_rows" in options:
            out["rollover_rows"] = int(options["rollover_rows"])
        return out

    def make_source(self, config, schema: ConnectionSchema):
        return FileSystemSource(
            config["path"], config.get("schema"), config.get("format"),
            config.get("bad_data", "fail"),
        )

    def make_sink(self, config, schema: ConnectionSchema):
        return FileSystemSink(
            config["path"], config.get("format"),
            config.get("rollover_rows", 100_000),
        )
