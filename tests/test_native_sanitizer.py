"""ASan/UBSan run over the native slot directory (SURVEY §5.2: host C++
gets sanitizers where the reference relies on Rust ownership). Builds
slotdir.cpp with -fsanitize=address,undefined and drives random
assign/take/get cycles against the pure-python directory under
LD_PRELOAD=libasan — see tools/sanitize_native.py."""

import os
import subprocess
import sys

import pytest


def _libasan() -> str:
    try:
        return subprocess.run(
            ["g++", "-print-file-name=libasan.so"], capture_output=True,
            text=True, timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        return ""


@pytest.mark.skipif(
    not os.path.exists(_libasan() or "/nonexistent"),
    reason="libasan not available",
)
def test_native_slotdir_sanitized():
    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "sanitize_native.py",
    )
    proc = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=400,
    )
    assert proc.returncode == 0, (
        f"sanitizer run failed:\n{proc.stdout[-2000:]}\n"
        f"{proc.stderr[-4000:]}"
    )
