"""User-defined functions: scalar UDFs, UDAFs, async UDFs.

Capability parity with the reference's arroyo-udf crates
(/root/reference/crates/arroyo-udf/*): the reference compiles Rust UDF
dylibs and embeds CPython for Python UDFs; here Python IS the host language,
so a UDF is a vectorized python function registered by name (decorator or
source-text registration through the API, mirroring the reference's
CREATE-UDF flow). Functions declare arrow types; scalar UDFs receive numpy
arrays and return an array; UDAFs receive the grouped values vector.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa


@dataclasses.dataclass
class PythonUdf:
    name: str
    fn: Callable
    arg_types: List[pa.DataType]
    return_type: pa.DataType
    vectorized: bool = True

    @property
    def is_async(self) -> bool:
        import inspect

        return inspect.iscoroutinefunction(self.fn)

    def bind(self, args):
        if self.is_async:
            from ..sql.lexer import SqlError

            raise SqlError(
                f"{self.name}() is an async UDF and must be a top-level "
                "SELECT item (planned as an async operator)"
            )
        from ..sql.expressions import BoundExpr

        def call(batch):
            vals = []
            for a in args:
                v = a.eval(batch)
                vals.append(np.asarray(v.to_numpy(zero_copy_only=False)))
            if self.vectorized:
                out = self.fn(*vals)
            else:
                out = np.array(
                    [self.fn(*row) for row in zip(*vals)], dtype=object
                )
            return pa.array(out, type=self.return_type)

        return BoundExpr(call, self.return_type, self.name)


@dataclasses.dataclass
class PythonUdaf:
    name: str
    fn: Callable  # values (np.ndarray) -> scalar
    arg_types: List[pa.DataType]
    return_type: pa.DataType


_UDFS: Dict[str, PythonUdf] = {}
_UDAFS: Dict[str, PythonUdaf] = {}


def udf(return_type, arg_types=(), name: Optional[str] = None,
        vectorized: bool = True):
    """Decorator: @udf(pa.int64(), [pa.int64()]) def double(xs): ..."""

    def deco(fn):
        u = PythonUdf(
            name or fn.__name__, fn, list(arg_types), return_type, vectorized
        )
        _UDFS[u.name] = u
        return fn

    return deco


def udaf(return_type, arg_types=(), name: Optional[str] = None):
    def deco(fn):
        u = PythonUdaf(name or fn.__name__, fn, list(arg_types), return_type)
        _UDAFS[u.name] = u
        return fn

    return deco


def get(name: str) -> Optional[PythonUdf]:
    return _UDFS.get(name)


def get_udaf(name: str) -> Optional[PythonUdaf]:
    return _UDAFS.get(name)


def register_from_source(source: str) -> List[str]:
    """Register UDFs from python source text (the API's CREATE-UDF path,
    reference: arroyo-api udfs.rs). The source must call @udf/@udaf.
    Returns every name the source (re)registered."""
    before_u = dict(_UDFS)
    before_a = dict(_UDAFS)
    namespace = {"udf": udf, "udaf": udaf, "pa": pa, "np": np}
    exec(compile(source, "<udf>", "exec"), namespace)  # noqa: S102
    changed = [
        n for n in _UDFS if _UDFS[n] is not before_u.get(n)
    ] + [n for n in _UDAFS if _UDAFS[n] is not before_a.get(n)]
    return sorted(set(changed))


def snapshot() -> tuple:
    """Capture registry state so a validation-only registration can be
    rolled back exactly (including redefinitions of existing names)."""
    return dict(_UDFS), dict(_UDAFS)


def restore(snap: tuple):
    _UDFS.clear()
    _UDFS.update(snap[0])
    _UDAFS.clear()
    _UDAFS.update(snap[1])


def clear_dynamic(names: List[str]):
    for n in names:
        _UDFS.pop(n, None)
        _UDAFS.pop(n, None)
