"""RabbitMQ connector (reference: crates/arroyo-connectors/src/rabbitmq/,
467 LoC): durable queues with consumer prefetch, at-least-once delivery
(messages are acked at the CHECKPOINT barrier, after their rows are
flushed downstream and covered by the epoch — a crash before the ack
redelivers, never loses), persistent delivery on the sink, and optional
exchange/routing-key addressing. Client gated on aio-pika/pika.

Throughput note: because acks are deferred to the checkpoint COMMIT
phase, the broker stops delivering once `prefetch` messages are
unacked — prefetch bounds the per-checkpoint-interval volume. The
default is sized accordingly (10k); size `prefetch` to at least the
expected per-epoch message count."""

from __future__ import annotations

import asyncio
from typing import Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector

# acks defer to the checkpoint COMMIT phase, so prefetch bounds the
# per-checkpoint-interval volume (see module docstring)
DEFAULT_PREFETCH = 10000


class RabbitmqSource(SourceOperator):
    def __init__(self, url: str, queue: str, schema, format, bad_data,
                 prefetch: int = DEFAULT_PREFETCH):
        super().__init__("rabbitmq_source")
        self.url = url
        self.queue = queue
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.prefetch = prefetch
        self._unacked: list = []
        self._pending_acks: dict = {}  # epoch -> messages awaiting commit

    async def handle_checkpoint(self, barrier, ctx, collector):
        # stage this epoch's messages for the COMMIT phase: the ack must
        # wait until the checkpoint manifest is durably published (a
        # barrier-time ack would lose data if the epoch's flush later
        # failed and the job restored to the previous epoch). Registering
        # commit_data makes the job controller run 2PC for this epoch.
        if self._unacked:
            self._pending_acks[barrier.epoch] = self._unacked
            self._unacked = []
            ctx.commit_data = b"rabbitmq-acks"

    async def handle_commit(self, epoch, commit_data, ctx):
        for m in self._pending_acks.pop(epoch, []):
            await m.ack()

    async def run(self, ctx, collector) -> SourceFinishType:
        aio_pika = require_client("aio_pika")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        conn = await aio_pika.connect_robust(self.url)
        async with conn:
            channel = await conn.channel()
            await channel.set_qos(prefetch_count=self.prefetch)
            queue = await channel.declare_queue(self.queue, durable=True)
            async with queue.iterator() as it:
                async def on_message(message):
                    for row in deser.deserialize_slice(
                        message.body, error_reporter=ctx.error_reporter
                    ):
                        ctx.buffer_row(row)
                    self._unacked.append(message)

                finish = await self.poll_async_iter(
                    it.__aiter__(), ctx, collector, on_message
                )
                if finish is not None:
                    return finish
                # stream ended: the tail is flushed at source close and
                # the pipeline drains, so ack the remainder
                await self.flush_buffer(ctx, collector)
                for m in self._unacked:
                    await m.ack()
                self._unacked = []
        return SourceFinishType.FINAL


class RabbitmqSink(Operator):
    def __init__(self, url: str, queue: str, format,
                 exchange: Optional[str] = None,
                 routing_key: Optional[str] = None):
        super().__init__("rabbitmq_sink")
        self.url = url
        self.queue = queue
        self.exchange_name = exchange
        self.routing_key = routing_key or queue
        self.serializer = Serializer(format=format or "json")
        self.conn = None
        self.channel = None
        self.exchange = None

    async def on_start(self, ctx):
        aio_pika = require_client("aio_pika")
        self.conn = await aio_pika.connect_robust(self.url)
        self.channel = await self.conn.channel()
        if self.exchange_name:
            self.exchange = await self.channel.get_exchange(
                self.exchange_name
            )
        else:
            self.exchange = self.channel.default_exchange
        self._aio_pika = aio_pika

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        persistent = getattr(
            self._aio_pika, "DeliveryMode", None
        )
        for rec in self.serializer.serialize(batch):
            msg = self._aio_pika.Message(
                body=rec,
                **(
                    {"delivery_mode": persistent.PERSISTENT}
                    if persistent is not None else {}
                ),
            )
            await self.exchange.publish(msg, routing_key=self.routing_key)

    async def on_close(self, ctx, collector, is_eod: bool):
        if self.conn is not None:
            await self.conn.close()
        return None


@register_connector
class RabbitmqConnector(Connector):
    name = "rabbitmq"
    description = "RabbitMQ source and sink"
    source = True
    sink = True
    config_schema = {
        "url": {"type": "string", "required": True},
        "queue": {"type": "string", "required": True},
        "prefetch": {"type": "integer"},
        "exchange": {"type": "string"},
        "routing_key": {"type": "string"},
    }

    def validate_options(self, options, schema):
        for k in ("url", "queue"):
            if k not in options:
                raise ValueError(f"rabbitmq requires a {k} option")
        return {
            "url": options["url"],
            "queue": options["queue"],
            "prefetch": int(options.get("prefetch", DEFAULT_PREFETCH)),
            "exchange": options.get("exchange"),
            "routing_key": options.get("routing_key"),
        }

    def make_source(self, config, schema: ConnectionSchema):
        return RabbitmqSource(config["url"], config["queue"],
                              config.get("schema"), config.get("format"),
                              config.get("bad_data", "fail"),
                              prefetch=config.get("prefetch", DEFAULT_PREFETCH))

    def make_sink(self, config, schema: ConnectionSchema):
        return RabbitmqSink(config["url"], config["queue"],
                            config.get("format"),
                            exchange=config.get("exchange"),
                            routing_key=config.get("routing_key"))
