"""WebSocket source.

Capability parity with the reference's websocket connector
(/root/reference/crates/arroyo-connectors/src/websocket/, 609 LoC):
connects to an endpoint, optionally sends subscription messages, and
deserializes incoming text/binary frames.
"""

from __future__ import annotations

from typing import List

from ..operators.base import SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from .base import ConnectionSchema, Connector, register_connector


class WebSocketSource(SourceOperator):
    def __init__(self, endpoint: str, subscription_messages: List[str],
                 schema, format: str, bad_data: str):
        super().__init__("websocket_source")
        self.endpoint = endpoint
        self.subscription_messages = subscription_messages
        self.out_schema = schema
        self.deserializer = Deserializer(schema, format=format or "json",
                                         bad_data=bad_data)

    async def run(self, ctx, collector) -> SourceFinishType:
        import websockets

        if ctx.task_info.task_index != 0:
            return SourceFinishType.FINAL
        async with websockets.connect(self.endpoint) as ws:
            for msg in self.subscription_messages:
                await ws.send(msg)

            async def on_frame(frame):
                payload = (
                    frame.encode() if isinstance(frame, str) else frame
                )
                for row in self.deserializer.deserialize_slice(
                    payload, error_reporter=ctx.error_reporter
                ):
                    ctx.buffer_row(row)

            # shared select-over-control poll loop: a QUIET stream must
            # not block checkpoint barriers or stop. Iteration ends
            # cleanly only on a normal close (the iterator raises on
            # abnormal closure, surfacing a task failure).
            finish = await self.poll_async_iter(
                ws.__aiter__(), ctx, collector, on_frame
            )
            if finish is not None:
                return finish
        return SourceFinishType.FINAL


@register_connector
class WebSocketConnector(Connector):
    name = "websocket"
    description = "websocket client source"
    source = True
    config_schema = {
        "endpoint": {"type": "string", "required": True},
        "subscription_message": {"type": "string"},
    }

    def validate_options(self, options, schema):
        if "endpoint" not in options:
            raise ValueError("websocket requires an endpoint option")
        subs = []
        for k in sorted(options):
            if k.startswith("subscription_message"):
                subs.append(options[k])
        return {"endpoint": options["endpoint"], "subscription_messages": subs}

    def make_source(self, config, schema: ConnectionSchema):
        return WebSocketSource(
            config["endpoint"], config.get("subscription_messages", []),
            config.get("schema"), config.get("format"),
            config.get("bad_data", "fail"),
        )
