--pk=id
CREATE TABLE debezium_source (
  id BIGINT PRIMARY KEY,
  customer_name TEXT,
  product_name TEXT,
  quantity BIGINT,
  price DOUBLE,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/aggregate_updates.json',
  format = 'debezium_json',
  type = 'source'
);
CREATE TABLE output (
  id BIGINT,
  customer_name TEXT,
  product_name TEXT,
  quantity BIGINT,
  price DOUBLE,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT id, customer_name, product_name, quantity, price, status
FROM debezium_source;
