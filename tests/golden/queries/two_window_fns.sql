CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (g BIGINT, c BIGINT, rn BIGINT, rk BIGINT) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT W.g, W.c,
       row_number() OVER (PARTITION BY W.par ORDER BY W.c DESC, W.g ASC) as rn,
       rank() OVER (ORDER BY W.c DESC) as rk
FROM (
  SELECT counter % 6 as g, (counter % 6) % 2 as par, count(*) as c,
         tumble(interval '30 second') as w
  FROM impulse
  GROUP BY 1, 2, w
) AS W;
