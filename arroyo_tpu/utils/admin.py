"""Per-process admin HTTP server: /status, /metrics, /debug/*.

Capability parity with the reference's admin server
(/root/reference/crates/arroyo-server-common/src/lib.rs start_admin_server:
/status, /name, /metrics, /debug/pprof): every role (controller, worker,
api) can expose liveness, Prometheus metrics, a stack/task dump, and a
windowed CPU profile capture (/debug/profile — the Python analog of the
reference's /debug/pprof/profile flamegraph endpoint,
arroyo-server-common/src/profile.rs:12-51) on a local port.
"""

from __future__ import annotations

import asyncio
import io
import time
from typing import Optional

from aiohttp import web

from ..config import config
from ..utils.logging import get_logger

logger = get_logger("admin")

_STARTED = time.time()


def build_admin_app(role: str, details_fn=None,
                    extra_routes: Optional[dict] = None) -> web.Application:
    """`details_fn() -> dict` supplies role-specific status fields;
    `extra_routes` maps paths to aiohttp GET handlers for role-specific
    debug surfaces (the controller mounts /debug/autoscale this way)."""

    async def status(request: web.Request):
        body = {
            "service": f"arroyo-tpu-{role}",
            "status": "ok",
            "uptime_seconds": round(time.time() - _STARTED, 1),
        }
        if details_fn is not None:
            try:
                body.update(details_fn() or {})
            except Exception as e:  # noqa: BLE001
                body["details_error"] = repr(e)
        return web.json_response(body)

    async def name(request: web.Request):
        return web.Response(text=f"arroyo-tpu-{role}\n")

    async def metrics(request: web.Request):
        from ..metrics import REGISTRY

        return web.Response(
            text=REGISTRY.expose(),
            content_type="text/plain",
        )

    async def debug_tasks(request: web.Request):
        lines = []
        for t in asyncio.all_tasks():
            coro = t.get_coro()
            lines.append(
                f"{'CANCELLED' if t.cancelled() else 'DONE' if t.done() else 'RUNNING'} "
                f"{getattr(coro, '__qualname__', coro)}"
            )
        return web.Response(text="\n".join(sorted(lines)) + "\n",
                            content_type="text/plain")

    async def debug_stacks(request: web.Request):
        import sys
        import threading
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        buf = io.StringIO()
        for tid, frame in sys._current_frames().items():
            buf.write(f"Thread {names.get(tid, tid)}:\n")
            buf.write("".join(traceback.format_stack(frame)))
            buf.write("\n")
        return web.Response(text=buf.getvalue(), content_type="text/plain")

    profile_lock = asyncio.Lock()

    async def debug_profile(request: web.Request):
        """CPU profile capture over a sampling window (reference:
        /debug/pprof/profile flamegraphs, arroyo-server-common
        profile.rs:12-51). cProfile wraps the event-loop thread for
        ?seconds=N (default 5, max 60) and returns the pstats table
        sorted by ?sort= (tottime default) — round-4's perf work leaned
        on ad-hoc cProfile runs; this standardizes the capture."""
        import cProfile
        import pstats

        try:
            seconds = min(float(request.query.get("seconds", 5)), 60.0)
            # row budget for the pstats table: stage-budget consumers
            # (tools/mesh_profile.py) need the long tail, humans don't
            limit = min(int(request.query.get("limit", 60)), 1000)
        except ValueError:
            return web.Response(status=400, text="bad seconds/limit\n")
        sort = request.query.get("sort", "tottime")
        if sort not in ("tottime", "cumulative", "ncalls"):
            return web.Response(status=400, text="bad sort\n")
        if profile_lock.locked():
            return web.Response(status=409,
                                text="profile already in progress\n")
        async with profile_lock:
            pr = cProfile.Profile()
            pr.enable()
            try:
                await asyncio.sleep(seconds)
            finally:
                pr.disable()
        buf = io.StringIO()
        pstats.Stats(pr, stream=buf).sort_stats(sort).print_stats(limit)
        return web.Response(text=buf.getvalue(), content_type="text/plain")

    async def debug_trace(request: web.Request):
        """Flight-recorder dump: the process's span ring buffer as Chrome
        trace-event JSON (load in Perfetto / chrome://tracing; merge
        multi-process dumps with tools/trace_report.py). Query params:
        ?trace=<id> filters one trace, ?prefix=<job_id>/ one job,
        ?clear=1 empties the buffer after the dump."""
        from .. import obs

        rec = obs.recorder()
        spans = rec.snapshot(
            trace_prefix=request.query.get("prefix"),
            trace_id=request.query.get("trace"),
        )
        if request.query.get("fmt") == "perfetto":
            # fleet-observatory export: spans + the batch-phase timeline
            # ledger as named per-(job, phase) swimlanes (?prefix= still
            # narrows spans; phase entries filter by the prefix's job)
            prefix = request.query.get("prefix") or ""
            body = obs.perfetto_trace(
                spans, job=prefix.rstrip("/") or None
            )
        else:
            body = obs.chrome_trace(spans)
        body["spanCount"] = len(spans)
        body["dropped"] = rec.dropped
        if request.query.get("clear"):
            rec.clear()
        return web.json_response(body)

    async def debug_latency(request: web.Request):
        """Device-tier observatory dump: this process's latency-marker
        quantiles (per-operator + end-to-end) and XLA compile/dispatch
        telemetry, including the recompile-cause log. ?job=<id> narrows
        to one job's subtasks."""
        from .. import obs

        return web.json_response(
            obs.latency_report(request.query.get("job"))
        )

    async def debug_attribution(request: web.Request):
        """Fleet-observatory dump: per-job attributed wall/CPU/device
        seconds, dispatch counts and bytes, the coverage ratio vs the
        unattributed bucket, and event-loop lag percentiles — the
        numbers that let an operator audit the admission ledger's
        fair-share grants against actual consumption on a multiplexed
        worker."""
        from ..obs import attribution

        return web.json_response(attribution.ACCOUNTING.summary())

    async def debug_history(request: web.Request):
        """Metric-history tier dump for THIS process (ISSUE 13): ring
        stats plus, with ?job=<id>, the job's retained series with
        windowed rate/delta/quantiles (?window=<s>, ?series=<family>).
        The controller's /debug/watch adds SLO/alert state on top; this
        route exists on every role so a worker's local history is
        inspectable in multi-process deployments."""
        from ..obs.history import HISTORY

        doc = {"history": HISTORY.stats(),
               "families": HISTORY.families()}
        job = request.query.get("job")
        if job:
            try:
                window = float(request.query.get(
                    "window", config().watch.window))
            except ValueError:
                return web.Response(status=400, text="bad window\n")
            doc["job"] = job
            doc["window"] = window
            doc["series"] = HISTORY.export_job(
                job, window=window, series=request.query.get("series"))
        return web.json_response(doc)

    async def debug_doctor(request: web.Request):
        """Bottleneck doctor for one job hosted in this process:
        ?job=<id> (required) returns the ranked limiting-factor verdict
        (see obs/doctor.py). The REST equivalent is
        GET /api/v1/jobs/{id}/doctor."""
        from ..obs import doctor

        job = request.query.get("job")
        if not job:
            return web.Response(status=400, text="job param required\n")
        return web.json_response(doctor.report(job))

    async def debug_state(request: web.Request):
        """State-at-scale dump: per-(task, table, kind) state sizes, rows,
        spill bytes and global-table delta-chain lengths from the
        scrape-time-refreshed gauges — the live numbers the rebase/spill
        knobs (state.rebase_epochs, state.memory_budget_bytes) are tuned
        from. ?job=<id> narrows to one job's subtasks."""
        from ..metrics import REGISTRY

        job = request.query.get("job")
        snap = REGISTRY.snapshot()
        tables: dict = {}
        fields = {
            "arroyo_state_bytes": "bytes",
            "arroyo_state_rows": "rows",
            "arroyo_state_spilled_bytes": "spilled_bytes",
            "arroyo_state_delta_chain_len": "chain_len",
        }
        for family, field in fields.items():
            for labels, value in snap.get(family, []):
                if job and labels.get("job") != job:
                    continue
                key = (labels.get("task", ""), labels.get("table", ""))
                ent = tables.setdefault(key, {
                    "task": labels.get("task"),
                    "table": labels.get("table"),
                    "kind": labels.get("kind"),
                })
                if labels.get("kind") and not ent.get("kind"):
                    ent["kind"] = labels["kind"]
                ent[field] = value
        return web.json_response({
            "tables": sorted(
                tables.values(),
                key=lambda e: (e["task"] or "", e["table"] or ""),
            ),
        })

    app = web.Application()
    app.router.add_get("/status", status)
    app.router.add_get("/name", name)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/state", debug_state)
    app.router.add_get("/debug/tasks", debug_tasks)
    app.router.add_get("/debug/stacks", debug_stacks)
    app.router.add_get("/debug/profile", debug_profile)
    app.router.add_get("/debug/trace", debug_trace)
    app.router.add_get("/debug/latency", debug_latency)
    app.router.add_get("/debug/history", debug_history)
    app.router.add_get("/debug/attribution", debug_attribution)
    app.router.add_get("/debug/doctor", debug_doctor)
    for path, handler in (extra_routes or {}).items():
        app.router.add_get(path, handler)
    return app


async def serve_admin(role: str, details_fn=None,
                      port: Optional[int] = None,
                      extra_routes: Optional[dict] = None):
    """Start the admin server; returns (runner, bound port). Port 0 binds
    an ephemeral port; admin.http_port < 0 disables (returns (None, 0))."""
    cfg = config().admin
    if port is None:
        port = cfg.http_port
    if port < 0:
        return None, 0
    app = build_admin_app(role, details_fn, extra_routes=extra_routes)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, cfg.bind_address, port)
    try:
        await site.start()
    except OSError as e:
        # a fixed port is already held by another role on this host; the
        # admin surface is advisory, so log and continue without it
        logger.warning("admin server bind failed on port %s: %s", port, e)
        await runner.cleanup()
        return None, 0
    bound = site._server.sockets[0].getsockname()[1]
    logger.info("admin server for %s on %s:%s", role, cfg.bind_address, bound)
    return runner, bound
