"""Connector framework: registry + traits.

Capability parity with the reference's Connector/ErasedConnector traits and
registry (/root/reference/crates/arroyo-operator/src/connector.rs:68-175,
/root/reference/crates/arroyo-connectors/src/lib.rs:39-65): each connector
declares metadata (name, type support, config schema for the UI), validates
WITH-options from SQL, and constructs source/sink operators.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ..operators.base import Operator
from ..schema import StreamSchema


@dataclasses.dataclass
class ConnectionSchema:
    """Schema + format info resolved from a CREATE TABLE statement."""

    stream_schema: StreamSchema
    format: Optional[str] = None  # json | raw_string | raw_bytes | avro | proto
    bad_data: str = "fail"  # fail | drop
    framing: Optional[str] = None
    event_time_field: Optional[str] = None
    watermark_field: Optional[str] = None


class Connector:
    """Subclass per external system. `name` keys the SQL `connector` option."""

    name: str = ""
    description: str = ""
    source: bool = False
    sink: bool = False
    # JSON-schema-ish description of accepted options, surfaced by the API
    config_schema: Dict[str, Any] = {}

    def validate_options(
        self, options: Dict[str, str], schema: Optional[ConnectionSchema]
    ) -> Dict[str, Any]:
        """Parse/validate WITH options into an operator config dict.
        Raises ValueError on bad config."""
        return dict(options)

    def make_source(self, config: Dict[str, Any], schema: ConnectionSchema) -> Operator:
        raise NotImplementedError(f"{self.name} is not a source")

    def make_sink(self, config: Dict[str, Any], schema: ConnectionSchema) -> Operator:
        raise NotImplementedError(f"{self.name} is not a sink")

    def test(self, config: Dict[str, Any]) -> tuple[bool, str]:
        """Connection test for the API's /connection_tables/test."""
        return True, "ok"

    def table_schema(self) -> Optional["StreamSchema"]:
        """Fixed schema for connectors that define their own (impulse,
        nexmark); None when CREATE TABLE must declare columns."""
        return None

    # DDL `METADATA FROM 'key'` keys this connector's source can populate
    # (reference Connector::metadata_defs, operator/src/connector.rs:62)
    metadata_keys: tuple = ()

    def metadata(self) -> Dict[str, Any]:
        return {
            "id": self.name,
            "name": self.name,
            "description": self.description,
            "source": self.source,
            "sink": self.sink,
            "config_schema": self.config_schema,
            "metadata_keys": list(self.metadata_keys),
        }


_CONNECTORS: Dict[str, Connector] = {}


def register_connector(cls):
    inst = cls()
    assert inst.name, f"{cls} missing name"
    _CONNECTORS[inst.name] = inst
    return cls


def get_connector(name: str) -> Connector:
    if name not in _CONNECTORS:
        raise ValueError(
            f"unknown connector {name!r}; available: {sorted(_CONNECTORS)}"
        )
    return _CONNECTORS[name]


def connectors() -> List[Connector]:
    return [v for _, v in sorted(_CONNECTORS.items())]
