"""Generation-overlap rescale + source elasticity e2e (ISSUE 15).

The tentpole acceptance paths, against the real embedded cluster:

  * the autoscaler applies a DS2 SOURCE target end-to-end — the impulse
    source's parallelism actually changes (split repartition), output is
    exactly-once, and no tumbling window straddling the rescale boundary
    splits into two rows;
  * the rescale itself runs the generation-overlap protocol: the new
    incarnation stages and restores while the old one drains, the job
    moves RESCALING -> RUNNING without a SCHEDULING pass, and the
    `rescale.overlap` span records the output gap;
  * a cluster stop/restore across a straddling tumbling window emits
    ONE row per (key, window) — the carried window-split regression;
  * the controller refuses to FINISH a job whose bounded source claims
    completion without draining its assigned range (truncation guard).
"""

import asyncio
import json

import pytest

from arroyo_tpu import obs
from arroyo_tpu.config import update
from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import EmbeddedScheduler
from arroyo_tpu.controller.state_machine import JobState


def _windowed_sql(out_path, n, rate=1000, keys=4, window="1 second"):
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '{rate}',
      message_count = '{n}', start_time = '0',
      realtime = 'true', replay = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, start TIMESTAMP, cnt BIGINT) WITH (
      connector = 'single_file', path = '{out_path}',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, window.start as start, cnt FROM (
      SELECT counter % {keys} as k, tumble(interval '{window}') as window,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


def _read_rows(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _assert_no_window_split(rows, n, keys):
    """Every (k, window.start) appears EXACTLY once and totals are exact
    — a straddling window split across a boundary would show the same
    window twice with partial counts (totals still exact)."""
    seen = {}
    total = 0
    for r in rows:
        seen.setdefault((r["k"], r["start"]), []).append(r["cnt"])
        total += r["cnt"]
    dups = {kw: v for kw, v in seen.items() if len(v) > 1}
    assert not dups, f"window split into multiple rows: {dups}"
    assert total == n, f"lost/duplicated events: {total} vs {n}"


def test_autoscaler_source_target_via_overlap_rescale(tmp_path):
    """ISSUE 15 acceptance: the autoscaler's DS2 source target is applied
    end-to-end. `min_parallelism = 2` clamps every SCALABLE node — now
    including the elastic impulse source — so the first post-warmup
    decision deterministically rescales source + window 1 -> 2 through
    the generation-overlap path. Exactly-once output, no straddling-
    window split, RESCALING -> RUNNING with no SCHEDULING pass, and the
    rescale.overlap span carries the measured output gap."""
    n = 4000
    out = tmp_path / "out.json"
    sql = _windowed_sql(out, n)

    async def go():
        with update(
            pipeline={"checkpointing": {"interval": 0.25}},
            obs={"trace_buffer_spans": 32768},
            autoscale={
                "enabled": True, "period": 0.3, "warmup_periods": 1,
                "cooldown_periods": 2, "min_parallelism": 2,
                "max_parallelism": 2,
            },
        ):
            obs.reset()
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job(
                    "ovl", sql=sql, storage_url=str(tmp_path / "ck"),
                    n_workers=2, parallelism=1,
                )
                state = await c.wait_for_state(
                    "ovl", JobState.FINISHED, JobState.FAILED, timeout=90
                )
                job = c.jobs["ovl"]
                spans = [
                    s for s in obs.recorder().snapshot()
                    if s.get("name") == "rescale.overlap"
                ]
                src_par = {
                    nid: nd.parallelism
                    for nid, nd in job.graph.nodes.items()
                    if nd.is_source
                }
                return (state, job.failure, job.rescales, job.restarts,
                        [(e["from"], e["to"]) for e in job.events],
                        spans, src_par,
                        list(job.autoscale_decisions))
            finally:
                await c.stop()

    (state, failure, rescales, restarts, events, spans, src_par,
     decisions) = asyncio.run(go())
    assert state == JobState.FINISHED, failure
    assert rescales >= 1, decisions[-6:]
    # the DS2 source target was ACTUATED: source parallelism changed
    assert list(src_par.values()) == [2], src_par
    acted = [d for d in decisions if d["action"] == "rescale"]
    assert acted, decisions
    src_nid = next(iter(src_par))
    assert any(int(d["targets"].get(str(src_nid), d["targets"].get(src_nid, 0)))
               == 2 for d in acted), (src_nid, acted)
    # generation overlap: a clean rescale promotes RESCALING -> RUNNING
    # directly — never through SCHEDULING (no stop-the-world reschedule)
    if restarts == 0:
        assert ("Rescaling", "Running") in events, events
        assert ("Rescaling", "Scheduling") not in events, events
        # the output-gap span exists and carries the measurement
        assert spans, "no rescale.overlap span recorded"
        assert all(float(s["attrs"]["gap_ms"]) > 0 for s in spans)
    # exactly-once, and the straddling window emitted ONE row
    _assert_no_window_split(_read_rows(out), n, keys=4)


def test_manual_source_rescale_exactly_once(tmp_path):
    """Direct rescale_job of the SOURCE node (1 -> 2) mid-run: the
    impulse splits subdivide at the checkpoint boundary, every counter
    appears exactly once, and the window straddling the boundary stays
    one row."""
    n = 4000
    out = tmp_path / "out.json"
    sql = _windowed_sql(out, n)

    async def go():
        with update(pipeline={"checkpointing": {"interval": 0.25}}):
            obs.reset()
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job(
                    "msrc", sql=sql, storage_url=str(tmp_path / "ck"),
                    n_workers=2, parallelism=1,
                )
                await c.wait_for_state("msrc", JobState.RUNNING, timeout=30)
                await asyncio.sleep(1.3)
                job = c.jobs["msrc"]
                targets = {
                    nid: 2 for nid, nd in job.graph.nodes.items()
                    if nd.is_source
                }
                assert targets, "no source node found"
                await c.rescale_job("msrc", targets)
                state = await c.wait_for_state(
                    "msrc", JobState.FINISHED, JobState.FAILED, timeout=90
                )
                return (state, job.failure, job.rescales,
                        {nid: nd.parallelism
                         for nid, nd in job.graph.nodes.items()
                         if nd.is_source})
            finally:
                await c.stop()

    state, failure, rescales, src_par = asyncio.run(go())
    assert state == JobState.FINISHED, failure
    assert rescales == 1
    assert list(src_par.values()) == [2]
    rows = _read_rows(out)
    _assert_no_window_split(rows, n, keys=4)
    # counter-level exactly-once: counts per key are the planned share
    per_k = {}
    for r in rows:
        per_k[r["k"]] = per_k.get(r["k"], 0) + r["cnt"]
    assert per_k == {k: n // 4 for k in range(4)}, per_k


def test_stop_restore_straddling_window_single_row(tmp_path):
    """Carried robustness regression (ROADMAP watch item): a tumbling
    window straddling a cluster stop/restore must emit ONE row — the
    restore re-opens the straddling window's accumulator (replay-mode
    impulse resumes INSIDE the window, so the restored partial and the
    post-restore remainder must merge)."""
    n = 4000
    out = tmp_path / "out.json"
    sql = _windowed_sql(out, n)

    async def phase1():
        with update(pipeline={"checkpointing": {"interval": 0.25}}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job(
                    "wsr", sql=sql, storage_url=str(tmp_path / "ck"),
                    n_workers=1, parallelism=1,
                )
                await c.wait_for_state("wsr", JobState.RUNNING, timeout=30)
                # stop ~1.6s in: the 1s tumbling window [1s, 2s) straddles
                await asyncio.sleep(1.6)
                await c.stop_job("wsr", mode="checkpoint")
                state = await c.wait_for_state(
                    "wsr", JobState.STOPPED, JobState.FAILED, timeout=60
                )
                assert state == JobState.STOPPED, c.jobs["wsr"].failure
            finally:
                await c.stop()

    async def phase2():
        with update(pipeline={"checkpointing": {"interval": 0.25}}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job(
                    "wsr", sql=sql, storage_url=str(tmp_path / "ck"),
                    n_workers=1, parallelism=1,
                )
                state = await c.wait_for_state(
                    "wsr", JobState.FINISHED, JobState.FAILED, timeout=90
                )
                assert state == JobState.FINISHED, c.jobs["wsr"].failure
            finally:
                await c.stop()

    asyncio.run(phase1())
    asyncio.run(phase2())
    _assert_no_window_split(_read_rows(out), n, keys=4)


def test_controller_refuses_finish_of_undrained_source(tmp_path, monkeypatch):
    """FINISH guard (carried chaos-plan re-arm bug, second half): a
    bounded source that returns FINAL with splits undrained must not let
    the job report FINISHED over a prefix of its output — the controller
    recovers instead, and with the truncation persisting the job ends
    FAILED, never falsely FINISHED."""
    from arroyo_tpu.connectors.impulse import ImpulseSource
    from arroyo_tpu.operators.base import SourceFinishType

    real_run = ImpulseSource.run

    async def truncated_run(self, ctx, collector):
        # emit roughly half the range, then lie: claim FINAL completion
        half = (self.message_count or 0) // 2
        for sp in self.splits.values():
            sp["hi"] = min(int(sp["hi"]), half)
        finish = await real_run(self, ctx, collector)
        if finish == SourceFinishType.FINAL:
            # restore the true bound so drain_status sees the deficit
            for sp in self.splits.values():
                sp["hi"] = self.message_count
        return finish

    monkeypatch.setattr(ImpulseSource, "run", truncated_run)

    n = 800
    out = tmp_path / "out.json"
    sql = _windowed_sql(out, n, rate=100000)

    async def go():
        with update(pipeline={"checkpointing": {"interval": 0.25}}):
            c = await ControllerServer(
                EmbeddedScheduler(), max_restarts=1
            ).start()
            try:
                await c.submit_job(
                    "trunc", sql=sql, storage_url=str(tmp_path / "ck"),
                    n_workers=1, parallelism=1,
                )
                state = await c.wait_for_state(
                    "trunc", JobState.FINISHED, JobState.FAILED, timeout=60
                )
                return state, c.jobs["trunc"].failure
            finally:
                await c.stop()

    state, failure = asyncio.run(go())
    assert state == JobState.FAILED, (
        f"a truncated source run must never report FINISHED ({state})"
    )
    assert "without draining" in str(failure), failure
