"""Serialization: Arrow RecordBatches -> encoded records for sinks.

Capability parity with the reference's ArrowSerializer
(/root/reference/crates/arroyo-formats/src/ser.rs:54): JSON (one object per
row), Debezium-JSON envelopes for updating streams, raw string, Avro and
Protobuf encodings (pure python).
"""

from __future__ import annotations

import json
from typing import Iterator, List, Optional

import pyarrow as pa

from ..schema import TIMESTAMP_FIELD, UPDATING_META_FIELD


class Serializer:
    def __init__(self, format: str = "json", include_timestamp: bool = False,
                 avro_schema: Optional[str] = None,
                 proto_descriptor: Optional[dict] = None,
                 schema_registry=None):
        self.format = format or "json"
        self.include_timestamp = include_timestamp
        self.avro_schema = avro_schema
        # with a registry the sink registers its schema once and frames
        # every record with magic 0 + the 4-byte schema id (Confluent
        # wire format; reference ser.rs + schema_resolver.rs write_schema)
        self.schema_registry = schema_registry
        self._registered_id: Optional[int] = None
        self.proto = None
        if self.format in ("protobuf", "proto"):
            from .proto import ProtoEncoder

            self.proto = ProtoEncoder(proto_descriptor)

    def serialize(self, batch: pa.RecordBatch) -> Iterator[bytes]:
        if self.format in ("json", "debezium_json"):
            yield from self._json(batch)
        elif self.format == "raw_string":
            col = batch.column(0)
            for v in col.to_pylist():
                yield (v if isinstance(v, str) else str(v)).encode()
        elif self.format == "avro":
            import struct

            from .avro import AvroEncoder

            enc = getattr(self, "_avro_encoder", None)
            if enc is None:
                enc = self._avro_encoder = AvroEncoder(
                    self.avro_schema, batch.schema
                )
            framing = b""
            if self.schema_registry is not None:
                if self._registered_id is None:
                    self._registered_id = self.schema_registry.write_schema(
                        enc.schema
                    )
                framing = b"\x00" + struct.pack(">I", self._registered_id)
            for row in self._rows(batch):
                yield framing + enc.encode(row)
        elif self.format in ("protobuf", "proto"):
            for row in self._rows(batch):
                yield self.proto.encode(row)
        else:
            raise ValueError(f"unknown sink format {self.format!r}")

    def _rows(self, batch: pa.RecordBatch) -> List[dict]:
        drop = {TIMESTAMP_FIELD} if not self.include_timestamp else set()
        drop.add(UPDATING_META_FIELD)
        names = [n for n in batch.schema.names if n not in drop]
        return batch.select(names).to_pylist()

    def _json(self, batch: pa.RecordBatch) -> Iterator[bytes]:
        is_updating = UPDATING_META_FIELD in batch.schema.names
        metas = (
            batch.column(batch.schema.names.index(UPDATING_META_FIELD))
            .to_pylist()
            if is_updating
            else None
        )
        for i, row in enumerate(self._rows(batch)):
            obj = {k: _json_value(v) for k, v in row.items()}
            if self.format == "debezium_json":
                if metas is not None and metas[i]["is_retract"]:
                    env = {"before": obj, "after": None, "op": "d"}
                else:
                    env = {"before": None, "after": obj, "op": "c"}
                yield json.dumps(env, default=str).encode()
            else:
                yield json.dumps(obj, default=str).encode()


def _json_value(v):
    import datetime

    if isinstance(v, datetime.datetime):
        return v.isoformat()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, dict):
        return {k: _json_value(x) for k, x in v.items()}
    return v


def make_serializer(conn_schema) -> Serializer:
    return Serializer(format=getattr(conn_schema, "format", None) or "json")
