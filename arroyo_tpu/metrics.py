"""Metrics registry with Prometheus text exposition.

Capability parity with the reference's `arroyo-metrics` crate +
TaskCounters (/root/reference/crates/arroyo-operator/src/context.rs):
per-task messages/batches/bytes rx-tx counters, per-queue occupancy gauges,
and UI-facing 5-minute rate windows (computed in engine.job_metrics).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, Tuple

LabelSet = Tuple[Tuple[str, str], ...]


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind
        self.values: Dict[LabelSet, float] = defaultdict(float)
        # scrape-time refreshers: key -> zero-arg callable returning the
        # current value (or None to keep the stored sample). Gauges whose
        # producer only updates on its own hot path (e.g. backpressure,
        # sampled every N collect() calls) register one so a quiesced
        # stream can't pin a stale value into every future scrape.
        self.refreshers: Dict[LabelSet, object] = {}
        self.lock = threading.Lock()

    def labels(self, **labels: str) -> "_Handle":
        key = tuple(sorted(labels.items()))
        return _Handle(self, key)

    def _refresh(self):
        """Run registered refreshers (lock held), dropping dead ones."""
        if not self.refreshers:
            return
        dead = []
        for key, fn in self.refreshers.items():
            try:
                v = fn()
            except Exception:  # noqa: BLE001 - producer gone mid-scrape
                v = None
            if v is None:
                dead.append(key)
            else:
                self.values[key] = v
        for key in dead:
            del self.refreshers[key]

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self.lock:
            self._refresh()
            for key, val in self.values.items():
                if key:
                    label_s = ",".join(
                        f'{k}="{_escape_label(v)}"' for k, v in key)
                    lines.append(f"{self.name}{{{label_s}}} {val}")
                else:
                    lines.append(f"{self.name} {val}")
        return "\n".join(lines)


class _Handle:
    __slots__ = ("metric", "key")

    def __init__(self, metric: _Metric, key: LabelSet):
        self.metric = metric
        self.key = key

    def inc(self, amount: float = 1.0):
        with self.metric.lock:
            self.metric.values[self.key] += amount

    def set(self, value: float):
        with self.metric.lock:
            self.metric.values[self.key] = value

    def set_refresher(self, fn):
        """Register a scrape-time refresher: `fn()` is called under the
        metric lock at expose/snapshot and must return the current value,
        or None to unregister itself (producer gone)."""
        with self.metric.lock:
            self.metric.refreshers[self.key] = fn

    def get(self) -> float:
        with self.metric.lock:
            return self.metric.values[self.key]


class Registry:
    def __init__(self):
        self.metrics: Dict[str, _Metric] = {}
        self.lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> _Metric:
        return self._get(name, help_, "counter")

    def gauge(self, name: str, help_: str = "") -> _Metric:
        return self._get(name, help_, "gauge")

    def _get(self, name: str, help_: str, kind: str) -> _Metric:
        with self.lock:
            if name not in self.metrics:
                self.metrics[name] = _Metric(name, help_, kind)
            return self.metrics[name]

    def expose(self) -> str:
        with self.lock:
            metrics = list(self.metrics.values())
        return "\n".join(m.expose() for m in metrics) + "\n"

    def snapshot(self) -> Dict[str, list]:
        """{metric name: [(labels dict, value)]} for structured consumers
        (the API's operator metric groups)."""
        with self.lock:
            metrics = list(self.metrics.items())
        out: Dict[str, list] = {}
        for name, m in metrics:
            with m.lock:
                m._refresh()
                out[name] = [(dict(k), v) for k, v in m.values.items()]
        return out

    def reset(self):
        with self.lock:
            self.metrics.clear()


REGISTRY = Registry()

# Task-level counters, one label-set per subtask (reference TaskCounters).
MESSAGES_RECV = REGISTRY.counter(
    "arroyo_worker_messages_recv", "messages received by a subtask")
MESSAGES_SENT = REGISTRY.counter(
    "arroyo_worker_messages_sent", "messages sent by a subtask")
BATCHES_RECV = REGISTRY.counter(
    "arroyo_worker_batches_recv", "batches received by a subtask")
BATCHES_SENT = REGISTRY.counter(
    "arroyo_worker_batches_sent", "batches sent by a subtask")
BYTES_RECV = REGISTRY.counter(
    "arroyo_worker_bytes_recv", "bytes received by a subtask")
BYTES_SENT = REGISTRY.counter(
    "arroyo_worker_bytes_sent", "bytes sent by a subtask")
ERRORS = REGISTRY.counter(
    "arroyo_worker_errors", "deserialization/user errors in a subtask")
BACKPRESSURE = REGISTRY.gauge(
    "arroyo_worker_backpressure",
    "fullness (0..1) of a subtask's most-loaded output queue — the "
    "reference derives its backpressure gauge from tx queue occupancy "
    "the same way (job_metrics.rs)")
QUEUE_SIZE = REGISTRY.gauge(
    "arroyo_worker_queue_size", "occupancy of an edge queue (batches)")
QUEUE_BYTES = REGISTRY.gauge(
    "arroyo_worker_queue_bytes", "occupancy of an edge queue (bytes)")
TPU_KERNEL_MILLIS = REGISTRY.counter(
    "arroyo_tpu_kernel_millis", "wall millis spent inside device kernels")


class RateWindow:
    """Fixed 5-minute circular buffer of (t, value) samples for UI rates
    (reference: job_metrics.rs:188-265)."""

    WINDOW = 300.0

    def __init__(self):
        self.samples: list[tuple[float, float]] = []

    def add(self, value: float, now: float | None = None):
        now = time.monotonic() if now is None else now
        self.samples.append((now, value))
        cutoff = now - self.WINDOW
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.pop(0)

    def rate(self) -> float:
        if len(self.samples) < 2:
            return 0.0
        (t0, v0), (t1, v1) = self.samples[0], self.samples[-1]
        return (v1 - v0) / (t1 - t0) if t1 > t0 else 0.0
