"""Host-side slot directory: maps (bin, key) groups to accumulator slots.

This is the "hash table on TPU" compromise documented in SURVEY.md §7:
slot assignment is a host dict over the *unique* (bin, key) pairs of each
batch (vectorized uniquing via numpy), while the O(rows) arithmetic runs on
device. A pallas open-addressing kernel can replace this later without
changing the operator contract.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class SlotDirectory:
    def __init__(self, scratch_slot_reserved: bool = True):
        self.by_bin: Dict[int, Dict[tuple, int]] = {}
        self.free: List[int] = []
        self.next_slot = 0
        self.n_live = 0
        # slot -> (bin, key) reverse map, maintained by assign/take/remove
        self.key_of: Dict[int, tuple] = {}

    def required_capacity(self) -> int:
        # +1 for the scratch slot used by shape padding
        return self.next_slot + 1

    def assign(
        self, bins: np.ndarray, key_cols: List[np.ndarray]
    ) -> np.ndarray:
        """Vectorized slot assignment for a batch. Returns slots[i] per row;
        allocates new slots for unseen (bin, key) pairs."""
        n = len(bins)
        if n == 0:
            return np.empty(0, dtype=np.int64)
        uniq, inverse = _unique_pairs(bins, key_cols)
        slot_of_unique = np.empty(len(uniq), dtype=np.int64)
        for u, row in enumerate(uniq):
            b = int(row[0])
            key = tuple(row[1:])
            bin_map = self.by_bin.setdefault(b, {})
            slot = bin_map.get(key)
            if slot is None:
                slot = self.free.pop() if self.free else self._alloc()
                bin_map[key] = slot
                self.key_of[slot] = (b, key)
                self.n_live += 1
            slot_of_unique[u] = slot
        return slot_of_unique[inverse]

    def _alloc(self) -> int:
        s = self.next_slot
        self.next_slot += 1
        return s

    # imperative allocation (session windows bypass assign()); the shard
    # hint only matters to the mesh facade, which load-balances with it
    def alloc_slot(self, shard_hint: int = 0) -> int:
        return self.free.pop() if self.free else self._alloc()

    def alloc_block(self, k: int) -> List[int]:
        """Bulk-allocate k slots in one call (session slot pool): drains
        the free list first, then extends the high-water mark once."""
        nf = min(k, len(self.free))
        out = self.free[len(self.free) - nf:]
        del self.free[len(self.free) - nf:]
        rem = k - nf
        if rem:
            start = self.next_slot
            self.next_slot += rem
            out.extend(range(start, start + rem))
        return out

    def alloc_slots(self, n: int, shard_hint: int = 0) -> np.ndarray:
        """Vectorized imperative allocation (mesh facade load-balances
        across shards; here it is just a block)."""
        return np.asarray(self.alloc_block(n), dtype=np.int64)

    def free_slot(self, slot: int):
        self.free.append(int(slot))

    def free_slots(self, slots):
        """Batch free (session expiry waves / slot-pool returns): one
        C-level extend instead of a python call per slot."""
        self.free.extend(np.asarray(slots, dtype=np.int64).tolist())

    def bins_up_to(self, bin_exclusive: int) -> List[int]:
        return sorted(b for b in self.by_bin if b < bin_exclusive)

    def live_bins(self) -> List[int]:
        return sorted(self.by_bin)

    def peek_bin(self, b: int) -> Optional[Dict[tuple, int]]:
        return self.by_bin.get(b)

    def slots_for_keys(self, b: int, keys) -> Dict[tuple, int]:
        """{key: slot} for the subset of `keys` live in bin b (point
        lookups, O(len(keys)))."""
        bin_map = self.by_bin.get(b)
        if not bin_map:
            return {}
        return {k: bin_map[k] for k in keys if k in bin_map}

    def bin_entries(self, b: int):
        """(keys, slots) of a live bin without removal; keys as a list of
        tuples (the native directory returns int64 arrays instead)."""
        bin_map = self.by_bin.get(b, {})
        return list(bin_map.keys()), np.fromiter(
            bin_map.values(), dtype=np.int64, count=len(bin_map)
        )

    def take_bin(self, b: int) -> Tuple[List[tuple], np.ndarray]:
        """Remove a bin for emission: returns (keys, slots) and frees the
        slots (caller must reset accumulator slots before reuse)."""
        bin_map = self.by_bin.pop(b, {})
        keys = list(bin_map.keys())
        slots = np.fromiter(bin_map.values(), dtype=np.int64, count=len(bin_map))
        for s in slots:
            self.free.append(int(s))
            self.key_of.pop(int(s), None)
        self.n_live -= len(bin_map)
        return keys, slots

    def remove(self, b: int, keys: List[tuple]) -> np.ndarray:
        """Remove specific keys from a bin (TTL eviction); returns the freed
        slots (caller must reset accumulator slots before reuse)."""
        bin_map = self.by_bin.get(b)
        if not bin_map:
            return np.empty(0, dtype=np.int64)
        freed = []
        for k in keys:
            slot = bin_map.pop(k, None)
            if slot is not None:
                freed.append(slot)
                self.free.append(slot)
                self.key_of.pop(slot, None)
                self.n_live -= 1
        if not bin_map:
            self.by_bin.pop(b, None)
        return np.asarray(freed, dtype=np.int64)

    def keys_for_slots(self, slots: np.ndarray) -> List[Optional[tuple]]:
        """Resolve slots back to their live (bin, key) in O(len(slots)) via
        the incrementally-maintained reverse map."""
        return [self.key_of.get(int(s)) for s in slots]

    def items(self):
        for b, bin_map in self.by_bin.items():
            for key, slot in bin_map.items():
                yield b, key, slot


def _unique_pairs(
    bins: np.ndarray, key_cols: List[np.ndarray]
) -> Tuple[np.ndarray, np.ndarray]:
    """Unique (bin, *keys) rows + inverse mapping. Fast path stacks numeric
    columns into one int64/struct matrix; object columns fall back to pandas
    factorize per column."""
    cols = [np.asarray(bins)]
    for c in key_cols:
        c = np.asarray(c)
        if c.dtype.kind == "M":
            c = c.view("i8")
        if c.dtype == np.uint64:
            # bit-preserving: values >= 2^63 become negative codes; window
            # emission normalizes back mod 2^64
            c = c.view(np.int64)
        if c.dtype.kind not in "iub":
            c = _factorize_to_codes(c, cols)
            cols.append(c)
        else:
            cols.append(c.astype(np.int64, copy=False))
    mat = np.stack([c.astype(np.int64, copy=False) for c in cols], axis=1)
    uniq, inverse = np.unique(mat, axis=0, return_inverse=True)
    return uniq, inverse.ravel()


# object-key interning: codes are only used within one assign() call for
# uniquing; the directory's tuples store the *codes*... that would break
# cross-batch identity, so we intern values globally instead.
_INTERN: Dict[object, int] = {}
_INTERN_REV: List[object] = []


def intern_value(v) -> int:
    if isinstance(v, list):  # msgpack round-trips tuples as lists
        v = tuple(tuple(x) if isinstance(x, list) else x for x in v)
    code = _INTERN.get(v)
    if code is None:
        code = len(_INTERN_REV)
        _INTERN[v] = code
        _INTERN_REV.append(v)
    return code


def unintern_value(code: int):
    return _INTERN_REV[code]


def _factorize_to_codes(col: np.ndarray, _cols) -> np.ndarray:
    return np.fromiter(
        (intern_value(v) for v in col), dtype=np.int64, count=len(col)
    )
