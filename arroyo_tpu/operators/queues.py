"""Bounded dataflow queues, counted in both batches and bytes.

Capability parity with the reference's batch_bounded channel
(/root/reference/crates/arroyo-operator/src/context.rs:91-196): capacity
counts items AND bytes so one huge batch can't blow memory while many tiny
batches can't add unbounded latency. Signals (watermarks/barriers/stop) are
always accepted — they are tiny and must never deadlock the control flow —
but data sends block (backpressure) when either bound is hit.
"""

from __future__ import annotations

import asyncio
import dataclasses
import weakref
from collections import deque
from typing import Optional

import pyarrow as pa

from ..metrics import QUEUE_BYTES, QUEUE_SIZE
from ..types import SignalMessage


def batch_bytes(batch: pa.RecordBatch) -> int:
    return batch.get_total_buffer_size()


class QueueClosed(Exception):
    pass


class BatchQueue:
    """One edge queue between a (src_subtask, dst_subtask) pair."""

    def __init__(self, max_batches: int, max_bytes: int, name: str = "",
                 job: str = ""):
        self.max_batches = max(1, max_batches)
        self.max_bytes = max(1, max_bytes)
        self.name = name
        self._items: deque = deque()
        self._bytes = 0
        self._closed = False
        self._readable = asyncio.Event()
        self._writable = asyncio.Event()
        self._writable.set()
        # the job label lets the cardinality GC (Registry.drop_job) drop a
        # stopped job's queue series in one pass — multiplexed workers
        # otherwise accumulate every churned job's gauges forever
        labels = {"queue": name, **({"job": job} if job else {})}
        self._size_gauge = QUEUE_SIZE.labels(**labels) if name else None
        self._bytes_gauge = QUEUE_BYTES.labels(**labels) if name else None
        if name:
            # the push/pop updates only run on the producer/consumer hot
            # paths, so a scrape between events (or after the last event —
            # a quiesced or torn-down edge) would report whatever occupancy
            # happened to be stored last. Same staleness class as the
            # backpressure gauge (PR 1): refresh at scrape time through a
            # weak reference, unregistering once the queue is collected so
            # autoscaler samples never read a dead edge as live depth.
            ref = weakref.ref(self)

            def _size_now():
                q = ref()
                return None if q is None else float(len(q._items))

            def _bytes_now():
                q = ref()
                return None if q is None else float(q._bytes)

            self._size_gauge.set_refresher(_size_now)
            self._bytes_gauge.set_refresher(_bytes_now)

    def qsize(self) -> int:
        return len(self._items)

    def fullness(self) -> float:
        """0..1 occupancy against whichever bound (count or bytes) is
        closer to blocking the sender — the backpressure signal. Clamped:
        signals bypass capacity checks and one oversized batch may exceed
        the byte bound, so raw occupancy can pass the limit."""
        return min(1.0, max(len(self._items) / self.max_batches,
                            self._bytes / self.max_bytes))

    def _has_capacity(self) -> bool:
        return len(self._items) < self.max_batches and self._bytes < self.max_bytes

    def _update_gauges(self):
        if self._size_gauge is not None:
            self._size_gauge.set(len(self._items))
            self._bytes_gauge.set(self._bytes)

    async def send(self, item, nbytes: Optional[int] = None):
        """Send a data batch; blocks when the queue is at capacity."""
        if self._closed:
            raise QueueClosed(self.name)
        if isinstance(item, SignalMessage):
            self._push(item, 0)
            return
        if nbytes is None:
            nbytes = batch_bytes(item)
        while not self._has_capacity():
            self._writable.clear()
            await self._writable.wait()
            if self._closed:
                raise QueueClosed(self.name)
        self._push(item, nbytes)

    def _push(self, item, nbytes: int):
        self._items.append((item, nbytes))
        self._bytes += nbytes
        self._readable.set()
        self._update_gauges()

    async def recv(self):
        while not self._items:
            if self._closed:
                raise QueueClosed(self.name)
            self._readable.clear()
            await self._readable.wait()
        item, nbytes = self._items.popleft()
        self._bytes -= nbytes
        if self._has_capacity():
            self._writable.set()
        self._update_gauges()
        return item

    def close(self):
        self._closed = True
        self._readable.set()
        self._writable.set()


@dataclasses.dataclass
class InputQueue:
    """A subtask input: the queue plus its logical input index (which in-edge
    it belongs to — joins distinguish left=0/right=1) and alignment state."""

    queue: BatchQueue
    logical_input: int = 0
    src_task: str = ""
    blocked: bool = False  # barrier arrived, holding until alignment
    finished: bool = False  # EndOfData seen
