"""Fused segment runtime: whole-chain compilation + device pipelining.

ROADMAP item 1's dispatch-floor attack (GSPMD's lesson — hand the
compiler BIGGER programs; Weld/HyPer's lesson — one compiled kernel per
stateless chain, not one dispatch per operator):

* **Plan-time segment fusion** (`SegmentFusionPass`, applied right after
  the ChainingOptimizer): maximal contiguous runs of >= 2 stateless
  value operators inside a chained node (filter -> project ->
  expression-eval, the ARROW_VALUE/PROJECTION/ARROW_KEY ops the planner
  emits) are replaced by ONE `FUSED_SEGMENT` chained op carrying the
  member configs. The runner then makes one dispatch per segment per
  batch instead of one per operator. With `engine.segment_fusion` off
  the pass instead annotates the members (`segment_member` /
  `segment_lead`) so the unfused A/B run counts the dispatches it pays
  into the same `arroyo_segment_*` families.

* **One composed program, three execution tiers**
  (`FusedSegmentOperator` + `build_program`): the whole chain's output
  expressions compose into ONE function over the segment's input
  leaves (numeric columns + host-evaluated struct/string reads, via
  the `BoundExpr.jax` mirrors in sql/expressions.py). On plain hosts
  it runs as the numpy *vector* tier — leaves viewed ZERO-COPY out of
  the arrow buffers (no per-stage wide-struct filter), the combined
  row mask applied once to the narrow outputs, output nulls
  reconstructed from leaf validity for strictly null-propagating
  subtrees — engaged only when bit-exact vs the arrow kernels
  (`JaxExpr.exact`). The lazy-*view* tier (composition through
  `_ProjectedView`/`_LazyFilteredBatch`, kernel-for-kernel identical
  to the unfused plan) runs opaque `py_fn` members and any batch the
  composer rejects. Under `ops._jax.device_tier_active` the SAME
  composed function is jitted into one XLA program per shape
  signature: leaves padded on a shared pow-2 `_StickyRung` ladder (a
  rung change recompiles the segment once, not N times), dispatched
  through `InstrumentedJit` (compile/dispatch telemetry +
  `arroyo_segment_dispatch_seconds`), with buffer donation on the
  steady-state program where the jax generation allows it
  (`engine.segment_donation`, gated like mesh donation). Chaos drills
  pin fused-vs-unfused byte identity across all tiers.

* **Async double-buffered pipelining**: jax-tier dispatches stage
  UN-materialized in a bounded FIFO (up to `engine.pipeline_depth - 1`
  deep), so the host Arrow decode/pack of batch k+1 overlaps the
  in-flight device dispatch of batch k; host-tier results emit eagerly
  (there is nothing in flight to overlap, and forced staging measured
  ~2% pure overhead on the 1-core bench host). Emission is strictly
  ordered; watermarks arriving while batches are staged are queued IN
  the FIFO (held, then re-injected after the batches they followed —
  the async_udf held-watermark pattern); checkpoint barriers drain the
  pipeline before capture (`SubtaskRunner._drain_pipeline`, span
  `runner.pipeline_drain`), so outputs and checkpoint state are
  byte-identical at any depth.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..config import config
from ..graph.logical import ChainedOp, LogicalGraph, OperatorName
from ..metrics import (
    SEGMENT_BATCHES,
    SEGMENT_DISPATCH_SECONDS,
    SEGMENT_DISPATCHES,
    SEGMENT_FUSED_OPS,
)
from ..utils.logging import get_logger
from .construct import register_operator
from ..operators.base import Operator

logger = get_logger("segments")

# operator kinds whose registered implementations are stateless value
# transforms (lint JAX004 `segment-purity` keeps the registered classes
# honest: no state, no checkpoint hooks — so fusing them can never skip
# a barrier's state capture)
FUSABLE_OPS = (
    OperatorName.ARROW_VALUE,
    OperatorName.PROJECTION,
    OperatorName.ARROW_KEY,
)


def fusable(op: ChainedOp) -> bool:
    return op.operator in FUSABLE_OPS


def plan_runs(chain: List[ChainedOp]) -> List[Tuple[int, int]]:
    """Maximal contiguous [start, end) runs of >= 2 fusable ops."""
    runs: List[Tuple[int, int]] = []
    i = 0
    while i < len(chain):
        if not fusable(chain[i]):
            i += 1
            continue
        j = i
        while j < len(chain) and fusable(chain[j]):
            j += 1
        if j - i >= 2:
            runs.append((i, j))
        i = j
    return runs


class SegmentFusionPass:
    """Rewrite each node's chain: fuse runs (segment_fusion on) or
    annotate them for A/B dispatch accounting (segment_fusion off)."""

    def __init__(self, fuse: Optional[bool] = None):
        self.fuse = (
            bool(config().engine.segment_fusion) if fuse is None else fuse
        )

    def optimize(self, graph: LogicalGraph) -> LogicalGraph:
        for node in graph.nodes.values():
            runs = plan_runs(node.chain)
            if not runs:
                continue
            if not self.fuse:
                for start, end in runs:
                    for k in range(start, end):
                        node.chain[k].config["segment_member"] = True
                    node.chain[start].config["segment_lead"] = True
                continue
            # rewrite back-to-front so earlier run indices stay valid
            for start, end in reversed(runs):
                members = node.chain[start:end]
                descs = [m.description or m.operator.value for m in members]
                seg = ChainedOp(
                    OperatorName.FUSED_SEGMENT,
                    {
                        "ops": [
                            {
                                "operator": m.operator.value,
                                "config": m.config,
                                "description": m.description,
                            }
                            for m in members
                        ],
                        # segment output schema = last member's
                        "schema": members[-1].config.get("schema"),
                    },
                    "segment[" + " -> ".join(descs) + "]",
                )
                node.chain[start:end] = [seg]
        return graph


# ---------------------------------------------------------------------------
# Host-tier composition: lazy views over the member projections
# ---------------------------------------------------------------------------


class _ProjectedView:
    """Duck-typed RecordBatch whose columns are a projection's output
    expressions over a base relation, computed (and cast to the output
    field type, mirroring CompiledProjection.__call__) on first access."""

    __slots__ = ("_exprs", "_base", "_cols", "num_rows", "schema")

    def __init__(self, proj, base):
        self._exprs = proj.exprs
        self._base = base
        self._cols: Dict[int, Any] = {}
        self.num_rows = base.num_rows
        self.schema = proj.out_schema

    def column(self, i: int):
        c = self._cols.get(i)
        if c is None:
            from ..sql.expressions import _cast

            c = self._exprs[i].eval(self._base)
            f = self.schema.field(i)
            if not c.type.equals(f.type):
                c = _cast(c, f.type)
            self._cols[i] = c
        return c

    def __getattr__(self, name):
        raise AttributeError(
            f"_ProjectedView (the fused-segment lazy projection view) "
            f"exposes only column()/num_rows/schema, not {name!r}; "
            f"materialize the stage in FusedSegmentOperator instead"
        )


def _materialize(cur) -> pa.RecordBatch:
    if isinstance(cur, pa.RecordBatch):
        return cur
    return pa.RecordBatch.from_arrays(
        [cur.column(i) for i in range(len(cur.schema))], schema=cur.schema
    )


@dataclasses.dataclass
class _Stage:
    kind: str  # "proj" | "opaque" | "identity"
    proj: Any = None            # CompiledProjection
    fn: Optional[Callable] = None  # opaque py_fn
    name: str = ""


def _build_stage(member: dict) -> _Stage:
    from ..sql.expressions import CompiledProjection

    cfg = member.get("config", {})
    name = member.get("description") or member.get("operator", "")
    py_fn = cfg.get("py_fn")
    if isinstance(py_fn, CompiledProjection):
        return _Stage("proj", proj=py_fn, name=name)
    if py_fn is None and "program" in cfg:
        return _Stage("proj", proj=CompiledProjection.from_config(
            cfg["program"]), name=name)
    if py_fn is not None:
        return _Stage("opaque", fn=py_fn, name=name)
    # identity key op (routing handled by edge schema key indices)
    return _Stage("identity", name=name)


# ---------------------------------------------------------------------------
# JAX tier: the whole chain as ONE jitted program
# ---------------------------------------------------------------------------


class _StageEnv:
    """Env for stage k > 0 expressions: col(j) resolves the PREVIOUS
    stage's output expression j (memoized per program invocation, so a
    shared subexpression traces once)."""

    __slots__ = ("_col_fns", "_parent", "_memo")

    def __init__(self, col_fns, parent):
        self._col_fns = col_fns
        self._parent = parent
        self._memo: Dict[int, Any] = {}

    def col(self, j):
        v = self._memo.get(j)
        if v is None:
            v = self._memo[j] = self._col_fns[j](self._parent)
        return v

    def host(self, key):
        return self._parent.host(key)


class _BaseEnv:
    __slots__ = ("_cols", "_hosts")

    def __init__(self, cols: Dict[int, Any], hosts: Dict[int, Any]):
        self._cols = cols
        self._hosts = hosts

    def col(self, j):
        return self._cols[j]

    def host(self, key):
        return self._hosts[key]


@dataclasses.dataclass
class _SegmentProgram:
    """The composed whole-segment program + its input plan. `raw_fn` is
    tier-polymorphic: handed numpy leaf arrays it IS the host vector
    tier (filter-late: leaves read unfiltered/zero-copy, one mask
    application on the narrow outputs); handed jax arrays under jit it
    is the device tier's traced body."""

    raw_fn: Callable              # prog(*leaf_arrays) -> (mask|None, outs)
    spec: List[tuple]             # ordered leaves: ("col", j) | ("host", key, BoundExpr)
    out_fields: List[pa.Field]    # output schema fields
    out_schema: pa.Schema
    out_deps: List[frozenset]     # per output: leaf keys it depends on
    mask_deps: Optional[frozenset]  # leaf keys the row mask depends on
    strict: List[bool]            # per output: strict null propagation
    mask_strict: bool
    exact: bool                   # bit-exact vs host kernels (vector tier gate)
    # device-tier state, built lazily on first jax dispatch
    jit: Any = None               # InstrumentedJit over jax.jit(raw_fn)
    rung: Any = None              # shared _StickyRung
    n_rows_cap: int = 1 << 30


_FIXED_NP = {
    pa.lib.Type_INT8: "int8", pa.lib.Type_INT16: "int16",
    pa.lib.Type_INT32: "int32", pa.lib.Type_INT64: "int64",
    pa.lib.Type_UINT8: "uint8", pa.lib.Type_UINT16: "uint16",
    pa.lib.Type_UINT32: "uint32", pa.lib.Type_UINT64: "uint64",
    pa.lib.Type_FLOAT: "float32", pa.lib.Type_DOUBLE: "float64",
    pa.lib.Type_TIMESTAMP: "int64", pa.lib.Type_DURATION: "int64",
}


def _leaf_np(arr: pa.Array) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Arrow column -> (dense numpy values, validity-or-None), ZERO-copy
    for fixed-width types: the values buffer is viewed directly (null
    slots carry whatever bytes arrow left there — the validity mask is
    what gives them meaning downstream, exactly like arrow kernels
    treat them). Bit-packed bools fall back to an unpacking copy."""
    valid = None
    if arr.null_count:
        valid = arr.is_valid().to_numpy(zero_copy_only=False)
    np_dtype = _FIXED_NP.get(arr.type.id)
    if np_dtype is not None:
        buf = arr.buffers()[1]
        np_arr = np.frombuffer(buf, dtype=np_dtype,
                               count=arr.offset + len(arr))[arr.offset:]
        return np_arr, valid
    if arr.null_count:
        arr = pc.fill_null(arr, False if pa.types.is_boolean(arr.type)
                           else 0)
    np_arr = arr.to_numpy(zero_copy_only=False)
    if np_arr.dtype.kind in ("M", "m"):  # datetime64/timedelta64 -> int64
        np_arr = np_arr.view("int64")
    return np.ascontiguousarray(np_arr), valid


def _pad(arr: np.ndarray, rung: int) -> np.ndarray:
    if len(arr) == rung:
        return arr
    out = np.zeros(rung, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def build_program(stages: List[_Stage], program_name: str):
    """Compose every stage's output expressions into ONE function over
    the segment's input leaves; None when any stage blocks composition
    (opaque py_fn, non-lowerable output, host leaf past stage 0, or a
    chain with no actual compute)."""
    from ..sql.expressions import jax_lowerable_type

    projs = [s for s in stages if s.kind != "identity"]
    if any(s.kind != "proj" for s in projs):
        return None
    col_leaves: set = set()
    host_leaves: Dict[int, Any] = {}  # id(BoundExpr) -> BoundExpr
    masks: List[Tuple[Callable, frozenset, bool, bool]] = []
    prev_cols: Optional[List[Callable]] = None
    prev_deps: Optional[List[frozenset]] = None
    prev_strict: Optional[List[bool]] = None
    any_compute = False

    def compose(e, k):
        """-> (fn(env0), leaf-dep keys, strict, exact, is_leaf) or None."""
        nonlocal any_compute
        jx = getattr(e, "jax", None)
        if jx is None:
            return None
        if k == 0:
            for h in jx.hosts:
                host_leaves.setdefault(id(h), h)
            col_leaves.update(jx.cols)
            deps = frozenset(
                [("col", j) for j in jx.cols]
                + [("host", id(h)) for h in jx.hosts]
            )
            if not jx.leaf:
                any_compute = True
            return jx.fn, deps, jx.strict, jx.exact, jx.leaf
        if jx.hosts:
            return None  # host leaf past stage 0: needs materialization
        deps = frozenset()
        strict = jx.strict
        for j in jx.cols:
            deps |= prev_deps[j]
            strict = strict and prev_strict[j]
        if not jx.leaf:
            any_compute = True
        pcols = prev_cols
        return (
            lambda env, f=jx.fn, _pc=pcols: f(_StageEnv(_pc, env)),
            deps, strict, jx.exact, jx.leaf,
        )

    k = 0
    last_proj = None
    exact = True
    for st in projs:
        proj = st.proj
        if proj.predicate is not None:
            m = compose(proj.predicate, k)
            if m is None:
                return None
            masks.append((m[0], m[1], m[2], m[3]))
            exact = exact and m[3]
        new_cols, new_deps, new_strict = [], [], []
        for e, f in zip(proj.exprs, proj.out_schema):
            if not jax_lowerable_type(f.type):
                return None
            c = compose(e, k)
            if c is None:
                return None
            fn, deps, strict, e_exact, _leaf = c
            exact = exact and e_exact
            # mirror the host cast-to-out-field-type step
            if not e.dtype.equals(f.type):
                from ..sql.expressions import JaxExpr, _jx_cast

                fn = _jx_cast(JaxExpr(fn), f.type).fn
            new_cols.append(fn)
            new_deps.append(deps)
            new_strict.append(strict)
        prev_cols, prev_deps, prev_strict = new_cols, new_deps, new_strict
        last_proj = proj
        k += 1
    if last_proj is None or not any_compute:
        return None

    spec: List[tuple] = [("col", j) for j in sorted(col_leaves)] + [
        ("host", key, be) for key, be in host_leaves.items()
    ]
    leaf_keys = [s[:2] for s in spec]
    outputs = prev_cols

    def prog(*arrays):
        env = _BaseEnv(
            {key[1]: a for key, a in zip(leaf_keys, arrays)
             if key[0] == "col"},
            {key[1]: a for key, a in zip(leaf_keys, arrays)
             if key[0] == "host"},
        )
        mask = None
        for mfn, _deps, _strict, _exact in masks:
            m = mfn(env)
            mask = m if mask is None else mask & m
        outs = tuple(fn(env) for fn in outputs)
        return mask, outs

    mask_deps = None
    mask_strict = True
    if masks:
        mask_deps = frozenset().union(*(m[1] for m in masks))
        mask_strict = all(m[2] for m in masks)
    return _SegmentProgram(
        raw_fn=prog,
        spec=spec,
        out_fields=list(last_proj.out_schema),
        out_schema=pa.schema(list(last_proj.out_schema)),
        out_deps=prev_deps,
        mask_deps=mask_deps,
        strict=prev_strict,
        mask_strict=mask_strict,
        exact=exact,
    )


def attach_device_program(prog: _SegmentProgram, program_name: str) -> None:
    """Build the jitted device form of a composed segment program: jax
    jit with donation where allowed (engine.segment_donation, gated like
    mesh donation via safe_donate), an InstrumentedJit wrapper feeding
    the compile/dispatch + segment telemetry, and the shared sticky
    padding rung."""
    from ..obs import device as obs_device
    from ..ops._jax import accelerator_present, get_jax, safe_donate
    from ..parallel.sharded_state import _StickyRung

    jax = get_jax()
    donate_cfg = str(config().engine.segment_donation).lower()
    donate: tuple = ()
    if donate_cfg == "on" or (donate_cfg == "auto" and accelerator_present()):
        donate = safe_donate(*range(len(prog.spec)))
    jfn = jax.jit(prog.raw_fn, donate_argnums=donate)
    # power-of-two ladder up to the coarse shape_buckets ceiling: engine
    # batches are pow2-sized (pipeline.source_batch_size), so the sticky
    # rung locks exactly onto the steady batch size instead of fighting
    # the 4x aggregate ladder's decay at half-rung
    cap = int(max(config().tpu.shape_buckets))
    ladder = tuple(
        1 << p for p in range(8, cap.bit_length())
        if (1 << p) <= cap
    ) or (cap,)
    prog.jit = obs_device.InstrumentedJit(program_name, jfn, segment=True)
    prog.rung = _StickyRung(ladder)
    prog.n_rows_cap = ladder[-1]


# ---------------------------------------------------------------------------
# Staged (pipelined) results
# ---------------------------------------------------------------------------


class _StagedBatch:
    """A host-tier result: already materialized, emission just deferred."""

    __slots__ = ("batch",)

    def __init__(self, batch: Optional[pa.RecordBatch]):
        self.batch = batch

    def materialize(self) -> Optional[pa.RecordBatch]:
        return self.batch


def _valid_of(validities: Dict[tuple, np.ndarray],
              deps: Optional[frozenset]) -> Optional[np.ndarray]:
    """AND of the validity masks of the leaves in `deps` (strict null
    propagation: an output row is null iff any contributing leaf was)."""
    if not deps or not validities:
        return None
    vs = [v for key, v in validities.items() if key in deps]
    if not vs:
        return None
    out = vs[0]
    for v in vs[1:]:
        out = out & v
    return out


def _as_rows(vals, n: int) -> np.ndarray:
    """Program outputs may be 0-d (a literal column): broadcast to n."""
    arr = np.asarray(vals)
    if arr.ndim == 0:
        arr = np.full(n, arr[()])
    return arr[:n]


def _materialize_result(prog: _SegmentProgram, n: int, mask_vals,
                        out_vals,
                        validities: Dict[tuple, np.ndarray],
                        ) -> Optional[pa.RecordBatch]:
    """numpy mask/outputs (+ leaf validities) -> the output RecordBatch,
    applying the row filter ONCE to the narrow output columns and
    reconstructing output nulls from strict leaf validity. Shared by the
    vector (host numpy) and jax (device) tiers."""
    keep = None
    if mask_vals is not None:
        keep = _as_rows(mask_vals, n)
        mv = _valid_of(validities, prog.mask_deps)
        if mv is not None:
            keep = keep & mv
        if not keep.any():
            return None
        if keep.all():
            keep = None
    arrays = []
    for i, (vals, field) in enumerate(zip(out_vals, prog.out_fields)):
        vals = _as_rows(vals, n)
        valid = _valid_of(validities, prog.out_deps[i])
        if keep is not None:
            vals = vals[keep]
            valid = valid[keep] if valid is not None else None
        arrays.append(_wrap_out(vals, valid, field.type))
    return pa.RecordBatch.from_arrays(arrays, schema=prog.out_schema)


def _wrap_out(vals: np.ndarray, valid: Optional[np.ndarray],
              t: pa.DataType) -> pa.Array:
    """numpy output column -> arrow array; zero-copy for all-valid
    fixed-width columns (the common case — pa.array() would copy)."""
    np_dtype = _FIXED_NP.get(t.id)
    if valid is None and np_dtype is not None \
            and vals.dtype == np.dtype(np_dtype) \
            and vals.flags["C_CONTIGUOUS"]:
        return pa.Array.from_buffers(
            t, len(vals), [None, pa.py_buffer(vals)]
        )
    if pa.types.is_timestamp(t):
        vals = vals.astype("int64", copy=False).view("datetime64[ns]")
    elif pa.types.is_duration(t):
        vals = vals.astype("int64", copy=False).view("timedelta64[ns]")
    arr = pa.array(vals, mask=None if valid is None else ~valid)
    if not arr.type.equals(t):
        arr = arr.cast(t)
    return arr


class _StagedDispatch:
    """A jax-tier result: the dispatch is in flight on the device; the
    host materializes (sync + arrow rebuild) only at emission time —
    which is how batch k's device time overlaps batch k+1's host pack."""

    __slots__ = ("prog", "rows", "mask_dev", "outs_dev", "validities")

    def __init__(self, prog: _SegmentProgram, rows: int, mask_dev, outs_dev,
                 validities: Dict[tuple, np.ndarray]):
        self.prog = prog
        self.rows = rows
        self.mask_dev = mask_dev
        self.outs_dev = outs_dev
        self.validities = validities

    def materialize(self) -> Optional[pa.RecordBatch]:
        mask = (
            np.asarray(self.mask_dev) if self.mask_dev is not None else None
        )
        outs = [np.asarray(o) for o in self.outs_dev]
        return _materialize_result(self.prog, self.rows, mask, outs,
                                   self.validities)


class _HeldWatermark:
    __slots__ = ("wm",)

    def __init__(self, wm):
        self.wm = wm


# ---------------------------------------------------------------------------
# The runtime operator
# ---------------------------------------------------------------------------


class FusedSegmentOperator(Operator):
    """One dispatch per batch for a whole stateless run, plus the
    double-buffered staging queue. Stateless by construction: no tables,
    no checkpoint capture — its only barrier obligation is draining the
    staged FIFO, which the runner does before capture."""

    is_fused_segment = True

    def __init__(self, members: List[dict], out_schema=None, name: str = ""):
        super().__init__(name or "segment")
        self.members = members
        self.out_schema = out_schema
        self._stages = [_build_stage(m) for m in members]
        short = "+".join(
            (s.name or s.kind)[:16] for s in self._stages
        ) or "identity"
        self.program_name = f"segment.{len(self._stages)}x.{short}"
        self._staged: deque = deque()
        self._depth = max(1, int(config().engine.pipeline_depth))
        self._prog: Any = False   # False = not yet built; None = view tier
        self._use_jax: Optional[bool] = None
        self._vector_broken = False
        self._host_h = SEGMENT_DISPATCH_SECONDS.labels(
            program=self.program_name, tier="host")
        SEGMENT_FUSED_OPS.labels(program=self.program_name).set(
            len(self._stages))
        self._counters = None

    # -- accounting --------------------------------------------------------

    def _count(self, ctx):
        c = self._counters
        if c is None:
            ti = ctx.task_info
            c = self._counters = (
                SEGMENT_BATCHES.labels(job=ti.job_id, task=ti.task_id),
                SEGMENT_DISPATCHES.labels(job=ti.job_id, task=ti.task_id,
                                          fused="1"),
            )
        c[0].inc()
        c[1].inc()

    # -- program selection -------------------------------------------------

    def _program(self) -> Optional[_SegmentProgram]:
        """The composed whole-chain program, built once: the numpy
        VECTOR tier runs it directly (filter-late, one mask pass on the
        narrow outputs); when the device tier is active it is jitted
        into ONE XLA program. None = not composable (opaque py_fn member
        etc.) -> the lazy-view host path."""
        if self._prog is False:
            prog = None
            try:
                prog = build_program(self._stages, self.program_name)
            except Exception:  # composition is an optimization, never fatal
                logger.exception(
                    "segment %s: program composition failed; view tier",
                    self.program_name,
                )
                prog = None
            self._prog = prog
        if self._use_jax is None and self._prog is not None:
            from ..ops._jax import device_tier_active

            self._use_jax = device_tier_active()
            if self._use_jax:
                try:
                    attach_device_program(self._prog, self.program_name)
                    logger.info(
                        "segment %s: lowered %d ops to one jitted program "
                        "(%d input leaves)", self.program_name,
                        len(self._stages), len(self._prog.spec),
                    )
                except Exception:
                    logger.exception(
                        "segment %s: device lowering failed; vector tier",
                        self.program_name,
                    )
                    self._use_jax = False
        return self._prog

    # -- execution ---------------------------------------------------------

    def _run_host(self, batch: pa.RecordBatch) -> Optional[pa.RecordBatch]:
        from ..sql.expressions import _LazyFilteredBatch

        cur = batch
        for st in self._stages:
            if st.kind == "identity":
                continue
            if st.kind == "opaque":
                cur = _materialize(cur)
                cur = st.fn(cur)
                if cur is None or cur.num_rows == 0:
                    return None
                continue
            proj = st.proj
            if proj.predicate is not None:
                mask = pc.fill_null(proj.predicate.eval(cur), False)
                kept = pc.sum(mask).as_py() or 0
                if kept == 0:
                    return None
                if kept < cur.num_rows:
                    cur = _LazyFilteredBatch(cur, mask, kept)
            cur = _ProjectedView(proj, cur)
        out = _materialize(cur)
        return out if out.num_rows else None

    def _pack_leaves(self, batch: pa.RecordBatch, prog: _SegmentProgram):
        """Host decode/pack: evaluate + densify the program's input
        leaves. Returns (arrays, validities) or None when a leaf null
        would reach a non-strict subtree (kleene and/or) — those nulls
        cannot be reconstructed from leaf validity, so the batch takes
        the lazy-view path instead."""
        arrays: List[np.ndarray] = []
        validities: Dict[tuple, np.ndarray] = {}
        for leaf in prog.spec:
            if leaf[0] == "col":
                col = batch.column(leaf[1])
            else:
                col = leaf[2].eval(batch)
            vals, valid = _leaf_np(col)
            if valid is not None:
                key = leaf[:2]
                if not prog.mask_strict and prog.mask_deps \
                        and key in prog.mask_deps:
                    return None
                if any(
                    key in deps and not strict
                    for deps, strict in zip(prog.out_deps, prog.strict)
                ):
                    return None
                validities[key] = valid
            arrays.append(vals)
        return arrays, validities

    def _dispatch_jax(self, batch: pa.RecordBatch, prog: _SegmentProgram):
        """Pack leaves, pad to the shared sticky rung, dispatch the
        jitted program. Returns a _StagedDispatch (un-materialized: the
        device crunches while the host packs the next batch), or None to
        fall back (nulls in a non-strict subtree, oversized batch)."""
        n = batch.num_rows
        if n > prog.n_rows_cap:
            return None
        packed = self._pack_leaves(batch, prog)
        if packed is None:
            return None
        arrays, validities = packed
        rung = prog.rung.fit(n)
        if rung < n:  # a just-decayed rung can undershoot; re-climb
            rung = prog.rung.fit(n)
        padded = [_pad(a, rung) for a in arrays]
        # validities stay host-side (numpy, unpadded): they only gate
        # output nulls/filtering at materialization time
        mask_dev, outs_dev = prog.jit(*padded, rung=rung)
        return _StagedDispatch(prog, n, mask_dev, outs_dev, validities)

    def _run_vector(self, batch: pa.RecordBatch, prog: _SegmentProgram):
        """Host vector tier: the composed program over numpy leaf
        arrays. Filter-late beats the per-stage lazy filter because the
        leaves are read zero-copy UNfiltered (no wide-struct filter
        kernel) and the single mask application touches only the narrow
        output columns. Returns the output batch, None (all filtered),
        or the batch itself as a fallback sentinel."""
        packed = self._pack_leaves(batch, prog)
        if packed is None:
            return batch  # sentinel: caller takes the view path
        arrays, validities = packed
        mask_vals, out_vals = prog.raw_fn(*arrays)
        return _materialize_result(prog, batch.num_rows, mask_vals,
                                   out_vals, validities)

    def _execute(self, batch: pa.RecordBatch):
        from .. import obs

        t0 = time.perf_counter()
        prog = self._program()
        staged = None
        if prog is not None and self._use_jax:
            staged = self._dispatch_jax(batch, prog)
        if staged is None:
            out = batch  # fallback sentinel
            if prog is not None and prog.exact and not self._vector_broken:
                try:
                    out = self._run_vector(batch, prog)
                except Exception:
                    # never fatal: the lazy-view path computes the same
                    # values through the arrow kernels
                    logger.exception(
                        "segment %s: vector tier failed; view tier",
                        self.program_name,
                    )
                    self._vector_broken = True
                    out = batch
            if out is batch:
                out = self._run_host(batch)
            staged = _StagedBatch(out) if out is not None else None
            self._host_h.observe(time.perf_counter() - t0)
        obs.timeline.note("segment", time.perf_counter() - t0)
        return staged

    # -- staging / pipelining ----------------------------------------------

    @property
    def staged_depth(self) -> int:
        return sum(
            1 for e in self._staged if not isinstance(e, _HeldWatermark)
        )

    async def _emit_head(self, ctx, collector):
        entry = self._staged.popleft()
        if isinstance(entry, _HeldWatermark):
            await self._release_watermark(ctx, entry.wm)
            return
        out = entry.materialize()
        if out is not None and out.num_rows:
            await collector.collect(out)

    async def _release_watermark(self, ctx, wm):
        runner = getattr(ctx, "_runner", None)
        if runner is None:
            return
        idx = runner.ops.index(self)
        await runner._chain_watermark(idx + 1, wm)

    async def _flush_to_depth(self, ctx, collector):
        # hold at most depth-1 batches; watermarks at the head flush
        # eagerly so downstream sees the exact unfused interleaving
        while self.staged_depth > self._depth - 1:
            await self._emit_head(ctx, collector)
        while self._staged and isinstance(self._staged[0], _HeldWatermark):
            await self._emit_head(ctx, collector)

    async def drain(self, ctx, collector):
        """Emit every staged entry in order (barriers, stops, close)."""
        while self._staged:
            await self._emit_head(ctx, collector)

    # -- operator hooks ----------------------------------------------------

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        self._count(ctx)
        staged = self._execute(batch)
        if staged is None:
            return
        if isinstance(staged, _StagedBatch) and not self._staged:
            # host-tier result: already materialized, nothing in flight
            # to overlap — emit straight through (the staging queue only
            # earns its latency where a device dispatch is actually
            # asynchronous)
            out = staged.batch
            if out is not None and out.num_rows:
                await collector.collect(out)
            return
        self._staged.append(staged)
        await self._flush_to_depth(ctx, collector)

    async def handle_watermark(self, watermark, ctx, collector):
        if not self._staged:
            return watermark
        # batches are in flight: queue the watermark behind them (strict
        # order), release it from the FIFO
        self._staged.append(_HeldWatermark(watermark))
        while self._staged and isinstance(self._staged[0], _HeldWatermark):
            await self._emit_head(ctx, collector)
        return None

    async def handle_checkpoint(self, barrier, ctx, collector):
        # normally a no-op: the runner drains the pipeline (with the
        # runner.pipeline_drain span) before capture; kept as a safety
        # net for direct chain invocations
        await self.drain(ctx, collector)

    async def on_close(self, ctx, collector, is_eod: bool):
        await self.drain(ctx, collector)
        return None


@register_operator(OperatorName.FUSED_SEGMENT)
def _make_segment(cfg: dict) -> Operator:
    return FusedSegmentOperator(
        cfg["ops"], cfg.get("schema"), cfg.get("name", "")
    )
