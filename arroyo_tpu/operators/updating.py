"""Updating (non-windowed) aggregates with retractions.

Capability parity with the reference's incremental_aggregator.rs
(/root/reference/crates/arroyo-worker/src/arrow/incremental_aggregator.rs):
unbounded GROUP BY over an append stream maintains per-key accumulators;
changed keys are flushed on a tick interval, emitting a retract row (the
previously emitted values) followed by the new row, tagged via the
`__updating_meta` struct column (arroyo-rpc/src/lib.rs:333
updating_meta_fields); a TTL evicts idle keys (reference updating_cache.rs).

Aggregation arithmetic runs on the shared device accumulator
(ops/aggregates.py) — count/sum/avg are incrementally updatable; min/max are
valid over append-only input (monotone). With `retractable` set (the input
is itself an updating stream), retract rows apply with sign -1 and a
per-key live-row count deletes keys whose rows have all been retracted
(emitting a final retraction). Invertible aggregates (count/sum/avg,
variance/regression, multisets) consume retractions directly; the planner
marks everything else (min/max/median/UDAF/...) with `replay`, which
re-aggregates from a value -> signed-count multiset at emission
(reference incremental_aggregator.rs raw-value replay).
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from ..engine.construct import register_operator
from ..graph.logical import OperatorName
from ..schema import TIMESTAMP_FIELD, UPDATING_META_FIELD
from .base import Operator
from .windows import WindowOperatorBase, _is_interned_type, _to_py


class UpdatingAggregateOperator(WindowOperatorBase):
    # slot-based state protocol end-to-end (single bin 0): the accumulator
    # shards across the device mesh like tumbling/sliding; key->shard
    # routing happens in MeshSlotDirectory.assign and updates ride the
    # in-step all_to_all (reference incremental_aggregator.rs:77-90 treats
    # the updating aggregate like any keyed operator)
    _mesh_ok = True
    # the C++ directory now serves every API this operator needs
    # (assign, slot-valued peek_bin, keys_for_slots via the native
    # reverse index, items): ~3x cheaper per-batch assignment than the
    # python np.unique path for int64-able keys
    _native_ok = True
    # the DEVICE directory grew the same surface in round 5 (slot-valued
    # peek_bin, keys_for_slots, slots_for_keys, targeted remove) via its
    # lazy host reverse index — steady-state assign stays a device
    # searchsorted hit with zero host dict work
    _device_ok = True

    def __init__(self, config: dict):
        super().__init__(config, "updating_aggregate")
        from ..config import config as get_config

        self.flush_interval = float(
            config.get(
                "flush_interval",
                get_config().pipeline.update_aggregate_flush_interval,
            )
        )
        ttl = config.get(
            "ttl_nanos",
            int(get_config().pipeline.update_aggregate_ttl * 1e9),
        )
        self.ttl_nanos: Optional[int] = int(ttl) if ttl else None
        # key tuple -> last emitted finalized values (None = never emitted)
        self.emitted: Dict[tuple, List] = {}
        self.dirty: set = set()
        self.last_seen: Dict[tuple, int] = {}
        self.max_ts = 0  # max event time seen (flush timestamp fallback)
        # retraction-consuming mode: input rows carry __updating_meta and
        # apply with sign -1 when is_retract; live row-count per key drives
        # key deletion once everything contributing has been retracted
        self.retractable: bool = bool(config.get("retractable"))
        self.meta_col: Optional[int] = config.get("meta_col")
        self.live: Dict[tuple, int] = {}
        # keys changed / deleted since the last checkpoint (incremental)
        self._ckpt_dirty: set = set()
        self._ckpt_dead: set = set()

    def tables(self):
        from ..state.table_config import global_table, time_key_table

        # incremental per-key rows: __ts = key's last_seen (retention = the
        # operator's own idle-key TTL), upserts + __dead tombstones; newest
        # row per key wins on restore
        return {
            "u": global_table("u"),
            "ui": time_key_table(
                "ui",
                retention_nanos=self.ttl_nanos,
                timestamp_field="__ts",
                key_fields=self._delta_key_fields(),
            ),
        }

    def tick_interval(self) -> Optional[float]:
        return self.flush_interval

    async def on_start(self, ctx):
        self._capture_key_meta(ctx)
        if ctx.table_manager is not None:
            table = await ctx.table("u")
            from .windows import _snaps_for_me

            for snap in _snaps_for_me(table, ctx, bool(self.key_cols)):
                self._restore_rows(snap, ctx)
                emitted_rows = snap.get("emitted", [])
                key_rows = [kv for kv, _ in emitted_rows]
                # range-mask on the VALUES (pre-interning), matching the
                # shuffle hash, like _restore_rows does
                mask = (
                    self._range_mask(key_rows, ctx) if key_rows else None
                )
                for i, (key_vals, vals) in enumerate(emitted_rows):
                    if mask is not None and not mask[i]:
                        continue
                    self.emitted[self._intern_key(key_vals)] = vals
                ls_rows = snap.get("last_seen", [])
                ls_mask = (
                    self._range_mask([kv for kv, _ in ls_rows], ctx)
                    if ls_rows else None
                )
                for i, (key_vals, seen) in enumerate(ls_rows):
                    if ls_mask is not None and not ls_mask[i]:
                        continue
                    self.last_seen[self._intern_key(key_vals)] = seen
                lv_rows = snap.get("live", [])
                lv_mask = (
                    self._range_mask([kv for kv, _ in lv_rows], ctx)
                    if lv_rows else None
                )
                for i, (key_vals, cnt) in enumerate(lv_rows):
                    if lv_mask is not None and not lv_mask[i]:
                        continue
                    self.live[self._intern_key(key_vals)] = cnt
            await self._restore_updating_incremental(ctx)
        # everything restored must re-verify against emitted on next flush;
        # it is also checkpoint-dirty so a legacy full snapshot gets
        # re-persisted as incremental rows at the first post-restore epoch
        for _, key, _slot in self.dir.items():
            self.dirty.add(key)
            self._ckpt_dirty.add(key)

    async def handle_checkpoint(self, barrier, ctx, collector):
        # flush before the barrier so checkpointed emitted-state matches
        # the snapshot (restores re-emit nothing)
        await self._flush(ctx, collector)
        if ctx.table_manager is None:
            return
        table = await ctx.table("u")
        if self._use_incremental():
            delta = self._build_updating_delta()
            if delta is not None:
                (await ctx.table("ui")).write_delta(delta)
            table.put(
                ctx.task_info.task_index,
                {
                    "bins": [], "keys": [], "values": [],
                    "emitted": [], "last_seen": [],
                    "subtask": ctx.task_info.task_index,
                },
            )
            return
        snap = self._snapshot_rows()
        snap["subtask"] = ctx.task_info.task_index
        snap["emitted"] = [
            [self._key_tuple_to_values(k), v]
            for k, v in self.emitted.items()
        ]
        snap["last_seen"] = [
            [self._key_tuple_to_values(k), v]
            for k, v in self.last_seen.items()
        ]
        if self.retractable:
            snap["live"] = [
                [self._key_tuple_to_values(k), v]
                for k, v in self.live.items()
            ]
        table.put(ctx.task_info.task_index, snap)

    def _build_updating_delta(self) -> Optional[pa.RecordBatch]:
        """Upsert rows for keys touched since the last epoch + __dead
        tombstones for retract-deleted keys. __ts is the key's last_seen so
        the TTL retention prunes idle keys from restore exactly like the
        live eviction does."""
        import msgpack

        slot_map = self._dirty_slot_map(self._ckpt_dirty)
        keys = list(slot_map)
        dead = list(self._ckpt_dead)
        self._ckpt_dirty = set()
        self._ckpt_dead = set()
        if not keys and not dead:
            return None
        n_phys = len(self.acc.phys)
        if keys:
            slots = np.asarray([slot_map[k] for k in keys], dtype=np.int64)
            values = self.acc.snapshot(slots)
        else:
            values = [np.empty(0, dtype=s.dtype) for s in self.acc.state]
        all_keys = keys + dead
        ts = np.asarray(
            [self.last_seen.get(k, self.max_ts) for k in keys]
            + [self.max_ts] * len(dead),
            dtype=np.int64,
        )
        arrays = [pa.array(ts)]
        names = ["__ts"]
        key_rows = [tuple(self._key_tuple_to_values(k)) for k in all_keys]
        for i, arr in enumerate(self._key_delta_arrays(key_rows)):
            arrays.append(arr)
            names.append(f"__k{i}")
        for j in range(n_phys):
            vj = np.asarray(values[j])
            col = np.concatenate([vj, np.zeros(len(dead), dtype=vj.dtype)])
            arrays.append(pa.array(col))
            names.append(f"__v{j}")
        arrays.append(
            pa.array(
                [
                    msgpack.packb(self.emitted[k])
                    if self.emitted.get(k) is not None
                    else None
                    for k in keys
                ]
                + [None] * len(dead),
                type=pa.binary(),
            )
        )
        names.append("__emitted")
        arrays.append(
            pa.array(
                np.asarray(
                    [self.live.get(k, 0) for k in keys] + [0] * len(dead),
                    dtype=np.int64,
                )
            )
        )
        names.append("__live")
        arrays.append(
            pa.array([False] * len(keys) + [True] * len(dead))
        )
        names.append("__dead")
        return pa.RecordBatch.from_arrays(arrays, names=names)

    async def _restore_updating_incremental(self, ctx):
        import msgpack

        if self._key_types is None:
            return
        table = await ctx.table("ui")
        newest: Dict[tuple, Optional[tuple]] = {}
        n_phys = len(self.acc.phys)
        for b in table.all_batches():
            names = b.schema.names
            ts = np.asarray(b.column(names.index("__ts")))
            key_cols = self._decode_delta_keys(b)
            vals = [
                np.asarray(b.column(names.index(f"__v{j}")))
                for j in range(n_phys)
            ]
            emitted = b.column(names.index("__emitted")).to_pylist()
            live = np.asarray(b.column(names.index("__live")))
            dead = np.asarray(b.column(names.index("__dead")))
            for r in range(b.num_rows):
                kv = tuple(c[r] for c in key_cols)
                newest[kv] = (
                    None
                    if dead[r]
                    else (
                        int(ts[r]),
                        [v[r] for v in vals],
                        emitted[r],
                        int(live[r]),
                    )
                )
        rows = [(kv, v) for kv, v in newest.items() if v is not None]
        table.clear_batches()
        if not rows:
            return
        mask = self._range_mask([list(kv) for kv, _ in rows], ctx)
        if mask is not None:
            rows = [rv for rv, m in zip(rows, mask) if m]
            if not rows:
                return
        cols: List[list] = [[] for _ in range(n_phys)]
        keys_l = []
        for kv, (ts_, vv, _, _) in rows:
            keys_l.append(list(kv))
            for j, v in enumerate(vv):
                cols[j].append(v)
        self._restore_rows(
            {
                "bins": [0] * len(rows),
                "keys": keys_l,
                "values": cols,
            },
            ctx,
        )
        for kv, (ts_, _, em, lv) in rows:
            key = self._intern_key(list(kv))
            self.last_seen[key] = ts_
            if em is not None:
                self.emitted[key] = msgpack.unpackb(em, raw=False)
            if self.retractable:
                self.live[key] = lv

    def _intern_key(self, key_vals: list) -> tuple:
        from ..ops.directory import intern_value

        return tuple(
            intern_value(v) if _is_interned_type(self._key_types[i]) else v
            for i, v in enumerate(key_vals)
        )

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        self._capture_key_meta(ctx)
        ts = ctx.in_schemas[0].timestamps(batch)
        bins = np.zeros(batch.num_rows, dtype=np.int64)  # single bin
        keys = self._key_arrays(batch)
        slots = self.dir.assign(bins, keys)
        self._ensure_capacity()
        signs = None
        if self.retractable:
            is_retract = np.asarray(
                batch.column(self.meta_col).field("is_retract")
                .to_numpy(zero_copy_only=False)
            )
            signs = np.where(is_retract, -1, 1).astype(np.int64)
        self.acc.update(slots, self._agg_input_cols(batch), signs=signs)
        now = int(ts.max()) if len(ts) else 0
        self.max_ts = max(self.max_ts, now)
        # mark touched keys dirty: O(unique-in-batch) via the directory's
        # reverse map, not O(live keys)
        if signs is not None:
            # per-unique-slot signed row delta, O(batch) memory (bincount
            # over raw slot ids would size by the largest live slot)
            uniq, inv = np.unique(slots, return_inverse=True)
            per_uniq = np.bincount(inv, weights=signs)
        else:
            uniq = np.unique(slots)
        for i, entry in enumerate(self.dir.keys_for_slots(uniq)):
            if entry is not None:
                _, key = entry
                self.dirty.add(key)
                self._ckpt_dirty.add(key)
                self._ckpt_dead.discard(key)
                self.last_seen[key] = now
                if signs is not None:
                    self.live[key] = self.live.get(key, 0) + int(per_uniq[i])

    def _dirty_slot_map(self, key_set) -> dict:
        """slot per live key for the (usually small) dirty set — point
        lookups, O(dirty), on every directory tier (python dict / native
        C++ probe / device bin index / mesh per-shard dispatch); the
        peek_bin fallback remains for any directory without the
        point-lookup surface."""
        lookup = getattr(self.dir, "slots_for_keys", None)
        if lookup is not None:
            return lookup(0, list(key_set))
        bin_map = self.dir.peek_bin(0) or {}
        return {k: bin_map[k] for k in key_set if k in bin_map}

    async def handle_tick(self, tick, ctx, collector):
        await self._flush(ctx, collector)
        self._evict(ctx)

    async def handle_watermark(self, watermark, ctx, collector):
        # flush BEFORE forwarding so downstream sees the deltas ahead of the
        # watermark (the end-of-stream watermark must trail the final
        # retract/append pairs, or downstream TTLs act on stale state)
        await self._flush(ctx, collector)
        return watermark

    async def on_close(self, ctx, collector, is_eod: bool):
        if is_eod:
            await self._flush(ctx, collector)
        return None

    async def _flush(self, ctx, collector):
        """Emit retract/append pairs for keys whose aggregate changed
        (reference handle_tick :994 + set_retract_metadata :1026)."""
        if not self.dirty:
            return
        slot_map = self._dirty_slot_map(self.dirty)
        keys = list(slot_map)
        self.dirty.clear()
        if not keys:
            return
        retract_keys: List[tuple] = []
        retract_vals: List[List] = []
        append_keys: List[tuple] = []
        append_vals: List[List] = []
        if self.retractable:
            # keys whose every contributing row was retracted: emit a final
            # retraction of the last emitted values and drop all state
            dead = [k for k in keys if self.live.get(k, 0) <= 0]
            if dead:
                keys = [k for k in keys if self.live.get(k, 0) > 0]
                for k in dead:
                    old = self.emitted.pop(k, None)
                    if old is not None:
                        retract_keys.append(k)
                        retract_vals.append(old)
                    self.last_seen.pop(k, None)
                    self.live.pop(k, None)
                    self._ckpt_dead.add(k)
                    self._ckpt_dirty.discard(k)
                freed = self.dir.remove(0, dead)
                if len(freed):
                    self.acc.reset_slots(freed)
        if keys:
            slots = np.asarray([slot_map[k] for k in keys], dtype=np.int64)
            agg_cols = self.acc.finalize(self.acc.gather(slots))
            # one C-level tolist per column instead of a numpy-scalar
            # .item() per cell (object columns pass through unchanged)
            col_lists = [
                c.tolist() if isinstance(c, np.ndarray)
                and c.dtype.kind != "O" else c
                for c in agg_cols
            ]
            for i, key in enumerate(keys):
                new_vals = [_to_py(c[i]) for c in col_lists]
                old = self.emitted.get(key)
                if old == new_vals:
                    continue
                if old is not None:
                    retract_keys.append(key)
                    retract_vals.append(old)
                append_keys.append(key)
                append_vals.append(new_vals)
                self.emitted[key] = new_vals
        if self._serve_view is not None:
            # StateServe: mirror the flushed aggregates into the serve
            # view — appends overwrite the key, a fully-retracted key
            # stages a tombstone (sealed at the next capture)
            view = self._serve_view
            for key, vals in zip(append_keys, append_vals):
                view.stage(
                    view.canon_key(self._key_tuple_to_values(key)),
                    dict(zip(view.value_names, vals)),
                )
            for key, old in zip(retract_keys, retract_vals):
                if key not in self.emitted:  # final retraction (dead key)
                    view.stage_tomb(
                        view.canon_key(self._key_tuple_to_values(key))
                    )
        if not retract_keys and not append_keys:
            return
        # flushes before the first watermark stamp rows with the max
        # event time seen — a zero timestamp would look ancient to
        # downstream event-time TTLs and get evicted immediately
        ts = ctx.watermarks.current_nanos() or self.max_ts
        if retract_keys:
            await collector.collect(
                self._build_updating(retract_keys, retract_vals, True, ts)
            )
        if append_keys:
            await collector.collect(
                self._build_updating(append_keys, append_vals, False, ts)
            )

    def _build_updating(
        self, keys: List[tuple], vals: List[List], is_retract: bool, ts: int
    ) -> pa.RecordBatch:
        from ..ops.directory import unintern_value

        n = len(keys)
        arrays = []
        for f in self.out_schema.schema:
            if f.name == TIMESTAMP_FIELD:
                arrays.append(
                    pa.array(np.full(n, ts, dtype=np.int64)).cast(f.type)
                )
            elif f.name == UPDATING_META_FIELD:
                from ..schema import updating_meta_array

                arrays.append(updating_meta_array(n, is_retract))
            elif f.name in (self._key_names or []):
                ki = self._key_names.index(f.name)
                kt = self._key_types[ki]
                kv = [_to_py(k[ki]) for k in keys]
                if _is_interned_type(kt):
                    arrays.append(
                        pa.array([unintern_value(v) for v in kv], type=kt)
                    )
                elif pa.types.is_unsigned_integer(kt):
                    arrays.append(
                        pa.array([v % (1 << 64) for v in kv], type=kt)
                    )
                else:
                    arrays.append(pa.array(kv, type=kt))
            else:
                ai = next(
                    j for j, s in enumerate(self.specs) if s.name == f.name
                )
                arrays.append(pa.array([v[ai] for v in vals], type=f.type))
        return pa.RecordBatch.from_arrays(arrays, schema=self.out_schema.schema)

    def _evict(self, ctx):
        """TTL eviction of idle keys (reference updating_cache.rs)."""
        if not self.ttl_nanos:
            return
        wm = ctx.watermarks.current_nanos()
        if wm is None:
            return
        from ..types import WATERMARK_END

        if wm >= WATERMARK_END:
            return  # end-of-stream marker, not a real event time
        cutoff = wm - self.ttl_nanos
        stale = [k for k, seen in self.last_seen.items() if seen < cutoff]
        if not stale:
            return
        freed = self.dir.remove(0, stale)
        if len(freed):
            self.acc.reset_slots(freed)
        for k in stale:
            self.last_seen.pop(k, None)
            self.emitted.pop(k, None)
            self.live.pop(k, None)
            self.dirty.discard(k)
            # retention alone ages these rows out of restore; no tombstone
            # needed since eviction == the retention cutoff itself
            self._ckpt_dirty.discard(k)


@register_operator(OperatorName.UPDATING_AGGREGATE)
def _make_updating(config: dict) -> Operator:
    return UpdatingAggregateOperator(config)
