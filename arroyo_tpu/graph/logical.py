"""Logical dataflow graph — the compiled form of a pipeline.

Capability parity with the reference's `arroyo-datastream` crate
(/root/reference/crates/arroyo-datastream/src/logical.rs): the operator
vocabulary (:28-44), edge types (:47), LogicalNode/LogicalProgram
(:220,:300) and proto round-trip. TPU-native redesign: operator configs are
plain msgpack-serializable dicts (no protobuf needed in-process; the
distributed path serializes the same structure), and nodes carry the
StreamSchema of each edge directly.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import json
from typing import Any, Dict, List, Optional

from ..schema import StreamSchema


class OperatorName(enum.Enum):
    """Complete operator vocabulary (reference: logical.rs:28-44)."""

    EXPRESSION_WATERMARK = "expression_watermark"
    ARROW_VALUE = "arrow_value"  # stateless projection/filter exec
    ARROW_KEY = "arrow_key"  # key calculation
    PROJECTION = "projection"
    ASYNC_UDF = "async_udf"
    JOIN = "join"  # windowed/expiring join
    INSTANT_JOIN = "instant_join"
    LOOKUP_JOIN = "lookup_join"
    WINDOW_FUNCTION = "window_function"
    TUMBLING_WINDOW_AGGREGATE = "tumbling_window_aggregate"
    SLIDING_WINDOW_AGGREGATE = "sliding_window_aggregate"
    SESSION_WINDOW_AGGREGATE = "session_window_aggregate"
    UPDATING_AGGREGATE = "updating_aggregate"
    CONNECTOR_SOURCE = "connector_source"
    CONNECTOR_SINK = "connector_sink"
    # a fused run of stateless value operators compiled into one segment
    # program (engine/segments.py SegmentFusionPass): config carries the
    # member ChainedOp dicts under "ops"
    FUSED_SEGMENT = "fused_segment"


class EdgeType(enum.Enum):
    """How batches route between nodes (reference: logical.rs:47)."""

    FORWARD = "forward"  # 1-1, no repartition
    SHUFFLE = "shuffle"  # hash-partition by routing keys
    LEFT_JOIN = "left_join"  # shuffle into a join's left input
    RIGHT_JOIN = "right_join"  # shuffle into a join's right input

    def is_shuffle(self) -> bool:
        return self != EdgeType.FORWARD

    def join_side(self) -> Optional[int]:
        if self == EdgeType.LEFT_JOIN:
            return 0
        if self == EdgeType.RIGHT_JOIN:
            return 1
        return None


@dataclasses.dataclass
class ChainedOp:
    """One operator inside a (possibly fused) node."""

    operator: OperatorName
    config: Dict[str, Any]
    description: str = ""


@dataclasses.dataclass
class LogicalNode:
    node_id: int
    description: str
    chain: List[ChainedOp]
    parallelism: int = 1

    @property
    def head(self) -> ChainedOp:
        return self.chain[0]

    @property
    def is_source(self) -> bool:
        return self.head.operator == OperatorName.CONNECTOR_SOURCE

    @property
    def is_sink(self) -> bool:
        return self.chain[-1].operator == OperatorName.CONNECTOR_SINK

    @staticmethod
    def single(
        node_id: int,
        operator: OperatorName,
        config: Dict[str, Any],
        description: str = "",
        parallelism: int = 1,
    ) -> "LogicalNode":
        return LogicalNode(
            node_id, description or operator.value,
            [ChainedOp(operator, config, description)], parallelism,
        )


@dataclasses.dataclass
class LogicalEdge:
    src: int  # node_id
    dst: int
    edge_type: EdgeType
    schema: StreamSchema  # schema of data on this edge (keys = routing keys)


@dataclasses.dataclass
class LogicalGraph:
    """The compiled pipeline DAG (reference: LogicalProgram, logical.rs:300)."""

    nodes: Dict[int, LogicalNode] = dataclasses.field(default_factory=dict)
    edges: List[LogicalEdge] = dataclasses.field(default_factory=list)

    # -- construction -------------------------------------------------------

    def add_node(self, node: LogicalNode) -> LogicalNode:
        assert node.node_id not in self.nodes, f"dup node {node.node_id}"
        self.nodes[node.node_id] = node
        return node

    def add_edge(
        self, src: int, dst: int, edge_type: EdgeType, schema: StreamSchema
    ) -> LogicalEdge:
        e = LogicalEdge(src, dst, edge_type, schema)
        self.edges.append(e)
        return e

    def next_id(self) -> int:
        return max(self.nodes.keys(), default=0) + 1

    # -- queries ------------------------------------------------------------

    def in_edges(self, node_id: int) -> List[LogicalEdge]:
        return [e for e in self.edges if e.dst == node_id]

    def out_edges(self, node_id: int) -> List[LogicalEdge]:
        return [e for e in self.edges if e.src == node_id]

    def sources(self) -> List[LogicalNode]:
        return [n for n in self.nodes.values() if not self.in_edges(n.node_id)]

    def sinks(self) -> List[LogicalNode]:
        return [n for n in self.nodes.values() if not self.out_edges(n.node_id)]

    def topo_order(self) -> List[LogicalNode]:
        indeg = {nid: len(self.in_edges(nid)) for nid in self.nodes}
        ready = sorted(nid for nid, d in indeg.items() if d == 0)
        out: List[LogicalNode] = []
        while ready:
            nid = ready.pop(0)
            out.append(self.nodes[nid])
            for e in self.out_edges(nid):
                indeg[e.dst] -= 1
                if indeg[e.dst] == 0:
                    ready.append(e.dst)
            ready.sort()
        assert len(out) == len(self.nodes), "cycle in logical graph"
        return out

    def update_parallelism(self, overrides: Dict[int, int]) -> None:
        """Rescale support (reference: logical.rs:317).

        The planner picks FORWARD for an edge exactly when both endpoints
        had equal parallelism at plan time AND round-robin delivery was
        acceptable there (planner._edge: forward OR unkeyed shuffle;
        key-affine operators always get keyed SHUFFLE edges). An override
        can break that equality, and the physical build asserts it — so
        any forward edge left unbalanced degrades to the unkeyed shuffle
        the planner would have chosen for the same parallelism pair."""
        for nid, p in overrides.items():
            self.nodes[nid].parallelism = p
        for e in self.edges:
            if (
                e.edge_type == EdgeType.FORWARD
                and self.nodes[e.src].parallelism
                != self.nodes[e.dst].parallelism
            ):
                e.edge_type = EdgeType.SHUFFLE

    def set_parallelism(self, p: int, internal_only: bool = False) -> None:
        for n in self.nodes.values():
            if internal_only and (n.is_source or n.is_sink):
                continue
            n.parallelism = p

    def features(self) -> set[str]:
        """Feature inventory for telemetry/UI (reference: features())."""
        out = set()
        for n in self.nodes.values():
            for op in n.chain:
                out.add(op.operator.value)
        return out

    def get_hash(self) -> str:
        return hashlib.sha256(
            json.dumps(self.to_json(), sort_keys=True).encode()
        ).hexdigest()[:16]

    # -- serialization (distribution + DB storage) --------------------------

    def to_json(self) -> dict:
        import pyarrow as pa

        def schema_json(s: StreamSchema) -> dict:
            buf = s.schema.serialize()
            return {
                "ipc": buf.to_pybytes().hex(),
                "key_indices": list(s.key_indices),
            }

        return {
            "nodes": [
                {
                    "node_id": n.node_id,
                    "description": n.description,
                    "parallelism": n.parallelism,
                    "chain": [
                        {
                            "operator": op.operator.value,
                            "config": _config_json(op.config),
                            "description": op.description,
                        }
                        for op in n.chain
                    ],
                }
                for n in self.nodes.values()
            ],
            "edges": [
                {
                    "src": e.src,
                    "dst": e.dst,
                    "edge_type": e.edge_type.value,
                    "schema": schema_json(e.schema),
                }
                for e in self.edges
            ],
        }

    @staticmethod
    def from_json(data: dict) -> "LogicalGraph":
        import pyarrow as pa

        def schema_from(d: dict) -> StreamSchema:
            schema = pa.ipc.read_schema(pa.py_buffer(bytes.fromhex(d["ipc"])))
            return StreamSchema(schema, tuple(d["key_indices"]))

        g = LogicalGraph()
        for nd in data["nodes"]:
            g.add_node(
                LogicalNode(
                    nd["node_id"],
                    nd["description"],
                    [
                        ChainedOp(
                            OperatorName(od["operator"]),
                            _config_unjson(od["config"]),
                            od["description"],
                        )
                        for od in nd["chain"]
                    ],
                    nd["parallelism"],
                )
            )
        for ed in data["edges"]:
            g.add_edge(
                ed["src"], ed["dst"], EdgeType(ed["edge_type"]),
                schema_from(ed["schema"]),
            )
        return g


def _value_json(v: Any) -> Any:
    if isinstance(v, StreamSchema):
        return {
            "__stream_schema__": {
                "ipc": v.schema.serialize().to_pybytes().hex(),
                "key_indices": list(v.key_indices),
            }
        }
    if isinstance(v, bytes):
        return {"__bytes__": v.hex()}
    if isinstance(v, dict):
        return _config_json(v)
    if isinstance(v, list):
        # fused-segment configs nest member op dicts under "ops"
        return [_value_json(x) for x in v]
    return v


def _config_json(config: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _value_json(v) for k, v in config.items()}


def _value_unjson(v: Any) -> Any:
    import pyarrow as pa

    if isinstance(v, dict) and "__stream_schema__" in v:
        d = v["__stream_schema__"]
        return StreamSchema(
            pa.ipc.read_schema(pa.py_buffer(bytes.fromhex(d["ipc"]))),
            tuple(d["key_indices"]),
        )
    if isinstance(v, dict) and "__bytes__" in v:
        return bytes.fromhex(v["__bytes__"])
    if isinstance(v, dict):
        return _config_unjson(v)
    if isinstance(v, list):
        return [_value_unjson(x) for x in v]
    return v


def _config_unjson(config: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _value_unjson(v) for k, v in config.items()}
