from .base import Operator, SourceOperator, SourceFinishType  # noqa: F401
from .control import (  # noqa: F401
    CheckpointMsg,
    CommitMsg,
    ControlResp,
    LoadCompactedMsg,
    StopMsg,
)
from .collector import Collector, EdgeSender  # noqa: F401
from .context import OperatorContext, SourceContext, WatermarkHolder  # noqa: F401
from .queues import BatchQueue, InputQueue  # noqa: F401
