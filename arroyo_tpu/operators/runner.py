"""The subtask event loop — the engine's hot loop.

Capability parity with the reference's operator_run_behavior
(/root/reference/crates/arroyo-operator/src/operator.rs:932-1065):
a select over (a) the control queue, (b) all input queues, (c) a periodic
tick — with Chandy-Lamport checkpoint-barrier alignment (barriered inputs
are blocked until every live input delivered the epoch's barrier, then the
chain snapshots state, reports to the job controller, and re-broadcasts the
barrier downstream), per-input watermark min-merge, and operator chaining
(a fused chain executes in one task with direct calls, reference
operator.rs:406-530 ChainedCollector).

asyncio-native redesign: each subtask is one asyncio task; input queue reads
are armed as sub-tasks and re-armed selectively (a blocked input is simply
not re-armed — no polling).
"""

from __future__ import annotations

import asyncio
import time
import traceback
import weakref
from typing import Dict, List, Optional

import pyarrow as pa

from .. import chaos, obs
from ..analysis.model.effects import protocol_effect
from ..analysis.races import shared_state
from ..analysis.races.sanitizer import set_task_root
from ..config import config
from ..metrics import (
    BARRIER_ALIGNMENT_SECONDS,
    BATCH_PROCESSING_SECONDS,
    BATCHES_RECV,
    BUSY_SECONDS,
    BYTES_RECV,
    CHECKPOINT_PHASE_SECONDS,
    E2E_LATENCY_SECONDS,
    LATENCY_MARKER_SECONDS,
    MESSAGES_RECV,
    WATERMARK_LAG_SECONDS,
)
from ..types import (
    SignalKind,
    SignalMessage,
    StopMode,
    Watermark,
    WatermarkKind,
)
from ..utils.logging import get_logger
from .base import Operator, SourceFinishType, SourceOperator
from .collector import Collector
from .context import OperatorContext, SourceContext
from .control import (
    CheckpointCompletedResp,
    CheckpointEventResp,
    CheckpointMsg,
    CommitMsg,
    LoadCompactedMsg,
    StopMsg,
    TaskFailedResp,
    TaskFinishedResp,
)
from .queues import BatchQueue, InputQueue, QueueClosed, batch_bytes

logger = get_logger("runner")


class ChainCollector:
    """Collector seen by chain op `i`: routes collected batches directly into
    op i+1 (same task, no queue) or to the tail edge collector."""

    def __init__(self, runner: "SubtaskRunner", op_idx: int):
        self.runner = runner
        self.op_idx = op_idx

    async def collect(self, batch: pa.RecordBatch):
        if batch.num_rows == 0:
            return
        nxt = self.op_idx + 1
        r = self.runner
        if r._audit_on:
            # conservation ledger: per-epoch selectivity counts — rows
            # leaving op i are rows entering op i+1 (direct call, no queue)
            r._op_counts[self.op_idx][1] += batch.num_rows
            if nxt < len(r.ops):
                r._op_counts[nxt][0] += batch.num_rows
        if nxt < len(r.ops):
            await r.ops[nxt].process_batch(batch, r.ctxs[nxt], r.collectors[nxt], 0)
        else:
            await r.tail.collect(batch)


# runner state is shared between the main select loop, the pipelined
# flush tasks it spawns (which set _flush_failed), and stop/commit
# control arrivals; the pipelined-flush bookkeeping is the hottest
# read-modify-write-across-await surface in the tree (ROADMAP item 4)
@shared_state(
    "_await_commit_epoch", "_inflight_flushes", "_flush_failed",
    "_flush_hwm", "_stopping", "_current_barrier", "_barrier_inputs",
    "_finish_kinds", "_last_flush",
    multi_writer=("_flush_failed", "_stopping"),
)
class SubtaskRunner:
    """Executes one subtask: a chain of operators with shared inputs/outputs."""

    def __init__(
        self,
        ops: List[Operator],
        ctxs: List[OperatorContext],
        inputs: List[InputQueue],
        tail: Collector,
        control_rx: asyncio.Queue,
        control_tx: asyncio.Queue,
    ):
        assert len(ops) == len(ctxs) and ops
        self.ops = ops
        self.ctxs = ctxs
        self.inputs = inputs
        self.tail = tail
        self.control_rx = control_rx
        self.control_tx = control_tx
        self.collectors = [ChainCollector(self, i) for i in range(len(ops))]
        for ctx in ctxs:
            ctx._runner = self  # back-ref for in-chain watermark injection
        self.task_info = ctxs[0].task_info
        self.watermarks = ctxs[0].watermarks
        # generation-overlap rescale: a staged incarnation's sources park
        # on this gate after on_start/restore until promotion releases
        # them (None everywhere else — zero cost on the normal path)
        self.source_gate: Optional[asyncio.Event] = None
        # hot-standby failover (ISSUE 17): a standby incarnation restores
        # its tables at arm time but parks HERE before any operator's
        # on_start — on_start derives in-memory state from the tables
        # non-idempotently (joins append, sources read offsets once), so
        # it must run exactly once, on the final promoted/tailed state
        self.standby_gate: Optional[asyncio.Event] = None
        self._finish_kinds: Dict[int, SignalKind] = {}
        self._barrier_inputs: set[int] = set()
        self._current_barrier = None
        self._stopping = False
        # committing state (reference states/committing): set to the epoch
        # of the latest checkpoint that reported commit data; the runner
        # must not tear down until the phase-2 CommitMsg for it arrives,
        # or the sealed sink transaction would be stranded uncommitted
        self._await_commit_epoch: Optional[int] = None
        tid = self.task_info.task_id
        jid = self.task_info.job_id
        self._batches_recv = BATCHES_RECV.labels(job=jid, task=tid)
        self._msgs_recv = MESSAGES_RECV.labels(job=jid, task=tid)
        self._bytes_recv = BYTES_RECV.labels(job=jid, task=tid)
        # flight recorder: per-subtask latency/lag instruments
        self._batch_seconds = BATCH_PROCESSING_SECONDS.labels(
            job=jid, task=tid)
        # DS2 true-rate denominator: seconds of useful work (vs idle on
        # queue reads / blocked on backpressure) — see metrics.BUSY_SECONDS
        self._busy_secs = BUSY_SECONDS.labels(job=jid, task=tid)
        self._align_gauge = BARRIER_ALIGNMENT_SECONDS.labels(
            job=jid, task=tid)
        self._phase_obs = {
            p: CHECKPOINT_PHASE_SECONDS.labels(job=jid, task=tid, phase=p)
            for p in ("align", "capture", "flush")
        }
        self._wm_lag = None  # registered lazily on the first watermark
        self._align_span = obs.NULL_SPAN
        self._align_started: Optional[float] = None
        # off-barrier checkpoint flush queue (ROADMAP item 4): up to
        # state.max_inflight_flushes epochs' flushes run concurrently
        # with later epochs' processing, strictly epoch-ordered per
        # subtask (each flush awaits its predecessor before doing I/O)
        self._inflight_flushes: List[asyncio.Task] = []
        self._last_flush: Optional[asyncio.Task] = None
        self._flush_failed = False
        self._max_inflight = max(1, int(config().state.max_inflight_flushes))
        self._flush_hwm = 0  # high-water mark of concurrent flushes (tests)
        # device-tier observatory: latency-marker transit up to this
        # subtask (and end-to-end when terminal), plus the trace id that
        # batch/watermark-triggered jax.compile spans anchor under
        self._marker_secs = LATENCY_MARKER_SECONDS.labels(job=jid, task=tid)
        self._e2e_secs = E2E_LATENCY_SECONDS.labels(job=jid, task=tid)
        self._compile_trace = obs.new_trace(jid, f"batch-{tid}")
        # fused segments in this chain (engine/segments.py): their staged
        # double-buffered batches must drain before a barrier's capture
        self._segment_idxs = [
            i for i, op in enumerate(ops)
            if getattr(op, "is_fused_segment", False)
        ]
        # conservation ledger (obs/audit.py): receiver-side attestation
        # taps (one per input whose queue the wiring stamped with its
        # edge key) + per-operator in/out selectivity counts. All state
        # here is select-loop-confined: _collect_audit snapshots it by
        # value before handing the payload to the pipelined flush task.
        self._audit_on = obs.audit.enabled()
        if self._audit_on:
            self._rx_taps: List[Optional[obs.audit.EdgeTap]] = [
                obs.audit.EdgeTap(e)
                if (e := getattr(iq.queue, "audit_edge", None)) else None
                for iq in inputs
            ]
        else:
            self._rx_taps = [None] * len(inputs)
        self._op_counts = [[0, 0] for _ in ops]

    def _note_busy(self, dt: float, phase: str):
        """Mirror one busy-seconds increment into the fleet observatory:
        per-job attributed busy (the ambient job context is set by run(),
        so flush tasks and device work inherit it) plus the batch-phase
        timeline ledger. Both are single dict/deque updates when on."""
        obs.attribution.note(busy=dt)
        obs.timeline.note(phase, dt, task=self.task_info.task_id)

    @property
    def is_source(self) -> bool:
        return isinstance(self.ops[0], SourceOperator)

    # ------------------------------------------------------------------ run

    async def run(self):
        # bind the job-id attribution context for this runner task's whole
        # dynamic extent: every await-descendant (checkpoint flush tasks,
        # to_thread storage work, device dispatches) inherits it, so cost
        # on a multiplexed worker rolls up to the right tenant
        obs.attribution.set_job(self.task_info.job_id)
        set_task_root(f"runner:{self.task_info.task_id}")
        try:
            if self.standby_gate is not None:
                # hot-standby arm (ISSUE 17): pay the storage restore NOW,
                # while the primary generation is still running — the
                # controller tails later epochs' delta chains onto these
                # open tables until promotion releases the gate
                with obs.span("task.standby_arm", cat="runner",
                              task=self.task_info.task_id):
                    from ..serve import serve_mirror_tables

                    for op, ctx in zip(self.ops, self.ctxs):
                        if ctx.table_manager is not None:
                            await ctx.table_manager.open({
                                **op.tables(),
                                **serve_mirror_tables(op, self.task_info),
                            })
                await self.standby_gate.wait()
            # under the job.schedule trace (context inherited at task
            # spawn): table restore + operator on_start become visible
            # stages of a (re)start in the flight recording
            with obs.span("task.start", cat="runner",
                          task=self.task_info.task_id) as sp:
                from ..serve import register_op as serve_register
                from ..serve import serve_mirror_tables

                for idx, (op, ctx) in enumerate(zip(self.ops, self.ctxs)):
                    if (ctx.table_manager is not None
                            and self.standby_gate is None):
                        # viewed operators additionally open the
                        # `__serve__` mirror table followers tail
                        await ctx.table_manager.open({
                            **op.tables(),
                            **serve_mirror_tables(op, self.task_info),
                        })
                    sp.event("on_start", op=type(op).__name__, op_idx=idx)
                    await op.on_start(ctx)
                    # StateServe: keyed operators expose an epoch-
                    # consistent read view (seeded from restored state,
                    # so a recovered job serves immediately)
                    serve_register(op, ctx)
            drained: Optional[bool] = None
            detail = ""
            if self.is_source:
                finish = await self._run_source()
                if finish == SourceFinishType.FINAL:
                    status = self.ops[0].drain_status()
                    if status is not None:
                        drained, detail = bool(status[0]), str(status[1])
            else:
                await self._run_operator_loop()
            self.control_tx.put_nowait(
                TaskFinishedResp(
                    self.task_info.task_id,
                    self.task_info.node_id,
                    self.task_info.task_index,
                    source_drained=drained,
                    source_drain_detail=detail,
                )
            )
        except Exception:
            logger.exception("task %s failed", self.task_info.task_id)
            self.control_tx.put_nowait(
                TaskFailedResp(
                    self.task_info.task_id,
                    self.task_info.node_id,
                    self.task_info.task_index,
                    traceback.format_exc(),
                )
            )

    async def run_prefinished(self):
        """Restored-as-finished (the restore manifest's `finished_tasks`):
        every row this task ever produced is already reflected in the
        restored downstream state, so re-running would duplicate it. Just
        close the output streams and report finished."""
        try:
            await self.tail.broadcast(SignalMessage.end_of_data())
            self.control_tx.put_nowait(
                TaskFinishedResp(
                    self.task_info.task_id,
                    self.task_info.node_id,
                    self.task_info.task_index,
                )
            )
        except Exception:
            logger.exception(
                "prefinished task %s failed", self.task_info.task_id
            )
            self.control_tx.put_nowait(
                TaskFailedResp(
                    self.task_info.task_id,
                    self.task_info.node_id,
                    self.task_info.task_index,
                    traceback.format_exc(),
                )
            )

    # --------------------------------------------------------------- source

    async def _run_source(self):
        src: SourceOperator = self.ops[0]  # type: ignore[assignment]
        ctx: SourceContext = self.ctxs[0]  # type: ignore[assignment]
        ctx._runner = self  # check_control delegates here
        if self.source_gate is not None:
            # staged incarnation: state is restored (on_start already
            # ran), now hold emission until the controller promotes this
            # generation — the old one is still draining its final epoch
            await self.source_gate.wait()
        finish = await src.run(ctx, self.collectors[0])
        await src.flush_buffer(ctx, self.collectors[0])
        if finish == SourceFinishType.FINAL:
            await self._close_chain(is_eod=True)
            await self.tail.broadcast(SignalMessage.end_of_data())
        elif finish == SourceFinishType.GRACEFUL:
            await self._close_chain(is_eod=False)
            await self.tail.broadcast(SignalMessage.stop())
        # IMMEDIATE: tear down silently
        return finish

    async def source_handle_control(self, collector) -> Optional[SourceFinishType]:
        """Called by sources between emissions (via ctx.check_control):
        drain pending control messages; returns a finish type when the source
        should stop."""
        src: SourceOperator = self.ops[0]  # type: ignore[assignment]
        ctx: SourceContext = self.ctxs[0]  # type: ignore[assignment]
        while True:
            try:
                msg = self.control_rx.get_nowait()
            except asyncio.QueueEmpty:
                return None
            if isinstance(msg, CheckpointMsg):
                # rows buffered before the barrier belong to this epoch
                await src.flush_buffer(ctx, collector)
                await self._checkpoint_chain(msg.barrier)
                if msg.barrier.then_stop:
                    return SourceFinishType.GRACEFUL
            elif isinstance(msg, StopMsg):
                if msg.mode == StopMode.IMMEDIATE:
                    return SourceFinishType.IMMEDIATE
                await src.flush_buffer(ctx, collector)
                return SourceFinishType.GRACEFUL
            elif isinstance(msg, CommitMsg):
                await self._handle_commit(msg)
            elif isinstance(msg, LoadCompactedMsg):
                await self._load_compacted(msg)

    # ------------------------------------------------------------ operators

    async def _run_operator_loop(self):
        pending: Dict[asyncio.Task, object] = {}

        def arm_input(i: int):
            iq = self.inputs[i]
            t = asyncio.ensure_future(iq.queue.recv())
            pending[t] = i

        def arm_control():
            t = asyncio.ensure_future(self.control_rx.get())
            pending[t] = "control"

        tick_interval = min(
            (op.tick_interval() for op in self.ops if op.tick_interval()),
            default=None,
        )
        tick_count = 0

        def arm_tick():
            if tick_interval:
                t = asyncio.ensure_future(asyncio.sleep(tick_interval))
                pending[t] = "tick"

        # operator-owned futures (async UDF completions etc., reference
        # operator.rs future_to_poll): re-queried whenever un-armed, since
        # processing a batch may create new pollable work
        op_futs: Dict[int, asyncio.Task] = {}

        def arm_op_futures():
            for idx, op in enumerate(self.ops):
                if idx not in op_futs:
                    f = op.future_to_poll()
                    if f is not None:
                        t = asyncio.ensure_future(f)
                        op_futs[idx] = t
                        pending[t] = ("opfut", idx)

        for i in range(len(self.inputs)):
            arm_input(i)
        arm_control()
        arm_tick()
        arm_op_futures()

        while not self._all_inputs_finished() and not self._stopping:
            done, _ = await asyncio.wait(
                pending.keys(), return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                tag = pending.pop(t)
                if tag == "control":
                    await self._handle_control(t.result())
                    arm_control()
                elif tag == "tick":
                    tick_count += 1
                    t0 = time.perf_counter()
                    for op, ctx, coll in zip(self.ops, self.ctxs, self.collectors):
                        if op.tick_interval():
                            await op.handle_tick(tick_count, ctx, coll)
                    dt = time.perf_counter() - t0
                    self._busy_secs.inc(dt)
                    obs.attribution.note(busy=dt)
                    arm_tick()
                elif isinstance(tag, tuple) and tag[0] == "opfut":
                    idx = tag[1]
                    op_futs.pop(idx, None)
                    await self.ops[idx].handle_future_result(
                        self.ctxs[idx], self.collectors[idx]
                    )
                else:
                    i: int = tag  # input index
                    try:
                        item = t.result()
                    except QueueClosed:
                        self._finish_kinds[i] = SignalKind.STOP
                        self.inputs[i].finished = True
                        # a closed input can no longer hold back alignment
                        if self._current_barrier is not None:
                            await self._maybe_complete_alignment()
                        continue
                    rearm = await self._handle_input_item(i, item)
                    if rearm and not self.inputs[i].finished and not self.inputs[i].blocked:
                        arm_input(i)
                    # alignment complete may unblock other inputs
                    if self._current_barrier is None:
                        for j, iq in enumerate(self.inputs):
                            if iq.blocked:
                                iq.blocked = False
                                if not iq.finished:
                                    arm_input(j)
            arm_op_futures()
        # keep the armed control-queue getter: it may already hold a
        # retrieved message (e.g. the phase-2 CommitMsg) that cancelling
        # would silently drop
        control_task = next(
            (t for t, tag in pending.items() if tag == "control"), None
        )
        for t in pending:
            if t is not control_task:
                t.cancel()
        control_task = await self._await_commit(control_task)
        if control_task is not None:
            control_task.cancel()
        # end-of-data only when every input actually delivered EOS — an
        # IMMEDIATE stop (crash-like teardown) leaves _finish_kinds empty
        # and must NOT finalize uncommitted sink output (exactly-once:
        # visibility belongs to the 2PC commit, not teardown)
        is_eod = (
            not self._stopping
            and len(self._finish_kinds) == len(self.inputs)
            and all(
                k == SignalKind.END_OF_DATA
                for k in self._finish_kinds.values()
            )
        )
        await self._close_chain(is_eod=is_eod)
        await self.tail.broadcast(
            SignalMessage.end_of_data() if is_eod else SignalMessage.stop()
        )

    @protocol_effect("worker.await_commit")
    async def _await_commit(self, control_task, timeout: float = 10.0):
        """Committing state (reference states/committing.rs): the inputs
        closed, but the last checkpoint reported commit data whose phase-2
        CommitMsg hasn't arrived yet — closing now would strand a sealed
        sink transaction. Keep consuming control messages (bounded) until
        the commit lands. Skipped on IMMEDIATE stop: crash-like teardown
        must not finalize anything (recovery replays the epoch)."""
        import time

        if self._await_commit_epoch is None or self._stopping:
            return control_task
        deadline = time.monotonic() + timeout
        while self._await_commit_epoch is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                logger.warning(
                    "%s: no commit received for epoch %s within %.0fs; "
                    "closing with the transaction sealed but uncommitted",
                    self.task_info.task_id, self._await_commit_epoch,
                    timeout,
                )
                break
            if control_task is None:
                control_task = asyncio.ensure_future(self.control_rx.get())
            try:
                msg = await asyncio.wait_for(
                    asyncio.shield(control_task), remaining
                )
            except asyncio.TimeoutError:
                continue  # deadline check above breaks the loop
            control_task = None
            await self._handle_control(msg)
        return control_task

    def _all_inputs_finished(self) -> bool:
        return all(iq.finished for iq in self.inputs)

    async def _handle_input_item(self, i: int, item) -> bool:
        """Process one message from input i. Returns whether to re-arm."""
        spec = chaos.fire("runner.stall", job=self.task_info.job_id,
                          task=self.task_info.task_id)
        if spec is not None:
            # a wedged operator: the input loop holds (async — only THIS
            # subtask stalls; co-resident tenants keep their turns on the
            # shared loop) while upstream queues back up and the
            # watermark falls behind — the freshness-SLO drill's seam
            if spec.param("block", False):
                # params.block: a CPU-bound/blocking UDF that never yields
                # — starves the WHOLE event loop (heartbeats, co-tenants),
                # the starvation drill's attack on squeezed deadlines
                time.sleep(float(spec.param("delay", 0.5)))  # arroyolint: disable=ASY002
            else:
                await asyncio.sleep(float(spec.param("delay", 0.5)))
        iq = self.inputs[i]
        if isinstance(item, SignalMessage):
            if item.kind == SignalKind.WATERMARK:
                changed = self.watermarks.set(i, item.watermark)
                if changed is not None:
                    self._track_watermark_lag(changed)
                    # window emission happens here: count it as busy time
                    # or watermark-driven operators look idle to the
                    # autoscaler no matter how hard they work
                    t0 = time.perf_counter()
                    anchor = obs.device.anchor(
                        self._compile_trace, "watermark.advance",
                        task=self.task_info.task_id,
                    )
                    try:
                        await self._chain_watermark(0, changed)
                    finally:
                        anchor.close()
                    dt = time.perf_counter() - t0
                    self._busy_secs.inc(dt)
                    self._note_busy(dt, "watermark")
                return True
            if item.kind == SignalKind.LATENCY_MARKER:
                await self._handle_marker(item)
                return True
            if item.kind == SignalKind.BARRIER:
                return await self._handle_barrier(i, item.barrier)
            if item.kind in (SignalKind.END_OF_DATA, SignalKind.STOP):
                self._finish_kinds[i] = item.kind
                iq.finished = True
                # a finished input can no longer hold back alignment
                if self._current_barrier is not None:
                    await self._maybe_complete_alignment()
                return False
            return True
        # data batch
        self._batches_recv.inc()
        self._msgs_recv.inc(item.num_rows)
        nbytes = batch_bytes(item)
        self._bytes_recv.inc(nbytes)
        obs.attribution.note(nbytes=nbytes)
        if self._audit_on:
            tap = self._rx_taps[i]
            if tap is not None:
                tap.observe(item)
            self._op_counts[0][0] += item.num_rows
        t0 = time.perf_counter()
        anchor = obs.device.anchor(
            self._compile_trace, "batch.process",
            task=self.task_info.task_id,
        )
        try:
            await self.ops[0].process_batch(
                item, self.ctxs[0], self.collectors[0], iq.logical_input
            )
        finally:
            anchor.close()
        dt = time.perf_counter() - t0
        self._batch_seconds.observe(dt)
        self._busy_secs.inc(dt)
        self._note_busy(dt, "process")
        return True

    async def _handle_marker(self, item: SignalMessage):
        """Latency marker (types.LatencyMarker): record transit since the
        source stamp, then forward to one destination per out edge — or,
        at a terminal subtask (sink), record end-to-end latency. Markers
        never block alignment and never touch event time; a marker that
        queued behind a blocked input simply carries the alignment delay
        in its transit, which is exactly the latency a record would see."""
        transit = max(0.0, (time.time_ns() - item.marker.stamp_ns) / 1e9)
        self._marker_secs.observe(transit)
        if self.tail.is_terminal:
            self._e2e_secs.observe(transit)
        else:
            await self.tail.forward_marker(item)

    def _track_watermark_lag(self, wm: Watermark):
        """Per-subtask watermark-lag gauge: wall clock minus the effective
        watermark, refreshed at scrape time so a quiesced stream shows its
        lag GROWING instead of pinning the last computed value."""
        if wm.kind != WatermarkKind.EVENT_TIME or wm.timestamp is None:
            return
        if self._wm_lag is None:
            self._wm_lag = WATERMARK_LAG_SECONDS.labels(
                job=self.task_info.job_id, task=self.task_info.task_id
            )
            holder_ref = weakref.ref(self.watermarks)

            def _lag_now():
                holder = holder_ref()
                if holder is None:
                    return None  # runner gone: unregister
                ts = holder.current_nanos()
                if ts is None:
                    return 0.0
                return max(0.0, (time.time_ns() - ts) / 1e9)

            self._wm_lag.set_refresher(_lag_now)
        self._wm_lag.set(max(0.0, (time.time_ns() - wm.timestamp) / 1e9))

    # ------------------------------------------------------------ watermark

    async def _chain_watermark(self, start_idx: int, wm: Watermark):
        """Run a watermark through chain ops [start_idx..); broadcast if it
        survives (reference operator.rs:733-790)."""
        cur: Optional[Watermark] = wm
        for idx in range(start_idx, len(self.ops)):
            cur = await self.ops[idx].handle_watermark(
                cur, self.ctxs[idx], self.collectors[idx]
            )
            if cur is None:
                return
        await self.tail.broadcast(SignalMessage.watermark_of(cur))

    # ------------------------------------------------------------- barriers

    def _barrier_span(self, name: str, barrier, parent: Optional[str] = None):
        """A span anchored to the barrier's epoch trace (NULL when the
        barrier is untraced, so nothing anchors to unrelated contexts)."""
        if not barrier.trace_id:
            return obs.NULL_SPAN
        return obs.start_span(
            name, trace=barrier.trace_id,
            parent=parent or (barrier.span_id or None), cat="runner",
            task=self.task_info.task_id, epoch=barrier.epoch,
        )

    async def _handle_barrier(self, i: int, barrier) -> bool:
        """Align: block input i until all live inputs delivered the barrier
        (reference operator.rs:673-708, 1036-1046)."""
        if self._audit_on:
            # receiver-side epoch cut: aligned inputs deliver no further
            # rows for this epoch once their barrier arrives, so input
            # i's attestation is complete right here
            tap = self._rx_taps[i]
            if tap is not None:
                tap.seal(barrier.epoch)
        if self._current_barrier is None:
            self._current_barrier = barrier
            self._align_started = time.perf_counter()
            self._align_span = self._barrier_span("barrier.align", barrier)
            self.control_tx.put_nowait(
                CheckpointEventResp(
                    self.task_info.task_id,
                    self.task_info.node_id,
                    self.task_info.task_index,
                    barrier.epoch,
                    "started_alignment",
                )
            )
        self._barrier_inputs.add(i)
        self.inputs[i].blocked = True
        await self._maybe_complete_alignment()
        return self._current_barrier is None  # re-arm only if aligned+done

    async def _maybe_complete_alignment(self):
        live = {
            j for j, iq in enumerate(self.inputs) if not iq.finished
        }
        if not live.issubset(self._barrier_inputs):
            return
        barrier = self._current_barrier
        if self._align_started is not None:
            align_secs = time.perf_counter() - self._align_started
            self._align_started = None
            self._align_gauge.set(align_secs)
            self._phase_obs["align"].observe(align_secs)
        self._align_span.set(inputs=len(self.inputs))
        self._align_span.finish()
        self._align_span = obs.NULL_SPAN
        await self._checkpoint_chain(barrier)
        # clear only the barrier we just processed: alignment state is
        # select-loop-confined today, and the guard keeps that true even
        # if a future path re-arms a new epoch under the chain's awaits
        if self._current_barrier is barrier:
            self._current_barrier = None
            self._barrier_inputs.clear()
        # unblocking + re-arming happens in the main loop

    @protocol_effect("worker.capture")
    async def _checkpoint_chain(self, barrier):
        """Capture every chain op's state at the barrier, re-broadcast the
        barrier downstream immediately, then flush (device->host
        materialization + file I/O) in a background task that overlaps
        later epochs' processing. The completed-report is sent when the
        flush lands. Up to state.max_inflight_flushes epochs' flushes may
        be in flight; they run strictly epoch-ordered per subtask (each
        awaits its predecessor), so file-list bookkeeping and completion
        reports stay ordered while barrier cadence is fully decoupled
        from upload time. `then_stop` and commit paths drain completely."""
        await self._drain_pipeline(barrier)
        await self._admit_flush()
        self.control_tx.put_nowait(
            CheckpointEventResp(
                self.task_info.task_id,
                self.task_info.node_id,
                self.task_info.task_index,
                barrier.epoch,
                "started_checkpointing",
            )
        )
        t0 = time.perf_counter()
        cap_span = self._barrier_span("checkpoint.capture", barrier)
        with cap_span:
            from ..serve import seal_op

            captured = []
            commit_data = None
            for idx, (op, ctx) in enumerate(zip(self.ops, self.ctxs)):
                await op.handle_checkpoint(barrier, ctx, self.collectors[idx])
                # StateServe: seal the view's staged rows under this
                # epoch at the same synchronization point the state
                # capture stamps dirty entries — reads at published
                # epoch P then see exactly P's durable view
                seal_op(op, barrier.epoch, ctx.table_manager)
                if ctx.table_manager is not None:
                    captured.append(
                        (
                            idx,
                            ctx.table_manager.capture(
                                barrier.epoch, self.watermarks.current_nanos()
                            ),
                        )
                    )
                if ctx.commit_data is not None:
                    commit_data = ctx.commit_data
                    ctx.commit_data = None
            if commit_data is not None:
                self._await_commit_epoch = barrier.epoch
            # downstream barriers parent to THIS hop's capture span, so the
            # epoch trace follows the operator graph across the data plane
            out_barrier = (
                barrier.with_span(cap_span.span_id)
                if cap_span.recording else barrier
            )
            await self.tail.broadcast(SignalMessage.barrier_of(out_barrier))
        # the broadcast sealed every sender-side tap at this epoch; the
        # receiver taps sealed at alignment — snapshot both (plus the
        # selectivity counts) by value NOW, before the select loop can
        # process post-barrier rows, and let the attestation ride the
        # pipelined completion report
        audit = self._collect_audit(barrier.epoch)
        self._phase_obs["capture"].observe(time.perf_counter() - t0)
        flush_span = self._barrier_span(
            "checkpoint.flush", barrier,
            parent=cap_span.span_id or None,
        )
        flush = asyncio.ensure_future(
            self._flush_and_report(barrier, captured, commit_data,
                                   self.watermarks.current_nanos(),
                                   flush_span, prev=self._last_flush,
                                   audit=audit)
        )
        self._last_flush = flush
        self._inflight_flushes.append(flush)
        self._flush_hwm = max(
            self._flush_hwm,
            sum(1 for t in self._inflight_flushes if not t.done()),
        )
        if barrier.then_stop:
            await self._await_pending_flush()

    def _collect_audit(self, epoch: int) -> Optional[dict]:
        """Assemble this subtask's conservation attestation for one epoch:
        sealed sender (tx) and receiver (rx) edge attestations plus the
        per-operator selectivity ledger, reset for the next epoch. Runs
        synchronously inside the barrier path, so the counts cut exactly
        at the epoch boundary."""
        if not self._audit_on:
            return None
        tx: Dict[str, list] = {}
        for edge in self.tail.edges:
            edge.drain_audit(epoch, tx)
        rx: Dict[str, list] = {}
        for tap in self._rx_taps:
            if tap is not None:
                v = tap.drain(epoch)
                if v is not None:
                    rx[tap.edge] = [v[0], v[1]]
        ops: Dict[str, list] = {}
        flow: Dict[str, str] = {}
        for idx, op in enumerate(self.ops):
            cnt = self._op_counts[idx]
            name = f"{idx}:{op.name}"
            ops[name] = [cnt[0], cnt[1]]
            flow[name] = getattr(op, "flow_class", "any")
            cnt[0] = 0
            cnt[1] = 0
        return {"tx": tx, "rx": rx, "ops": ops, "flow": flow}

    async def _drain_pipeline(self, barrier):
        """Drain every fused segment's staged (double-buffered) batches
        downstream before the barrier's state capture, so the epoch's
        durable state reflects every pre-barrier event and no batch is
        in flight across the checkpoint. Recorded as a
        `runner.pipeline_drain` span per barrier (the rescale drill
        reports drain time per barrier from these spans)."""
        if not self._segment_idxs:
            return
        staged = sum(
            self.ops[i].staged_depth for i in self._segment_idxs
        )
        span = self._barrier_span("runner.pipeline_drain", barrier)
        t0 = time.perf_counter()
        with span:
            for i in self._segment_idxs:
                await self.ops[i].drain(self.ctxs[i], self.collectors[i])
            span.set(staged=staged,
                     drain_ms=round(1e3 * (time.perf_counter() - t0), 3))

    @protocol_effect("worker.admit_flush")
    async def _admit_flush(self):
        """Block until a flush slot is free (bounds capture-ahead: the
        barrier path stalls only once max_inflight epochs are uploading)."""
        self._inflight_flushes = [
            t for t in self._inflight_flushes if not t.done()
        ]
        while len(self._inflight_flushes) >= self._max_inflight:
            await self._inflight_flushes[0]
            self._inflight_flushes = [
                t for t in self._inflight_flushes if not t.done()
            ]

    @protocol_effect("worker.drain_flushes")
    async def _await_pending_flush(self):
        """Drain EVERY in-flight flush (stop/commit/close paths stay
        strictly drained — teardown must never strand an upload)."""
        flushes, self._inflight_flushes = self._inflight_flushes, []
        for flush in flushes:
            await flush
        self._last_flush = None

    @protocol_effect("worker.flush")
    async def _flush_and_report(self, barrier, captured, commit_data,
                                watermark, flush_span=obs.NULL_SPAN,
                                prev: Optional[asyncio.Task] = None,
                                audit: Optional[dict] = None):
        set_task_root(f"flush:{self.task_info.task_id}")
        if prev is not None and not prev.done():
            await asyncio.wait({prev})
        if self._flush_failed:
            # an earlier epoch's flush already failed the task: reporting
            # (or flushing) later epochs would publish state past a hole
            flush_span.set(skipped="predecessor_failed")
            flush_span.finish()
            return
        t0 = time.perf_counter()
        tok = flush_span.attach() if flush_span.recording else None
        try:
            metadata: Dict[str, dict] = {}
            for idx, staged in captured:
                tm = self.ctxs[idx].table_manager
                # the storage-commit leg of the epoch tree: to_thread
                # copies the attached context, so storage.put spans nest
                metadata[f"op{idx}"] = await asyncio.to_thread(
                    tm.flush_captured, barrier.epoch, staged
                )
        except Exception:
            # surface immediately: the controller sees the failure rather
            # than a checkpoint-wait timeout, and nothing is silently lost
            logger.exception(
                "checkpoint flush failed for %s epoch %s",
                self.task_info.task_id, barrier.epoch,
            )
            # monotonic latch: True is the only post-init value, so a
            # concurrent setter is idempotent and the stale entry guard
            # only ever skips work already doomed
            self._flush_failed = True  # arroyolint: disable=RACE002
            flush_span.set(error=traceback.format_exc(limit=3)[:300])
            self.control_tx.put_nowait(
                TaskFailedResp(
                    self.task_info.task_id,
                    self.task_info.node_id,
                    self.task_info.task_index,
                    traceback.format_exc(),
                )
            )
            return
        finally:
            if tok is not None:
                flush_span.detach(tok)
            flush_span.finish()
            flush_dt = time.perf_counter() - t0
            self._phase_obs["flush"].observe(flush_dt)
            # checkpoint flushes overlap later batches (off-barrier
            # uploads): the timeline shows them as their own swimlane
            obs.timeline.note("flush", flush_dt,
                              task=self.task_info.task_id)
        self.control_tx.put_nowait(
            CheckpointCompletedResp(
                self.task_info.task_id,
                self.task_info.node_id,
                self.task_info.task_index,
                barrier.epoch,
                subtask_metadata=metadata,
                watermark=watermark,
                has_commit_data=commit_data is not None,
                commit_data=commit_data,
                audit=audit,
            )
        )

    # -------------------------------------------------------------- control

    async def _handle_control(self, msg):
        if isinstance(msg, CommitMsg):
            await self._handle_commit(msg)
        elif isinstance(msg, StopMsg) and msg.mode == StopMode.IMMEDIATE:
            self._stopping = True
        elif isinstance(msg, LoadCompactedMsg):
            await self._load_compacted(msg)
        elif isinstance(msg, CheckpointMsg) and not self.is_source:
            # checkpoints reach non-sources via in-band barriers; a direct
            # message is a protocol error — ignore but log.
            logger.warning(
                "non-source %s got direct CheckpointMsg", self.task_info.task_id
            )

    @protocol_effect("worker.commit")
    async def _handle_commit(self, msg: CommitMsg):
        span = obs.NULL_SPAN
        if msg.trace_id:
            span = obs.start_span(
                "commit.apply", trace=msg.trace_id,
                parent=msg.span_id or None, cat="runner",
                task=self.task_info.task_id, epoch=msg.epoch,
            )
        with span:
            node_data = msg.committing_data.get(self.task_info.node_id, {})
            for op, ctx in zip(self.ops, self.ctxs):
                await op.handle_commit(msg.epoch, node_data, ctx)
        if (
            self._await_commit_epoch is not None
            and msg.epoch >= self._await_commit_epoch
        ):
            self._await_commit_epoch = None

    async def _load_compacted(self, msg: LoadCompactedMsg):
        for idx, ctx in enumerate(self.ctxs):
            if msg.op_idx is not None and idx != msg.op_idx:
                continue
            if ctx.table_manager is not None:
                await ctx.table_manager.load_compacted(msg.table, msg.paths)

    # ----------------------------------------------------------------- close

    async def _close_chain(self, is_eod: bool):
        # a checkpoint flush may still be in flight; exceptions surface here
        await self._await_pending_flush()
        for idx, (op, ctx) in enumerate(zip(self.ops, self.ctxs)):
            wm = await op.on_close(ctx, self.collectors[idx], is_eod)
            if wm is not None:
                # run through the remainder of the chain, then downstream
                await self._chain_watermark(idx + 1, wm)
