--udf=udfs.py
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE async_output (
  counter BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO async_output
SELECT async_double_negative(counter) FROM impulse_source;
