"""Shared-plan multi-tenancy (ISSUE 16).

Fast-tier proofs of the sharing seams: plan fingerprints are
alias/ordering-normalized (two differently-written jobs over the same
scan config share a mount key); the shared bus is a retained log with
exact cursor slicing, honest late-join refusal, hold-for-expected
retention and shared-fate backpressure; the attribution apportioner
splits a `__shared/<fp>` host's cost across subscribers sum-preserving;
and the E2E mount path — two tenants on one scan produce byte-identical
output vs their solo runs, one tenant's stop never perturbs the other,
and the last detach tears the host down (refcounted release).
"""

import asyncio

import pytest

from arroyo_tpu.config import update
from arroyo_tpu.engine.shared import BUS, SharedChannel
from arroyo_tpu.sql import plan_query
from arroyo_tpu.sql.fingerprint import (
    apply_mount,
    node_fingerprints,
    shareable_source,
)


def pipeline_sql(table="impulse", out="/tmp/unused.json", n=500,
                 rate=1000, start_time=True, realtime=False,
                 replay=False, key_mod=4):
    opts = f"connector = 'impulse', event_rate = '{rate}', " \
           f"message_count = '{n}'"
    if start_time:
        opts += ", start_time = '0'"
    if realtime:
        opts += ", realtime = 'true'"
    if replay:
        opts += ", replay = 'true'"
    return f"""
    CREATE TABLE {table} WITH ({opts});
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{out}', format = 'json',
      type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % {key_mod} as k,
             tumble(interval '100 millisecond') as w, count(*) as cnt
      FROM {table} GROUP BY 1, 2
    );
    """


# -- fingerprints ------------------------------------------------------------


def test_fingerprint_alias_invariant():
    a = shareable_source(plan_query(pipeline_sql(table="events_a")).graph)
    b = shareable_source(
        plan_query(pipeline_sql(table="my_other_name")).graph
    )
    assert a is not None and b is not None
    assert a.fingerprint == b.fingerprint


def test_fingerprint_differs_on_source_config():
    a = shareable_source(plan_query(pipeline_sql(rate=1000)).graph)
    b = shareable_source(plan_query(pipeline_sql(rate=2000)).graph)
    assert a.fingerprint != b.fingerprint


def test_fingerprint_ignores_downstream_pipeline():
    """Tenants with different queries over the same scan share the key."""
    a = shareable_source(plan_query(pipeline_sql(key_mod=4)).graph)
    b = shareable_source(plan_query(pipeline_sql(key_mod=8)).graph)
    assert a.fingerprint == b.fingerprint


def test_node_fingerprints_cover_graph():
    g = plan_query(pipeline_sql()).graph
    fps = node_fingerprints(g)
    assert set(fps) == set(g.nodes)
    assert len(set(fps.values())) == len(fps)  # distinct per node here


def test_shareable_requires_deterministic_replay():
    # wall-clock event time (no start_time) is not replayable
    assert shareable_source(
        plan_query(pipeline_sql(start_time=False)).graph) is None
    # realtime without replay stamps wall-clock event time
    assert shareable_source(
        plan_query(pipeline_sql(realtime=True)).graph) is None
    # realtime + replay re-synthesizes event time: shareable
    assert shareable_source(
        plan_query(pipeline_sql(realtime=True, replay=True)).graph
    ) is not None


def test_apply_mount_rewrites_in_place():
    g = plan_query(pipeline_sql()).graph
    scan = shareable_source(g)
    shape = (len(g.nodes), len(g.edges))
    mount = {"node_id": scan.node_id, "fingerprint": scan.fingerprint,
             "connector": scan.connector}
    apply_mount(g, mount)
    op = g.nodes[scan.node_id].chain[0]
    assert op.config["connector"] == "mounted"
    assert op.config["fingerprint"] == scan.fingerprint
    assert op.config["schema"] is not None
    assert (len(g.nodes), len(g.edges)) == shape
    apply_mount(g, mount)  # idempotent
    assert g.nodes[scan.node_id].chain[0].config["connector"] == "mounted"


# -- the shared bus ----------------------------------------------------------


class Rows:
    """Offset-carrying stand-in batch: slice() keeps row identity."""

    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    @property
    def num_rows(self):
        return self.hi - self.lo

    def slice(self, offset, length=None):
        hi = self.hi if length is None else self.lo + offset + length
        return Rows(self.lo + offset, hi)

    def span(self):
        return (self.lo, self.hi)


def test_bus_late_joiner_and_cursor_slicing():
    async def go():
        ch = SharedChannel("fp-slice", max_retained_rows=10_000)
        await ch.publish(0, Rows(0, 100))
        await ch.publish(100, Rows(100, 250))
        assert await ch.attach("t1", 0)
        assert [b.span() for b in await ch.read("t1")] \
            == [(0, 100), (100, 250)]
        # late joiner lands mid-batch: first delivered row is exactly
        # its cursor row
        assert await ch.attach("t2", 150)
        assert [b.span() for b in await ch.read("t2")] == [(150, 250)]
        assert ch.consumed == {"t1": 250, "t2": 100}
        # EOS: drained readers see None, not a hang
        await ch.close()
        assert await ch.read("t1") is None

    asyncio.run(go())


def test_bus_rewind_on_host_restart():
    async def go():
        ch = SharedChannel("fp-rewind", max_retained_rows=10_000)
        await ch.publish(0, Rows(0, 100))
        await ch.publish(100, Rows(100, 200))
        # host restarts from its epoch at offset 100 and re-publishes
        await ch.publish(100, Rows(100, 180))
        assert ch.end == 180
        assert [s for s, _b in ch.log] == [0, 100]
        # a fresh reader replays the rewound log seamlessly
        assert await ch.attach("t", 0)
        assert [b.span() for b in await ch.read("t")] \
            == [(0, 100), (100, 180)]

    asyncio.run(go())


def test_bus_refuses_mount_below_base():
    async def go():
        ch = SharedChannel("fp-trim", max_retained_rows=100)
        for i in range(6):
            await ch.publish(i * 50, Rows(i * 50, (i + 1) * 50))
        # zero subscribers: retention kept a cap-sized tail
        assert ch.base == 200
        assert not await ch.attach("late", 0)  # caller spawns unshared
        assert await ch.attach("ok", 250)

    asyncio.run(go())


def test_bus_holds_retention_for_expected_mounts():
    async def go():
        ch = SharedChannel("fp-expect", max_retained_rows=100)
        ch.expect("t")
        for i in range(6):
            await ch.publish(i * 50, Rows(i * 50, (i + 1) * 50))
        assert ch.base == 0  # full log held for the pending mount
        assert await ch.attach("t", 0)
        assert sum(b.num_rows for b in await ch.read("t")) == 300

    asyncio.run(go())


def test_bus_fresh_channel_advances_base_for_restored_host():
    async def go():
        # durable host restores mid-stream onto a NEW bus incarnation:
        # rows below its restore offset were never retained here
        ch = SharedChannel("fp-mid", max_retained_rows=10_000)
        await ch.publish(500, Rows(500, 600))
        assert ch.base == 500
        assert not await ch.attach("t0", 0)  # honest refusal, not a gap

    asyncio.run(go())


def test_bus_backpressure_is_shared_fate():
    async def go():
        ch = SharedChannel("fp-bp", max_retained_rows=100)
        assert await ch.attach("slow", 0)
        await ch.publish(0, Rows(0, 50))
        blocked = asyncio.ensure_future(ch.publish(50, Rows(50, 150)))
        await asyncio.sleep(0.05)
        assert not blocked.done()  # slowest reader throttles the scan
        assert sum(b.num_rows for b in await ch.read("slow")) == 150
        await asyncio.wait_for(blocked, 1.0)

    asyncio.run(go())


def test_bus_epoch_bookkeeping():
    ch = SharedChannel("fp-epoch")
    ch.note_host_capture(1, 100)
    ch.note_host_capture(2, 300)
    ch.note_tenant_capture("t", 1, 80)
    ch.note_tenant_capture("t", 2, 300)
    # only PUBLISHED tenant epochs are durable restore points
    assert ch.tenant_durable_position("t", 0) == 0
    assert ch.tenant_durable_position("t", 1) == 80
    assert ch.tenant_durable_position("t", 2) == 300
    ch.set_floor("t", 80)
    ch.set_floor("t", 40)  # monotone
    assert ch.floors["t"] == 80


# -- attribution apportioning ------------------------------------------------


def test_shared_host_cost_apportioned_sum_preserving():
    from arroyo_tpu.obs.attribution import Accounting

    fp = "fp-attr"
    host = "__shared/" + fp
    ch = BUS.get_or_create(fp, 1000)
    try:
        ch.consumed.update({"a": 300, "b": 100})
        acct = Accounting()
        acct.note(job=host, busy=4.0, device=2.0, dispatches=7,
                  nbytes=1001)
        acct.note(job="a", busy=1.0)
        acct.flush()
        # pro-rata by consumed rows (a:b = 3:1), sum-preserving
        assert acct._totals["a"]["busy"] == pytest.approx(1.0 + 3.0)
        assert acct._totals["b"]["busy"] == pytest.approx(1.0)
        assert acct._totals["a"]["device"] \
            + acct._totals["b"]["device"] == pytest.approx(2.0)
        assert acct._totals["a"]["dispatches"] \
            + acct._totals["b"]["dispatches"] == 7
        assert acct._totals["a"]["bytes"] \
            + acct._totals["b"]["bytes"] == 1001
        # the host bucket is fully reassigned: no __shared/* escape from
        # the per-tenant coverage accounting
        assert host not in acct._totals
        assert not any(j.startswith("__shared/")
                       for j in acct.summary()["jobs"])

        # second interval: no rows moved, but readers are attached —
        # idle scan cost splits evenly instead of escaping
        ch.cursors.update({"a": 400, "b": 400})
        acct.note(job=host, busy=1.0)
        acct.flush()
        assert acct._totals["a"]["busy"] \
            + acct._totals["b"]["busy"] == pytest.approx(6.0)
    finally:
        BUS.drop(fp)


# -- E2E: mount, per-tenant isolation, refcounted teardown -------------------


def canonical(path):
    with open(path) as f:
        return sorted(line for line in f.read().splitlines() if line)


def test_shared_mount_end_to_end(tmp_path):
    """Two tenants mount one impulse scan; both outputs are
    byte-identical to unshared solo runs of the same SQL; the hidden
    host is torn down by the last tenant's release."""
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    def sql(tag, enabled_dir):
        return pipeline_sql(out=str(tmp_path / f"{enabled_dir}-{tag}.json"),
                            n=800, rate=100_000)

    async def fleet(tag, enabled):
        fps = []
        with update(sharing={"enabled": enabled},
                    pipeline={"checkpointing": {"interval": 0.3,
                                                "storage_url": ""}}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                for j in range(2):
                    await c.submit_job(f"t{j}", sql=sql(f"t{j}", tag),
                                       n_workers=1, parallelism=1)
                for j in range(2):
                    st = await c.wait_for_state(
                        f"t{j}", JobState.FINISHED, JobState.FAILED,
                        timeout=60,
                    )
                    assert st == JobState.FINISHED, c.jobs[f"t{j}"].failure
                fps = [c.jobs[f"t{j}"].shared_fp for j in range(2)]
                # refcounted teardown: the finished tenants' releases
                # drained the host and dropped the channel
                deadline = asyncio.get_event_loop().time() + 10
                while c.sharing.hosts and \
                        asyncio.get_event_loop().time() < deadline:
                    await asyncio.sleep(0.1)
                assert not c.sharing.hosts
            finally:
                await c.stop()
        return fps

    fps = asyncio.run(fleet("sh", True))
    assert fps[0] and fps[0] == fps[1], fps
    assert BUS.get(fps[0]) is None
    solo_fps = asyncio.run(fleet("solo", False))
    assert not any(solo_fps)
    for j in range(2):
        shared = canonical(tmp_path / f"sh-t{j}.json")
        solo = canonical(tmp_path / f"solo-t{j}.json")
        assert shared and shared == solo, f"t{j} diverged under sharing"


def test_shared_tenant_stop_leaves_cotenant_intact(tmp_path):
    """Stopping one mounted tenant mid-run must not perturb the other:
    the survivor's output stays byte-identical to its solo run, and the
    host keeps running until the LAST tenant detaches."""
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.controller.state_machine import JobState

    def sql(tag):
        # wall-paced replay (~2.5 s): the stop lands mid-stream
        return pipeline_sql(out=str(tmp_path / f"{tag}.json"), n=2500,
                            rate=1000, realtime=True, replay=True)

    async def fleet():
        with update(sharing={"enabled": True},
                    pipeline={"checkpointing": {"interval": 0.3,
                                                "storage_url": ""}}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job("keep", sql=sql("keep"), n_workers=1,
                                   parallelism=1)
                await c.submit_job("gone", sql=sql("gone"), n_workers=1,
                                   parallelism=1)
                await asyncio.sleep(0.8)
                status = c.sharing.status()
                assert status and list(status.values())[0]["refcount"] == 2
                await c.stop_job("gone", "immediate")
                st = await c.wait_for_state(
                    "keep", JobState.FINISHED, JobState.FAILED, timeout=60
                )
                assert st == JobState.FINISHED, c.jobs["keep"].failure
                # the survivor held the host alive past the co-tenant's
                # stop; its own release then tears everything down
            finally:
                await c.stop()

    async def solo():
        with update(pipeline={"checkpointing": {"interval": 0.3,
                                                "storage_url": ""}}):
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job("solo", sql=sql("solo"), n_workers=1,
                                   parallelism=1)
                st = await c.wait_for_state(
                    "solo", JobState.FINISHED, JobState.FAILED, timeout=60
                )
                assert st == JobState.FINISHED, c.jobs["solo"].failure
            finally:
                await c.stop()

    asyncio.run(fleet())
    asyncio.run(solo())
    keep = canonical(tmp_path / "keep.json")
    assert keep and keep == canonical(tmp_path / "solo.json"), \
        "co-tenant stop perturbed the survivor's output"
