"""TLS material for the control plane (gRPC) and data plane (TCP).

Capability parity with the reference's TLS support
(/root/reference/crates/arroyo-server-common/src/lib.rs tls +
config.rs TlsConfig): one config section supplies cert/key/ca for both
transports. An explicit `ca` trust root is REQUIRED when TLS is enabled —
cluster planes authenticate against it (mutual TLS: servers also require
client certificates signed by it), never against system roots, so both
planes behave identically and there is no encrypted-but-unauthenticated
mode. Connections dial workers by IP, so hostname verification pins the
configured `server_name` DNS SAN.
"""

from __future__ import annotations

import ssl
from functools import lru_cache
from typing import Optional, Tuple

from ..config import config


def _settings() -> Optional[tuple]:
    """Validated (cert, key, ca, server_name) from config, or None when
    TLS is off. Hashable so per-connection callers hit the context cache."""
    t = config().tls
    if not t.enabled:
        return None
    if not (t.cert and t.key and t.ca):
        raise ValueError(
            "tls.enabled requires tls.cert, tls.key and tls.ca — cluster "
            "planes authenticate against the explicit CA bundle (no "
            "system-trust mode)"
        )
    return (t.cert, t.key, t.ca, t.server_name)


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def grpc_server_credentials():
    """grpc.ssl_server_credentials from config, or None when TLS is off."""
    s = _settings()
    if s is None:
        return None
    cert, key, ca, _ = s
    import grpc

    return grpc.ssl_server_credentials(
        [(_read(key), _read(cert))],
        root_certificates=_read(ca),
        require_client_auth=True,
    )


def grpc_channel_credentials() -> Tuple[Optional[object], list]:
    """(channel credentials, channel options) for a client, or (None, [])
    when TLS is off."""
    s = _settings()
    if s is None:
        return None, []
    cert, key, ca, server_name = s
    import grpc

    creds = grpc.ssl_channel_credentials(
        root_certificates=_read(ca),
        private_key=_read(key),
        certificate_chain=_read(cert),
    )
    return creds, [("grpc.ssl_target_name_override", server_name)]


@lru_cache(maxsize=8)
def _server_context(cert: str, key: str, ca: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    ctx.load_verify_locations(ca)
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


@lru_cache(maxsize=8)
def _client_context(cert: str, key: str, ca: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(ca)
    ctx.load_cert_chain(cert, key)
    return ctx


def data_server_context() -> Optional[ssl.SSLContext]:
    s = _settings()
    if s is None:
        return None
    cert, key, ca, _ = s
    return _server_context(cert, key, ca)


def data_client_context() -> Tuple[Optional[ssl.SSLContext], Optional[str]]:
    """(client ssl context, server_hostname) for the data plane. Contexts
    are cached per (cert, key, ca) so the O(edges x parallelism) senders
    of a shuffle don't re-read key material per connection."""
    s = _settings()
    if s is None:
        return None, None
    cert, key, ca, server_name = s
    return _client_context(cert, key, ca), server_name
