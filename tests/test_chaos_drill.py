"""The full exactly-once acceptance drills (ISSUE 2): worker SIGKILL
mid-window + data-plane drop + manifest CAS loss across three goldens
(windowed aggregate, join, updating query), plus the transactional-kafka
drill. Slow: each kill costs a heartbeat-timeout detection wait; the
default suite runs the fast smoke drill in test_chaos.py instead."""

import pytest

from arroyo_tpu import chaos
from arroyo_tpu.chaos import drill

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    chaos.clear()
    yield
    chaos.clear()


@pytest.mark.parametrize("query", drill.DEFAULT_DRILL_QUERIES)
def test_standard_drill(query, tmp_path):
    """(a) SIGKILL a worker mid-window, (b) drop a data-plane connection,
    (c) fail a manifest CAS write — output identical to the fault-free
    run, every scheduled fault fired."""
    res = drill.run_drill(query, seed=20260804, workdir=str(tmp_path))
    assert res.passed, f"{query}: {res.error}\nfired: {res.fired}"
    assert res.restarts >= 2  # kill + at least one of drop/CAS recovered
    assert res.comparable_log == res.expected_log


def test_same_seed_reproduces_fired_log(tmp_path):
    """The acceptance reproducibility clause, run for real: two faulted
    runs under the same chaos seed produce the same comparable
    fired-fault log."""
    a = drill.run_drill(
        drill.DEFAULT_DRILL_QUERIES[0], seed=777,
        workdir=str(tmp_path / "a"),
    )
    b = drill.run_drill(
        drill.DEFAULT_DRILL_QUERIES[0], seed=777,
        workdir=str(tmp_path / "b"),
    )
    assert a.passed, a.error
    assert b.passed, b.error
    assert a.comparable_log == b.comparable_log
    # and a different seed schedules a different log
    assert (
        drill.standard_plan(777).expected_log()
        != drill.standard_plan(778).expected_log()
    )


def test_rescale_drill_exactly_once(tmp_path):
    """ISSUE 5 satellite: a worker SIGKILL lands mid-autoscaler-triggered
    rescale (the stop checkpoint fails, the job recovers, the autoscaler
    re-decides) and a later rescale fails between its durable stop
    checkpoint and the reschedule (recovery must come back at the NEW
    parallelism) — canonical output byte-identical to the fault-free run,
    every scheduled rescale.* fault fired, decision audit log written."""
    res = drill.run_rescale_drill(seed=20260804, workdir=str(tmp_path))
    assert res.passed, f"{res.error}\nfired: {res.fired}"
    assert res.restarts >= 1  # the mid-rescale kill forced a recovery
    fired_points = {f["point"] for f in res.fired}
    assert {"rescale.stop_delay", "rescale.reschedule_fail",
            "worker.kill"} <= fired_points
    assert (tmp_path / "autoscale_decisions.json").exists()


def test_pipeline_drill_staged_batches_survive_kill(tmp_path):
    """ISSUE 14 acceptance: a fused stateless segment with the two-deep
    staging pipeline on takes a worker SIGKILL mid-flight — canonical
    output byte-identical to the UNFUSED fault-free run (no staged event
    lost or duplicated), and the runner.pipeline_drain spans prove a
    barrier actually drained a staged batch."""
    res = drill.run_pipeline_drill(seed=20260804, workdir=str(tmp_path))
    assert res.passed, f"{res.error}\nextras: {res.extras}"
    assert res.restarts >= 1
    assert res.extras["pipeline_drain_staged_max"] >= 1
    assert res.extras["barriers_with_staged"] >= 1


def test_state_bloat_drill_flat_checkpoints(tmp_path):
    """ISSUE 8 acceptance (ROADMAP item 4): session state grows ~10x
    during the run, a worker is SIGKILLed mid-upload with storage
    latency widening the in-flight flush window — output byte-identical
    to the fault-free run AND checkpoint capture time + per-epoch delta
    bytes stay ~flat as state grows (a full-snapshot design shows ~10x
    growth on both)."""
    res = drill.run_state_bloat_drill(seed=20260804, workdir=str(tmp_path))
    assert res.passed, f"{res.error}\nextras: {res.extras}"
    assert res.restarts >= 1  # the mid-upload SIGKILL forced a recovery
    assert res.extras["epochs_measured"] >= 6, res.extras
    assert (
        res.extras["capture_ms_late_median"]
        <= 2.0 * res.extras["capture_ms_early_median"] + 2.0
    ), res.extras


def test_kafka_exactly_once_drill(tmp_path):
    """VERDICT r5 item 8 wiring: the protocol-shaped kafka fake (fenced
    producer epochs, abortable transactions) driven through the embedded
    cluster under worker kill + manifest CAS loss — the transactional
    sink's read-committed output carries every row exactly once."""
    res = drill.run_kafka_drill(seed=20260804, workdir=str(tmp_path))
    assert res.passed, f"{res.error}\nfired: {res.fired}"
    assert res.restarts >= 1
