"""Operator and source contexts: watermark tracking, state access, metrics.

Capability parity with the reference's OperatorContext/SourceContext
(/root/reference/crates/arroyo-operator/src/context.rs): WatermarkHolder
min-merges per-input watermarks (:35-89) with idle handling; SourceContext
buffers rows by size+time before emitting (:219-437) and rate-limits user
error reports.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, TYPE_CHECKING

import pyarrow as pa

from ..metrics import ERRORS
from ..types import LatencyMarker, TaskInfo, Watermark, WatermarkKind
from ..schema import StreamSchema

if TYPE_CHECKING:
    from ..state.table_manager import TableManager


class WatermarkHolder:
    """Tracks the last watermark per input queue; the operator's effective
    watermark is the min over non-idle inputs (all-idle → Idle)."""

    def __init__(self, n_inputs: int):
        self.watermarks: List[Optional[Watermark]] = [None] * max(1, n_inputs)

    def set(self, input_idx: int, wm: Watermark) -> Optional[Watermark]:
        """Record a new watermark; returns the new combined watermark if it
        changed the operator's effective watermark, else None."""
        before = self.combined()
        self.watermarks[input_idx] = wm
        after = self.combined()
        if after is None:
            return None
        if before is None or before != after:
            return after
        return None

    def combined(self) -> Optional[Watermark]:
        # every input must have reported at least once
        if any(w is None for w in self.watermarks):
            return None
        active = [w.timestamp for w in self.watermarks
                  if w.kind == WatermarkKind.EVENT_TIME]
        if not active:
            return Watermark.idle()
        return Watermark.event_time(min(active))

    def current_nanos(self) -> Optional[int]:
        c = self.combined()
        if c is None or c.is_idle():
            return None
        return c.timestamp


@dataclasses.dataclass
class ErrorReporter:
    """Rate-limited non-fatal error reporting (reference: bad-data handling
    in SourceCollector)."""

    task_info: TaskInfo
    max_per_interval: int = 10
    interval: float = 10.0
    _count: int = 0
    _window_start: float = 0.0
    errors: List[str] = dataclasses.field(default_factory=list)

    def report(self, message: str, details: str = ""):
        ERRORS.labels(job=self.task_info.job_id,
                      task=self.task_info.task_id).inc()
        now = time.monotonic()
        if now - self._window_start > self.interval:
            self._window_start = now
            self._count = 0
        self._count += 1
        if self._count <= self.max_per_interval:
            self.errors.append(f"{message}: {details}" if details else message)
            if len(self.errors) > 100:
                del self.errors[:50]


class OperatorContext:
    """Per-(operator, subtask) context handed to every operator callback."""

    def __init__(
        self,
        task_info: TaskInfo,
        in_schemas: List[StreamSchema],
        out_schema: Optional[StreamSchema],
        watermarks: WatermarkHolder,
        table_manager: Optional["TableManager"] = None,
    ):
        self.task_info = task_info
        self.in_schemas = in_schemas
        self.out_schema = out_schema
        self.watermarks = watermarks
        self.table_manager = table_manager
        self.error_reporter = ErrorReporter(task_info)
        # sink commit payloads stashed at checkpoint, committed on CommitMsg
        self.commit_data: Optional[bytes] = None
        self._runner = None  # back-ref set by SubtaskRunner

    def last_watermark(self) -> Optional[int]:
        return self.watermarks.current_nanos()

    async def table(self, name: str):
        assert self.table_manager is not None, "operator has no state tables"
        return await self.table_manager.get_table(name)


class SourceContext(OperatorContext):
    """Adds source-side row buffering: rows accumulate until batch-size or
    linger-time flush (reference SourceCollector::should_flush)."""

    def __init__(self, *args, batch_size: int = 512, linger: float = 0.1, **kw):
        super().__init__(*args, **kw)
        self.batch_size = batch_size
        self.linger = linger
        self._buffer: List[Dict[str, Any]] = []
        self._buffer_started: Optional[float] = None
        self._runner = None  # set by SubtaskRunner before run()
        # latency-marker stamping cadence (obs.latency_marker_interval,
        # captured at build time — contexts are constructed under the
        # config scope the job runs with); 0 disables
        from ..config import config

        self._marker_interval = float(config().obs.latency_marker_interval)
        self._marker_last: Optional[float] = None
        self._marker_seq = 0

    async def check_control(self, collector):
        """Drain pending control messages (checkpoint barriers, stop); call
        between emissions. Returns a SourceFinishType when the source should
        return, else None."""
        assert self._runner is not None
        return await self._runner.source_handle_control(collector)

    def note_busy(self, dt: float) -> None:
        """Source busy accounting: generation/ingest time EXCLUDING
        pacing sleeps feeds this subtask's arroyo_worker_busy_seconds
        (and the per-tenant attributed mirror), so the autoscaler's DS2
        policy can size sources — busy ratio ~1 means the source cannot
        hold wall pace at its current parallelism (ISSUE 15 source
        elasticity)."""
        if dt <= 0:
            return
        r = self._runner
        if r is not None and getattr(r, "_busy_secs", None) is not None:
            r._busy_secs.inc(dt)
            from .. import obs

            obs.attribution.note(busy=dt)

    def buffer_row(self, row: Dict[str, Any]):
        if self._buffer_started is None:
            self._buffer_started = time.monotonic()
        self._buffer.append(row)

    def should_flush(self) -> bool:
        if not self._buffer:
            return False
        if len(self._buffer) >= self.batch_size:
            return True
        return (time.monotonic() - (self._buffer_started or 0)) >= self.linger

    def next_latency_marker(self) -> Optional[LatencyMarker]:
        """A fresh wall-clock-stamped marker when the configured stamping
        interval elapsed (the first call always stamps, so even bounded
        test pipelines ship at least one marker per source), else None."""
        if self._marker_interval <= 0:
            return None
        now = time.monotonic()
        if (self._marker_last is not None
                and now - self._marker_last < self._marker_interval):
            return None
        self._marker_last = now
        self._marker_seq += 1
        return LatencyMarker(
            self.task_info.task_id, self._marker_seq, time.time_ns()
        )

    def take_buffer(self) -> Optional[pa.RecordBatch]:
        if not self._buffer:
            return None
        rows, self._buffer = self._buffer, []
        self._buffer_started = None
        assert self.out_schema is not None
        cols = {name: [] for name in self.out_schema.names}
        for row in rows:
            for name in cols:
                cols[name].append(row.get(name))
        arrays = [
            pa.array(cols[f.name], type=f.type) for f in self.out_schema.schema
        ]
        return pa.RecordBatch.from_arrays(arrays, schema=self.out_schema.schema)
