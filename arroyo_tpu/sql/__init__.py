"""SQL frontend: lexer -> parser -> logical planner -> LogicalGraph.

Capability parity with the reference's arroyo-planner crate
(/root/reference/crates/arroyo-planner/src/lib.rs:789
parse_and_get_arrow_program), rebuilt from scratch in Python (the reference
sits on Rust DataFusion, unavailable here): a recursive-descent SQL parser,
a vectorized expression compiler over pyarrow.compute kernels, and a
planner that rewrites SELECTs into the engine's operator DAG (source +
watermark, projections/filters, window TVF aggregates, joins, sinks).
"""

from .planner import SchemaProvider, plan_query  # noqa: F401
