"""StateBackend — the engine-facing checkpoint store.

Capability parity with the reference's ParquetBackend + checkpoint metadata
flow (/root/reference/crates/arroyo-state/src/parquet.rs:25-171 and
arroyo-worker/src/job_controller/checkpoint_state.rs): owns the storage
provider + protocol paths, writes per-(node, op, table, subtask) data files,
assembles/publishes the epoch manifest from subtask reports, resolves
restore manifests, compacts small per-epoch files, and retires old epochs.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import pyarrow as pa

from ..utils.logging import get_logger
from . import protocol
from .protocol import ProtocolPaths
from .storage import StorageProvider

logger = get_logger("state")


class StateBackend:
    def __init__(self, storage_url: str, job_id: str):
        self.storage = StorageProvider(storage_url)
        self.paths = ProtocolPaths(job_id)
        self.job_id = job_id
        self.generation: Optional[int] = None
        self.restore_manifest: Optional[Dict[str, Any]] = None

    # -- lifecycle ----------------------------------------------------------

    def initialize(self, restore_epoch: Optional[int] = None) -> "StateBackend":
        """Claim a generation; resolve the restore manifest (latest durable
        checkpoint, or a specific epoch)."""
        self.generation = protocol.initialize_generation(self.storage, self.paths)
        if restore_epoch is not None:
            self.restore_manifest = protocol.load_manifest(
                self.storage, self.paths, restore_epoch
            )
            if self.restore_manifest is None:
                raise ValueError(f"no checkpoint manifest for epoch {restore_epoch}")
        else:
            self.restore_manifest = protocol.resolve_latest(self.storage, self.paths)
        return self

    @property
    def restore_epoch(self) -> Optional[int]:
        return self.restore_manifest["epoch"] if self.restore_manifest else None

    # -- data files ---------------------------------------------------------

    def global_blob_path(self, epoch: int, node_id: int, op_idx: int,
                         table: str, subtask: int) -> str:
        """Deterministic (and generation-fenced) path for an epoch's
        global-table blob — computable at CAPTURE time so the manifest
        chain can be extended before the flush lands."""
        return self.paths.data_file(
            epoch, node_id, op_idx, table, subtask, "bin",
            gen=self.generation,
        )

    def write_blob(self, path: str, blob: bytes) -> str:
        self.storage.put(path, blob)
        return path

    def write_global_blob(self, epoch: int, node_id: int, op_idx: int,
                          table: str, subtask: int, blob: bytes) -> str:
        path = self.global_blob_path(epoch, node_id, op_idx, table, subtask)
        self.storage.put(path, blob)
        return path

    def write_time_key_file(self, epoch: int, node_id: int, op_idx: int,
                            table: str, subtask: int,
                            data: pa.Table,
                            timestamp_field: str = "_timestamp"
                            ) -> Dict[str, Any]:
        path = self.paths.data_file(
            epoch, node_id, op_idx, table, subtask, "parquet",
            gen=self.generation,
        )
        size = self.storage.write_parquet(path, data)
        ts_col = data.column(timestamp_field).cast(pa.int64())
        import pyarrow.compute as pc

        return {
            "path": path,
            "bytes": size,
            "rows": data.num_rows,
            "min_ts": pc.min(ts_col).as_py() or 0,
            "max_ts": pc.max(ts_col).as_py() or 0,
        }

    def read_blob(self, path: str) -> Optional[bytes]:
        return self.storage.get(path)

    def read_parquet(self, path: str):
        return self.storage.read_parquet(path)

    # -- manifest assembly --------------------------------------------------

    def publish_checkpoint(
        self,
        epoch: int,
        task_reports: Dict[str, Any],  # task_id -> CheckpointCompletedResp
        finished_tasks: Any = (),  # task_ids finished before the barrier
    ) -> Dict[str, Any]:
        tasks = {}
        committing: Dict[str, Any] = {}
        watermarks = {}
        for task_id, resp in task_reports.items():
            tasks[task_id] = {
                "node_id": resp.node_id,
                "subtask": resp.subtask_index,
                "op_tables": resp.subtask_metadata,
            }
            watermarks[task_id] = resp.watermark
            if getattr(resp, "commit_data", None):
                cd = resp.commit_data
                if isinstance(cd, bytes):
                    cd = {"__hex__": cd.hex()}
                committing.setdefault(str(resp.node_id), {})[
                    str(resp.subtask_index)
                ] = cd
        manifest = {
            "job_id": self.job_id,
            "tasks": tasks,
            "watermarks": watermarks,
            "committing": committing,
            "finished_tasks": sorted(finished_tasks),
            "created_at": time.time(),
        }
        protocol.publish_checkpoint(
            self.storage, self.paths, self.generation, epoch, manifest
        )
        if committing:
            protocol.prepare_commit(
                self.storage, self.paths, self.generation, epoch, committing
            )
        return manifest

    def claim_commit(self, epoch: int) -> bool:
        return protocol.claim_commit(
            self.storage, self.paths, self.generation, epoch
        )

    def latest_manifest(self) -> Optional[Dict[str, Any]]:
        return protocol.resolve_latest(self.storage, self.paths)

    # -- restore lookups ----------------------------------------------------

    def tables_for(
        self, node_id: int, op_idx: int
    ) -> List[Dict[str, Any]]:
        """All subtasks' table metadata for (node, op) in the restore
        manifest: [{subtask, tables: {name: meta}}]."""
        if not self.restore_manifest:
            return []
        out = []
        for task in self.restore_manifest["tasks"].values():
            if task["node_id"] != node_id:
                continue
            op_tables = task["op_tables"].get(f"op{op_idx}")
            if op_tables:
                out.append({"subtask": task["subtask"], "tables": op_tables})
        return out

    def restore_watermark(self, task_id: str) -> Optional[int]:
        """The watermark retention-pruning uses on restore. For a task id
        that didn't exist pre-restart (rescale), fall back to the node's
        minimum checkpointed watermark — the safe lower bound that still
        prunes emitted/expired rows from the re-read key ranges."""
        if not self.restore_manifest:
            return None
        wms = self.restore_manifest["watermarks"]
        wm = wms.get(task_id)
        if wm is not None:
            return wm
        node = task_id.split("-")[0]
        peers = [
            w for t, w in wms.items()
            if w is not None and t.split("-")[0] == node
        ]
        return min(peers) if peers else None

    # -- compaction ---------------------------------------------------------

    def compact_time_key_files(
        self, epoch: int, node_id: int, op_idx: int, table: str,
        files: List[dict],
    ) -> Optional[dict]:
        """Merge small per-epoch parquet files into one (reference
        parquet.rs:171 compact_operator). Returns the new file's metadata;
        old files stay until their manifests are GC'd."""
        if len(files) < 2:
            return None
        tables = []
        for f in files:
            t = self.storage.read_parquet(f["path"])
            if t is not None:
                tables.append(t)
        if not tables:
            return None
        merged = pa.concat_tables(tables, promote_options="default")
        path = self.paths.compacted_file(epoch, node_id, op_idx, table)
        size = self.storage.write_parquet(path, merged)
        return {
            "path": path,
            "bytes": size,
            "rows": merged.num_rows,
            "min_ts": min(f["min_ts"] for f in files),
            "max_ts": max(f["max_ts"] for f in files),
        }

    def compact_epoch(self, epoch: int, manifest: Dict[str, Any]) -> List[dict]:
        """Scan a just-published manifest for (node, op, table) groups whose
        carried-forward file count reached the configured threshold and merge
        each into one compacted file (reference: controller-driven compaction,
        compaction.rs + ControlMessage::LoadCompacted). Returns swap
        instructions [{node_id, op_idx, table, files}] for the workers; the
        swapped references land in the NEXT manifest, old files stay durable
        until retire_unreferenced() sees nothing pointing at them."""
        from ..config import config as get_config

        cfg = get_config().pipeline.checkpointing
        if not cfg.compaction_enabled or not manifest:
            return []
        groups: Dict[tuple, Dict[str, dict]] = {}
        for task in manifest.get("tasks", {}).values():
            node_id = task["node_id"]
            for op_key, tables in (task.get("op_tables") or {}).items():
                for tname, meta in tables.items():
                    if meta.get("kind") != "time_key":
                        continue
                    g = groups.setdefault(
                        (node_id, int(op_key[2:]), tname), {}
                    )
                    for f in meta.get("files", []):
                        g[f["path"]] = f
        out = []
        for (node_id, op_idx, tname), by_path in groups.items():
            files = list(by_path.values())
            if len(files) < cfg.compaction_epoch_threshold:
                continue
            merged = self.compact_time_key_files(
                epoch, node_id, op_idx, tname, files
            )
            if merged is not None:
                logger.info(
                    "compacted %d files -> %s (node %d op %d table %s)",
                    len(files), merged["path"], node_id, op_idx, tname,
                )
                out.append({
                    "node_id": node_id, "op_idx": op_idx, "table": tname,
                    "files": [merged],
                })
        return out

    def retire_unreferenced(self):
        """GC checkpoint epochs older than the latest manifest whose data
        directories contain no file the manifest still references, plus
        superseded compacted files no manifest points at anymore
        (reference gc.rs — safe min_epoch derived from live references)."""
        manifest = self.latest_manifest()
        if not manifest:
            return
        referenced = set()
        for task in manifest.get("tasks", {}).values():
            for tables in (task.get("op_tables") or {}).values():
                for meta in tables.values():
                    if meta.get("path"):
                        referenced.add(meta["path"])
                    # incremental global tables: the whole blob chain
                    # (base + deltas across epochs) stays live until a
                    # rebase truncates it
                    for f in meta.get("chain", []):
                        referenced.add(f["path"])
                    for f in meta.get("files", []):
                        referenced.add(f["path"])
        latest_epoch = manifest.get("epoch")
        if latest_epoch is None:
            return
        for e in self._known_epochs():
            if e >= latest_epoch:
                continue
            prefix = self.paths.checkpoint_dir(e)
            if any(r.startswith(prefix) for r in referenced):
                continue
            self.storage.delete_directory(prefix)
        # a re-merge supersedes the previous compacted file: delete merges
        # the latest manifest no longer references. Merges stamped at the
        # latest epoch or later are NOT yet referenced by any manifest
        # (workers swap first, the next checkpoint records them) — keep them.
        for key in self.storage.list(f"{self.job_id}/compacted"):
            if key in referenced:
                continue
            merge_epoch = None
            for part in key.split("-"):
                if part.startswith("epoch"):
                    try:
                        merge_epoch = int(part[len("epoch"):])
                    except ValueError:
                        pass
            if merge_epoch is not None and merge_epoch < latest_epoch:
                self.storage.delete(key)

    def _known_epochs(self) -> List[int]:
        epochs = set()
        for key in self.storage.list(f"{self.job_id}/checkpoints"):
            for p in key.split("/"):
                if p.startswith("checkpoint-"):
                    try:
                        epochs.add(int(p.split("-")[1]))
                    except ValueError:
                        pass
        return sorted(epochs)

    def cleanup(self, min_epoch: int):
        protocol.cleanup_checkpoints(
            self.storage, self.paths, min_epoch, self._known_epochs()
        )
