"""Mesh execution mode through the full engine: window operators keep
their accumulator state sharded over a multi-device mesh (in-step
all_to_all replaces the host hash shuffle) and must produce output
identical to the host-parallel run, including across checkpoint/restore.

This is the engine-integration counterpart of tests/test_parallel.py,
covering VERDICT round-1 item 3 (mesh path as a real execution mode, not
a demo). Reference equivalence target: parallel subtasks + network
shuffle in /root/reference/crates/arroyo-worker/src/engine.rs:209-365.
"""

import asyncio

import pytest

from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query

IMPULSE_DDL = """
CREATE TABLE impulse (
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'impulse',
  event_rate = '1000000',
  message_count = '8000',
  start_time = '0'
);
"""

Q5 = (
    IMPULSE_DDL
    + """
    SELECT AuctionBids.k, AuctionBids.num
    FROM (
      SELECT counter % 8 as k, count(*) AS num,
             hop(interval '2 millisecond', interval '4 millisecond') as window
      FROM impulse
      GROUP BY 1, window
    ) AS AuctionBids
    JOIN (
      SELECT max(CountBids.num) AS maxn, CountBids.window
      FROM (
        SELECT counter % 8 as k, count(*) AS num,
               hop(interval '2 millisecond', interval '4 millisecond') as window
        FROM impulse
        GROUP BY 1, window
      ) AS CountBids
      GROUP BY CountBids.window
    ) AS MaxBids
    ON AuctionBids.window = MaxBids.window
       AND AuctionBids.num >= MaxBids.maxn;
    """
)

TUMBLE_AGG = (
    IMPULSE_DDL
    + """
    SELECT counter % 16 as k, tumble(interval '2 millisecond') as w,
           count(*) as cnt, sum(counter) as total, max(counter) as hi
    FROM impulse
    GROUP BY 1, 2;
    """
)


def _require_devices(n):
    import jax

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def run_rows(sql, parallelism=1, mesh_devices=0, **tpu_overrides):
    results = []
    overrides = {
        "tpu": {"mesh_devices": mesh_devices, "mesh_rows_per_shard": 128,
                **tpu_overrides}
    }
    with update(**overrides):
        plan = plan_query(sql, parallelism=parallelism,
                          preview_results=results)

        async def go():
            eng = Engine(plan.graph).start()
            await eng.join(120)

        asyncio.run(go())
    return sorted(
        tuple(sorted(r.items())) for r in results
    )


def test_mesh_tumbling_matches_host():
    _require_devices(4)
    host = run_rows(TUMBLE_AGG, parallelism=2, mesh_devices=0)
    mesh = run_rows(TUMBLE_AGG, parallelism=1, mesh_devices=4)
    assert host and mesh == host


def test_mesh_q5_matches_host():
    """The headline query shape: hop-window counts joined with per-window
    max — mesh output must match the host-parallel run exactly."""
    _require_devices(4)
    host = run_rows(Q5, parallelism=2, mesh_devices=0)
    mesh = run_rows(Q5, parallelism=1, mesh_devices=4)
    assert host and mesh == host


def test_mesh_device_exchange_golden_q5():
    """Mesh-tier golden for the DEVICE-ROUTED keyed exchange: the fused
    route+scatter+reduce program (no host combiner, owner routing and
    all_to_all on device) must produce output identical to the host-fed
    exchange on the same input — the routing contract is the same
    splitmix64 hash, so the two tiers differ only in WHERE the shuffle
    runs."""
    _require_devices(4)
    host_fed = run_rows(Q5, mesh_devices=4, mesh_exchange="host_fed")
    device = run_rows(Q5, mesh_devices=4, mesh_exchange="device")
    assert host_fed and device == host_fed


def test_mesh_device_exchange_golden_tumbling():
    """Device-routed exchange golden over multi-phys aggregates
    (count/sum/max share one exchange buffer) incl. capacity growth."""
    _require_devices(4)
    host_fed = run_rows(TUMBLE_AGG, mesh_devices=4,
                        mesh_exchange="host_fed")
    device = run_rows(TUMBLE_AGG, mesh_devices=4, mesh_exchange="device")
    assert host_fed and device == host_fed


def test_mesh_under_host_parallelism():
    """Mesh state composes with host-parallel subtasks: each subtask owns a
    key range whose state shards across its own mesh."""
    _require_devices(4)
    host = run_rows(TUMBLE_AGG, parallelism=1, mesh_devices=0)
    mixed = run_rows(TUMBLE_AGG, parallelism=2, mesh_devices=2)
    assert host and mixed == host


def test_mesh_checkpoint_restore(tmp_path):
    """Checkpoint taken in mesh mode restores correctly (and the snapshot
    form is portable: the restore runs host-mode)."""
    _require_devices(4)
    import json

    n = 4000
    src = str(tmp_path / "in.json")
    with open(src, "w") as f:
        for i in range(n):
            us = i * 10  # 10us apart -> 40ms of event time
            f.write(
                json.dumps(
                    {
                        "counter": i,
                        "timestamp": f"2023-03-01T00:00:00.{us:06d}Z",
                    }
                )
                + "\n"
            )

    def make_sql(sink, throttled):
        throttle = "\n  throttle_per_sec = '4000'," if throttled else ""
        return f"""
        CREATE TABLE src (
          timestamp TIMESTAMP, counter BIGINT NOT NULL
        ) WITH (connector = 'single_file', path = '{src}',
                format = 'json', type = 'source',{throttle}
                event_time_field = 'timestamp');
        CREATE TABLE out (
          k BIGINT NOT NULL, w_cnt BIGINT NOT NULL
        ) WITH (connector = 'single_file', path = '{sink}',
                format = 'json', type = 'sink');
        INSERT INTO out
        SELECT counter % 16 as k, count(*) as w_cnt
        FROM src
        GROUP BY 1, tumble(interval '1 millisecond');
        """

    storage = str(tmp_path / "ckpt")
    sink = str(tmp_path / "out.json")

    async def phase1():
        with update(tpu={"mesh_devices": 4, "mesh_rows_per_shard": 128}):
            plan = plan_query(make_sql(sink, throttled=True), parallelism=1)
            eng = Engine(plan.graph, job_id="mesh-fz",
                         storage_url=storage).start()
            for _ in range(2):
                await asyncio.sleep(0.08)
                await eng.checkpoint_and_wait()
            await asyncio.sleep(0.08)
            await eng.checkpoint_and_wait(then_stop=True)
            await eng.join(120)

    asyncio.run(phase1())

    async def phase2():
        # restore WITHOUT mesh: snapshots are portable across modes
        plan = plan_query(make_sql(sink, throttled=False), parallelism=1)
        eng = Engine(plan.graph, job_id="mesh-fz",
                     storage_url=storage).start()
        await eng.join(120)

    asyncio.run(phase2())

    rows = [json.loads(x) for x in open(sink) if x.strip()]
    got = {}
    for r in rows:
        got[r["k"]] = got.get(r["k"], 0) + r["w_cnt"]
    # all events exactly once across the stop/restore boundary
    assert sum(got.values()) == n
    assert set(got) == set(range(16))
    assert all(v == n // 16 for v in got.values())


SESSION_AGG = (
    IMPULSE_DDL
    + """
    SELECT counter % 8 as k, session(interval '50 microsecond') as w,
           count(*) as cnt, sum(counter) as total
    FROM impulse WHERE counter % 100 < 30
    GROUP BY 1, 2;
    """
)


def test_mesh_session_matches_host():
    """Session windows in mesh mode: per-key gap merges with the
    accumulator sharded over the mesh must reproduce the host run
    (VERDICT round-2 item 3; reference session_aggregating_window.rs
    treats sessions like any keyed window)."""
    _require_devices(4)
    host = run_rows(SESSION_AGG, parallelism=1, mesh_devices=0)
    mesh = run_rows(SESSION_AGG, parallelism=1, mesh_devices=4)
    assert host and mesh == host
    # the counter%100<30 filter splits each key into multiple sessions
    assert len(host) > 8


def test_mesh_updating_matches_host(tmp_path):
    """Updating (non-windowed) aggregate in mesh mode: retract/append
    stream must net to the same final state as the host run (reference
    incremental_aggregator.rs:77-90)."""
    _require_devices(4)
    from tests.test_updating import merge_debezium

    def run(out, mesh_devices):
        sql = IMPULSE_DDL + f"""
        CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT, total BIGINT) WITH (
          connector = 'single_file', path = '{out}',
          format = 'debezium_json', type = 'sink'
        );
        INSERT INTO out
        SELECT counter % 6 as k, count(*) as cnt, sum(counter) as total
        FROM impulse GROUP BY 1;
        """
        overrides = {
            "tpu": {"mesh_devices": mesh_devices, "mesh_rows_per_shard": 128}
        }
        with update(**overrides):
            plan = plan_query(sql, parallelism=1)

            async def go():
                eng = Engine(plan.graph).start()
                await eng.join(120)

            asyncio.run(go())
        import json
        final, _ = merge_debezium(
            l for l in open(out) if l.strip()
        )
        return sorted((r["k"], r["cnt"], r["total"]) for r in final)

    host = run(tmp_path / "host.json", 0)
    mesh = run(tmp_path / "mesh.json", 4)
    assert host and mesh == host
    assert len(host) == 6


def test_mesh_session_checkpoint_restore(tmp_path):
    """Session-window state checkpointed in mesh mode restores correctly
    into a host-mode run (snapshot portability)."""
    _require_devices(4)
    import json

    n = 4000
    src = str(tmp_path / "in.json")
    with open(src, "w") as f:
        for i in range(n):
            # bursts of 40 rows 1us apart, 200us dead time between bursts
            burst, off = divmod(i, 40)
            us = burst * 240 + off
            f.write(json.dumps({
                "counter": i,
                "timestamp": f"2023-03-01T00:00:00.{us:06d}Z",
            }) + "\n")

    def make_sql(sink, throttled):
        throttle = "\n  throttle_per_sec = '4000'," if throttled else ""
        return f"""
        CREATE TABLE src (
          timestamp TIMESTAMP, counter BIGINT NOT NULL
        ) WITH (connector = 'single_file', path = '{src}',
                format = 'json', type = 'source',{throttle}
                event_time_field = 'timestamp');
        CREATE TABLE out (
          k BIGINT NOT NULL, s_cnt BIGINT NOT NULL
        ) WITH (connector = 'single_file', path = '{sink}',
                format = 'json', type = 'sink');
        INSERT INTO out
        SELECT counter % 8 as k, count(*) as s_cnt
        FROM src
        GROUP BY 1, session(interval '100 microsecond');
        """

    storage = str(tmp_path / "ckpt")
    sink = str(tmp_path / "out.json")

    async def phase1():
        with update(tpu={"mesh_devices": 4, "mesh_rows_per_shard": 128}):
            plan = plan_query(make_sql(sink, throttled=True), parallelism=1)
            eng = Engine(plan.graph, job_id="mesh-sess",
                         storage_url=storage).start()
            for _ in range(2):
                await asyncio.sleep(0.08)
                await eng.checkpoint_and_wait()
            await asyncio.sleep(0.08)
            await eng.checkpoint_and_wait(then_stop=True)
            await eng.join(120)

    asyncio.run(phase1())

    async def phase2():
        # restore WITHOUT mesh: snapshots are portable across modes
        plan = plan_query(make_sql(sink, throttled=False), parallelism=1)
        eng = Engine(plan.graph, job_id="mesh-sess",
                     storage_url=storage).start()
        await eng.join(120)

    asyncio.run(phase2())

    rows = [json.loads(x) for x in open(sink) if x.strip()]
    got = {}
    for r in rows:
        got[r["k"]] = got.get(r["k"], 0) + r["s_cnt"]
    # every event in exactly one session across the stop/restore boundary
    assert sum(got.values()) == n
    assert set(got) == set(range(8))
    assert all(v == n // 8 for v in got.values())
    # sessions actually split on the 200us gaps (100 bursts, 8 keys each)
    assert len(rows) > 100


def test_mesh_updating_checkpoint_restore(tmp_path):
    """Updating-aggregate state checkpointed in mesh mode restores into a
    mesh-mode run with exact net state."""
    _require_devices(4)
    import json
    from tests.test_updating import merge_debezium

    out = tmp_path / "out.json"
    url = str(tmp_path / "ck")
    sql = IMPULSE_DDL.replace("'1000000'", "'20000'").replace(
        "start_time = '0'", "start_time = '0', realtime = 'true'"
    ).replace("'8000'", "'4000'") + f"""
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{out}',
      format = 'debezium_json', type = 'sink'
    );
    INSERT INTO out
    SELECT counter % 5 as k, count(*) as cnt FROM impulse GROUP BY 1;
    """

    async def phase(stop):
        with update(tpu={"mesh_devices": 4, "mesh_rows_per_shard": 128}):
            plan = plan_query(sql, parallelism=1)
            eng = Engine(plan.graph, job_id="mesh-upd",
                         storage_url=url).start()
            if stop:
                await asyncio.sleep(0.1)
                await eng.checkpoint_and_wait(then_stop=True)
            await eng.join(120)

    asyncio.run(phase(stop=True))
    asyncio.run(phase(stop=False))
    final, _ = merge_debezium(l for l in open(out) if l.strip())
    got = {r["k"]: r["cnt"] for r in final}
    assert got == {k: 800 for k in range(5)}


def test_global_session_window_salted_mesh():
    """A keyless (global) session window in mesh mode takes the SALTED
    path (planner marks window-only/keyless groupings mesh_salted):
    imperative slot allocation via SharedMeshSlotDirectory plus
    cross-shard folds at gather/merge must reproduce the single-device
    result."""
    import asyncio

    from arroyo_tpu.config import update
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.sql import plan_query

    sql = """
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '3000', start_time = '0'
    );
    SELECT session(interval '1 millisecond') AS w, count(*) AS cnt,
           sum(counter) AS total
    FROM impulse GROUP BY w;
    """
    results = []
    with update(tpu={"mesh_devices": 4, "mesh_rows_per_shard": 128}):
        plan = plan_query(sql, preview_results=results)
        # the session aggregate must actually be marked salted
        assert any(
            op.config.get("mesh_salted")
            for node in plan.graph.nodes.values()
            for op in node.chain
            if "aggregates" in op.config
        )

        async def go():
            eng = Engine(plan.graph).start()
            await eng.join(120)

        asyncio.run(go())
    # 3000 events at 1/us with a 1ms gap: one continuous session
    assert len(results) == 1
    assert results[0]["cnt"] == 3000
    assert results[0]["total"] == sum(range(3000))


SALTED_HOST_STATE = (
    IMPULSE_DDL
    + """
    SELECT tumble(interval '2 millisecond') as w,
           count(*) as cnt,
           count(DISTINCT counter % 50) as dcnt,
           median(counter) as med,
           max(counter) as hi
    FROM impulse
    GROUP BY 1;
    """
)


def test_mesh_salted_host_state_aggregates():
    """Salted mesh aggregation with HOST-STATE specs (count DISTINCT
    multiset, median buffer): the window itself is the only group key,
    so the planner marks mesh_salted; host stores are keyed by global
    slot and must produce the same answer as the host run (round-4
    verdict: salting excluded host-state aggregates).

    mesh_salted_tier='mesh' pins the salted SHARDED path explicitly —
    on this virtual CPU mesh 'auto' would tier the window-global stage
    onto a single device (tested separately below)."""
    _require_devices(4)
    host = run_rows(SALTED_HOST_STATE, parallelism=1, mesh_devices=0)
    mesh = run_rows(SALTED_HOST_STATE, parallelism=1, mesh_devices=4,
                    mesh_salted_tier="mesh")
    assert host and mesh == host


def test_mesh_salted_tier_auto_on_virtual_mesh():
    """On a VIRTUAL (forced host-platform) mesh, 'auto' runs salted
    window-global aggregates on the single-device tier: there is no key
    axis to shard and the salted spread costs S x serial work for a
    handful of groups. Output must be identical either way, and the
    stage must actually leave the mesh accumulator."""
    _require_devices(4)
    single = run_rows(SALTED_HOST_STATE, parallelism=1, mesh_devices=4)
    mesh = run_rows(SALTED_HOST_STATE, parallelism=1, mesh_devices=4,
                    mesh_salted_tier="mesh")
    assert single and single == mesh
    # construction-level assert: auto => standard accumulator, not the
    # sharded one (the engine run above only proves output equality)
    from arroyo_tpu.operators.windows import TumblingWindowOperator
    from arroyo_tpu.parallel.sharded_state import ShardedAccumulator

    cfg = {
        "aggregates": [{"kind": "count", "name": "cnt"}],
        "key_cols": [],
        "schema": None,
        "width_nanos": 1000,
        "mesh_salted": True,
        "mesh_devices": 4,
        "backend": "jax",
    }
    with update(tpu={"mesh_devices": 4}):
        op = TumblingWindowOperator.__new__(TumblingWindowOperator)
        from arroyo_tpu.operators.windows import WindowOperatorBase

        WindowOperatorBase.__init__(op, cfg, "tumbling_window")
        assert not isinstance(op.acc, ShardedAccumulator)
    with update(tpu={"mesh_devices": 4, "mesh_salted_tier": "mesh"}):
        op = TumblingWindowOperator.__new__(TumblingWindowOperator)
        WindowOperatorBase.__init__(op, cfg, "tumbling_window")
        assert isinstance(op.acc, ShardedAccumulator) and op.acc.salted


def test_mesh_microbatch_flush_boundaries():
    """Micro-batched mesh updates (tpu.mesh_flush_rows) must flush at
    every state read: tiny flush threshold vs giant threshold produce
    identical output (the giant one only ever flushes via gather)."""
    _require_devices(4)
    with update(tpu={"mesh_flush_rows": 0}):
        immediate = run_rows(TUMBLE_AGG, parallelism=1, mesh_devices=4)
    with update(tpu={"mesh_flush_rows": 1 << 30}):
        deferred = run_rows(TUMBLE_AGG, parallelism=1, mesh_devices=4)
    assert immediate and deferred == immediate


def test_mesh_session_slot_pool_balance():
    """The session operator's block-refilled slot pool must keep mesh
    placement balanced: allocations from MeshSlotDirectory.alloc_slots
    land round-robin across shards."""
    import numpy as np

    from arroyo_tpu.parallel.sharded_state import STRIDE, MeshSlotDirectory

    d = MeshSlotDirectory(4)
    slots = d.alloc_slots(64, shard_hint=3)
    shards = np.asarray(slots) // STRIDE
    counts = np.bincount(shards, minlength=4)
    assert counts.tolist() == [16, 16, 16, 16]
    # freed slots recycle within their shard
    for s in slots[:8]:
        d.free_slot(int(s))
    again = d.alloc_slots(8, shard_hint=0)
    assert sorted(np.asarray(again) // STRIDE) == sorted(shards[:8])
