"""Explicit-state BFS explorer with counterexample reconstruction.

BFS over `Model.enabled` from `initial_state`, hashing NamedTuple states,
with three checks:

  * step violations (a `Step.violation` is an invariant broken by the
    transition itself — publish order, chain atomicity, fencing, double
    commit, illegal JobState moves);
  * state invariants (`Model.check_state`: deadlock, stall, stranded
    transaction at stop, fault-free FAILED);
  * a post-pass on the explored graph: in an exhaustive run, every
    non-terminal state must be able to reach a terminal one (the
    "stuck non-terminal state" detector — backward reachability from
    terminals over the recorded edges).

Partial-order reduction (on by default, `por=False` disables): when
several workers have purely worker-local steps enabled, only the
lowest-index worker's local steps are expanded alongside all global
steps. Worker-local steps on distinct workers commute (they touch
disjoint worker tuples; their shared effects — blob/report insertion —
are commutative set adds), deferred steps stay enabled (only a fault
targeting that worker can disable them, and fault steps are global, so
that interleaving is still explored), and the invariants never inspect
the relative order of two workers' local steps. The mutant corpus test
runs every mutant under both `por` settings and asserts identical
verdicts — an empirical guard on the reduction, on top of the argument.

A violating path serializes to a `Trace`: the (label, arg) event list
from the initial state, the violation, and the handler effects each step
cites (TRANSITION_HANDLERS) — which is what `replay.py` turns into a
seeded chaos FaultPlan.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Tuple

from .spec import (
    Model,
    ModelConfig,
    Step,
    Sys,
    TRANSITION_HANDLERS,
    initial_state,
)

# worker-local labels: touch one worker's tuple plus commutative global
# set-inserts only (see the POR argument in the module docstring)
_LOCAL_LABELS = ("w.capture", "w.flush", "w.commit", "w.finish")


@dataclasses.dataclass
class Trace:
    """A reproducible counterexample: events from the initial state."""

    violation: str
    events: List[Tuple[str, Tuple]]  # (label, arg) in order
    config: dict
    mutant: str = ""

    def fault_events(self) -> List[Tuple[str, Tuple]]:
        return [(lb, arg) for (lb, arg) in self.events
                if lb.startswith("fault.")]

    def handlers_cited(self) -> List[str]:
        seen: List[str] = []
        for lb, _arg in self.events:
            for h in TRANSITION_HANDLERS.get(lb, ()):
                if h not in seen:
                    seen.append(h)
        return seen

    def to_json(self) -> dict:
        return {
            "violation": self.violation,
            "mutant": self.mutant,
            "config": self.config,
            "events": [[lb, list(arg)] for (lb, arg) in self.events],
            "handlers_cited": self.handlers_cited(),
        }

    @classmethod
    def from_json(cls, obj: dict) -> "Trace":
        return cls(
            violation=obj["violation"],
            events=[(lb, tuple(arg)) for lb, arg in obj["events"]],
            config=obj.get("config", {}),
            mutant=obj.get("mutant", ""),
        )


@dataclasses.dataclass
class ExploreResult:
    states: int
    transitions: int
    violations: List[Trace]
    exhaustive: bool          # False when the state budget truncated BFS
    terminal_states: int
    max_frontier: int

    @property
    def clean(self) -> bool:
        return not self.violations


def _reduce(steps: List[Step]) -> List[Step]:
    """Ample-set-style reduction: keep all global steps, but expand only
    the lowest-index worker's local steps when several workers have
    them. (Deferred locals stay enabled in every successor.)"""
    local_by_worker: Dict[int, List[Step]] = {}
    out: List[Step] = []
    for st in steps:
        if st.label in _LOCAL_LABELS and not st.violation:
            local_by_worker.setdefault(st.arg[0], []).append(st)
        else:
            out.append(st)
    if local_by_worker:
        out.extend(local_by_worker[min(local_by_worker)])
    return out


def explore(
    model: Model,
    budget: int = 2_000_000,
    por: bool = True,
    max_violations: int = 8,
    first_violation: bool = False,
) -> ExploreResult:
    """BFS the model's state space. Stops early once `max_violations`
    distinct violation kinds are collected (or the first, when
    `first_violation`), or when `budget` states were expanded (the
    result is then marked non-exhaustive)."""
    init = initial_state(model.cfg)
    # state -> (predecessor state, (label, arg)) for trace reconstruction
    parent: Dict[Sys, Optional[Tuple[Sys, Tuple[str, Tuple]]]] = {init: None}
    edges: Dict[Sys, List[Sys]] = {}
    frontier = deque([init])
    violations: List[Trace] = []
    seen_kinds: set = set()
    n_trans = 0
    terminals = 0
    exhausted = True
    max_frontier = 1

    def record(state: Sys, step_ev: Optional[Tuple[str, Tuple]],
               violation: str):
        kind = violation.split(":", 1)[0]
        if kind in seen_kinds:
            return
        seen_kinds.add(kind)
        events: List[Tuple[str, Tuple]] = [step_ev] if step_ev else []
        cur = state
        while parent[cur] is not None:
            prev, ev = parent[cur]
            events.append(ev)
            cur = prev
        events.reverse()
        violations.append(Trace(
            violation=violation,
            events=events,
            config=model.cfg._asdict(),
            mutant=model.cfg.mutant,
        ))

    while frontier:
        if len(parent) > budget:
            exhausted = False
            break
        if violations and (first_violation
                           or len(violations) >= max_violations):
            exhausted = False
            break
        state = frontier.popleft()
        steps = model.enabled(state)
        inv = model.check_state(state, steps)
        if inv is not None:
            record(state, None, inv)
            continue
        if model.done(state):
            terminals += 1
            continue
        if por:
            steps = _reduce(steps)
        succs = edges.setdefault(state, [])
        for st in steps:
            n_trans += 1
            if st.violation:
                record(state, (st.label, st.arg), st.violation)
                continue
            if st.nxt is None:
                continue
            succs.append(st.nxt)
            if st.nxt not in parent:
                parent[st.nxt] = (state, (st.label, st.arg))
                frontier.append(st.nxt)
        max_frontier = max(max_frontier, len(frontier))

    # post-pass: stuck non-terminal states (exhaustive runs only — a
    # truncated frontier makes "cannot reach a terminal" meaningless)
    if exhausted and not violations:
        can_finish = {s for s in parent if model.done(s)}
        # reverse edges, then backward-propagate reachability
        rev: Dict[Sys, List[Sys]] = {}
        for src, dsts in edges.items():
            for d in dsts:
                rev.setdefault(d, []).append(src)
        work = deque(can_finish)
        while work:
            cur = work.popleft()
            for p in rev.get(cur, ()):
                if p not in can_finish:
                    can_finish.add(p)
                    work.append(p)
        for s in parent:
            if s not in can_finish and not model.done(s):
                record(
                    s, None,
                    "non-terminal-state-cannot-terminate: "
                    f"{s.ctrl.js} (stop={s.ctrl.stop} "
                    f"rescale={s.ctrl.rescale} pending={s.ctrl.pending})",
                )
                break

    return ExploreResult(
        states=len(parent),
        transitions=n_trans,
        violations=violations,
        exhaustive=exhausted,
        terminal_states=terminals,
        max_frontier=max_frontier,
    )


def explore_config(cfg: ModelConfig, transitions, terminals,
                   **kw) -> ExploreResult:
    return explore(Model(cfg, transitions, terminals), **kw)
