"""Must NOT fire RACE002: every post-await write revalidates by
re-reading the field fresh in its own RHS — the or-restore (newer value
wins), the fresh-read increment, and the monotonic max-merge."""
import asyncio

from arroyo_tpu.analysis.races import shared_state


@shared_state("stop_requested", "counter",
              multi_writer=("stop_requested", "counter"))
class Job:
    def __init__(self):
        self.stop_requested = None
        self.counter = 0


class Engine:
    async def drive(self, job):
        mode = job.stop_requested
        job.stop_requested = None
        await self.checkpoint(job)
        job.stop_requested = job.stop_requested or mode

    async def bump(self, job):
        await asyncio.sleep(0)
        job.counter = job.counter + 1

    async def raise_hwm(self, job, epoch):
        await asyncio.sleep(0)
        job.counter = max(job.counter, epoch)

    async def checkpoint(self, job):
        await asyncio.sleep(0)
