"""The HTTP-family connectors driven END TO END against local loopback
servers — real sockets, the real operator code, through the real engine
(sse / websocket / polling_http sources, webhook sink). The reference
covers these connectors with unit + integ tests
(/root/reference/crates/arroyo-connectors/src/{sse,websocket,
polling_http,webhook}); here a local aiohttp/websockets server stands in
for the external service so the tests run hermetically."""

import asyncio
import json

import pytest
from aiohttp import web

from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query


async def _start_site(app):
    # shutdown_timeout=0.1: handlers deliberately hold streams open (like
    # real SSE/long-poll endpoints); cleanup must not wait a minute
    runner = web.AppRunner(app, shutdown_timeout=0.1)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, port


def test_sse_source_resumes_from_last_event_id(tmp_path):
    """SSE source: streams data events, checkpoint-stops mid-stream,
    and on restart replays from the checkpointed Last-Event-ID header —
    every event exactly once across the two runs."""
    url = str(tmp_path / "ck")
    out = tmp_path / "out.json"
    requests = []

    async def sse_handler(request):
        last = int(request.headers.get("Last-Event-ID", -1))
        requests.append(last)
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream"}
        )
        await resp.prepare(request)
        for i in range(last + 1, 200):
            await resp.write(
                f"id: {i}\ndata: {json.dumps({'n': i})}\n\n".encode()
            )
            await asyncio.sleep(0.01)
        # keep the stream open like a real SSE endpoint: the engine
        # stops the source via control, not via EOF
        await asyncio.sleep(60)
        return resp

    async def phase():
        app = web.Application()
        app.router.add_get("/events", sse_handler)
        runner, port = await _start_site(app)
        try:
            sql = f"""
            CREATE TABLE src (n BIGINT) WITH (
              connector = 'sse',
              endpoint = 'http://127.0.0.1:{port}/events',
              type = 'source', format = 'json'
            );
            CREATE TABLE dst (n BIGINT) WITH (
              connector = 'single_file', path = '{out}',
              format = 'json', type = 'sink'
            );
            INSERT INTO dst SELECT n FROM src;
            """
            plan = plan_query(sql, parallelism=1)
            eng = Engine(plan.graph, job_id="sse1", storage_url=url).start()
            await asyncio.sleep(0.35)
            await eng.checkpoint_and_wait(then_stop=True)
            await eng.join(60)
        finally:
            await runner.cleanup()

    asyncio.run(phase())
    first = [json.loads(l)["n"] for l in open(out) if l.strip()]
    assert first and first == list(range(len(first))), first
    assert len(first) < 200, "stream finished before the stop: too fast"

    asyncio.run(phase())
    rows = [json.loads(l)["n"] for l in open(out) if l.strip()]
    assert sorted(rows) == list(range(max(rows) + 1)), (
        "resume lost or duplicated events"
    )
    assert len(rows) == len(set(rows))
    # the second connection presented the checkpointed Last-Event-ID
    assert len(requests) >= 2 and requests[1] == first[-1]


def test_websocket_source_streams(tmp_path):
    """WebSocket source: subscription message then streamed json frames
    through the engine to a sink."""
    websockets = pytest.importorskip(
        "websockets", reason="websockets package not installed"
    )

    out = tmp_path / "out.json"
    got_subs = []

    async def handler(ws):
        sub = await ws.recv()
        got_subs.append(sub)
        for i in range(25):
            await ws.send(json.dumps({"n": i}))
        # hold open until the client disconnects (engine stops via
        # control); serve() waits for handlers at shutdown, so an
        # unconditional sleep would stall the test teardown
        await ws.wait_closed()

    async def go():
        async with websockets.serve(handler, "127.0.0.1", 0,
                                    close_timeout=0.1) as server:
            port = server.sockets[0].getsockname()[1]
            sql = f"""
            CREATE TABLE src (n BIGINT) WITH (
              connector = 'websocket',
              endpoint = 'ws://127.0.0.1:{port}',
              subscription_message = '{{"subscribe": "all"}}',
              type = 'source', format = 'json'
            );
            CREATE TABLE dst (n BIGINT) WITH (
              connector = 'single_file', path = '{out}',
              format = 'json', type = 'sink'
            );
            INSERT INTO dst SELECT n * 2 AS n FROM src;
            """
            plan = plan_query(sql, parallelism=1)
            eng = Engine(plan.graph).start()
            await asyncio.sleep(0.6)
            from arroyo_tpu.types import StopMode

            await eng.stop(StopMode.GRACEFUL)
            await eng.join(60)

    asyncio.run(go())
    rows = sorted(json.loads(l)["n"] for l in open(out) if l.strip())
    assert rows == [i * 2 for i in range(25)]
    assert got_subs == ['{"subscribe": "all"}']


def test_polling_http_emit_on_change(tmp_path):
    """polling_http source: polls on an interval and, with
    emit_behavior=changed, emits only when the payload changes."""
    out = tmp_path / "out.json"
    polls = []

    async def poll_handler(request):
        polls.append(1)
        # payload advances every 3 polls: several polls see an
        # unchanged body and must not re-emit
        v = (len(polls) - 1) // 3
        return web.json_response({"v": v})

    async def go():
        app = web.Application()
        app.router.add_get("/data", poll_handler)
        runner, port = await _start_site(app)
        try:
            sql = f"""
            CREATE TABLE src (v BIGINT) WITH (
              connector = 'polling_http',
              endpoint = 'http://127.0.0.1:{port}/data',
              poll_interval = '0.03',
              emit_behavior = 'changed',
              type = 'source', format = 'json'
            );
            CREATE TABLE dst (v BIGINT) WITH (
              connector = 'single_file', path = '{out}',
              format = 'json', type = 'sink'
            );
            INSERT INTO dst SELECT v FROM src;
            """
            plan = plan_query(sql, parallelism=1)
            eng = Engine(plan.graph).start()
            await asyncio.sleep(0.7)
            from arroyo_tpu.types import StopMode

            await eng.stop(StopMode.GRACEFUL)
            await eng.join(60)
        finally:
            await runner.cleanup()

    asyncio.run(go())
    rows = [json.loads(l)["v"] for l in open(out) if l.strip()]
    assert len(polls) > len(rows), "emit-on-change did not dedupe polls"
    assert rows == sorted(set(rows)), f"duplicate emissions: {rows}"
    assert rows[0] == 0 and len(rows) >= 2


def test_webhook_sink_retries_then_delivers(tmp_path):
    """Webhook sink: POST per record; transient 500s are retried with
    backoff and every record is delivered."""
    received = []
    fail_first = {"n": 2}

    async def hook(request):
        if fail_first["n"] > 0:
            fail_first["n"] -= 1
            return web.Response(status=500)
        received.append(await request.json())
        return web.Response(status=200)

    async def go():
        app = web.Application()
        app.router.add_post("/hook", hook)
        runner, port = await _start_site(app)
        try:
            sql = f"""
            CREATE TABLE impulse WITH (
              connector = 'impulse', event_rate = '100000',
              message_count = '10', start_time = '0'
            );
            CREATE TABLE dst (counter BIGINT UNSIGNED) WITH (
              connector = 'webhook',
              endpoint = 'http://127.0.0.1:{port}/hook',
              type = 'sink', format = 'json'
            );
            INSERT INTO dst SELECT counter FROM impulse;
            """
            plan = plan_query(sql, parallelism=1)
            eng = Engine(plan.graph).start()
            await eng.join(60)
        finally:
            await runner.cleanup()

    asyncio.run(go())
    assert sorted(r["counter"] for r in received) == list(range(10))
    assert fail_first["n"] == 0, "retry path never exercised"
