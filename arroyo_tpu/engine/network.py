"""TCP data plane: Arrow IPC record batches between workers.

Capability parity with the reference's network manager
(/root/reference/crates/arroyo-worker/src/network_manager.rs): raw TCP
carrying Arrow-IPC-encoded RecordBatches with a fixed routing header
`Quad{src_node, src_subtask, dst_node, dst_subtask}`
(network_manager.rs:170-236, write_message_and_header:551, read_message:605);
one outgoing connection per (remote worker, edge); incoming frames route to
the destination subtask's local input queue; backpressure propagates from
the bounded in-process queues through per-connection flow control
(the pump only reads the next outgoing batch after the socket write
drains). Signals ride the same framing msgpack-encoded.

Frame layout (little-endian):
  magic u32 = 0xA77051  | kind u8 (0=data,1=signal,2=hello)
  src_node u32 | src_subtask u32 | dst_node u32 | dst_subtask u32
  payload_len u64 | sent_ns u64 | trace_len u16
  trace bytes (msgpack {"t": trace_id, "s": span_id}, flight recorder)
  payload bytes

Multi-tenancy: node ids are per-job, so quads collide across jobs
multiplexed onto one worker. Each connection therefore opens with ONE
hello frame (kind=2, payload msgpack {"ns": "<job_id>@<incarnation>"})
binding every subsequent frame on that connection to the sender job's
route namespace; the server routes on (ns, quad). The incarnation
(controller schedule counter) additionally fences a straggler connection
from a torn-down incarnation of the SAME job out of the fresh
incarnation's queues.

Every frame header carries the sender's wall-clock send timestamp, which
the receiver folds into the `arroyo_exchange_frame_seconds` histogram;
the trace preamble attaches to signal frames carrying barrier context and
to every obs.frame_sample_every'th data frame (sampled exchange spans).
"""

from __future__ import annotations

import asyncio
import io
import struct
import time
from typing import Dict, Optional, Tuple

import msgpack
import pyarrow as pa

from .. import chaos, obs
from ..metrics import EXCHANGE_FRAME_SECONDS
from ..types import (
    CheckpointBarrier,
    LatencyMarker,
    SignalKind,
    SignalMessage,
    Watermark,
    WatermarkKind,
)
from ..utils.logging import get_logger
from ..operators.queues import BatchQueue

logger = get_logger("network")

MAGIC = 0xA77051
_HEADER = struct.Struct("<IBIIIIQQH")

Quad = Tuple[int, int, int, int]  # src_node, src_sub, dst_node, dst_sub


def encode_signal(sig: SignalMessage) -> bytes:
    out = {"kind": sig.kind.value}
    if sig.watermark is not None:
        out["wm_kind"] = sig.watermark.kind.value
        out["wm_ts"] = sig.watermark.timestamp
    if sig.barrier is not None:
        b = sig.barrier
        out["barrier"] = [b.epoch, b.min_epoch, b.timestamp, b.then_stop]
        if b.trace_id:
            # flight-recorder context rides the barrier across workers
            out["barrier"] += [b.trace_id, b.span_id]
    if sig.marker is not None:
        m = sig.marker
        out["marker"] = [m.source_task, m.seq, m.stamp_ns]
    return msgpack.packb(out)


def decode_signal(data: bytes) -> SignalMessage:
    obj = msgpack.unpackb(data, raw=False)
    kind = SignalKind(obj["kind"])
    wm = None
    barrier = None
    marker = None
    if "wm_kind" in obj:
        wm = Watermark(WatermarkKind(obj["wm_kind"]), obj.get("wm_ts"))
    if "barrier" in obj:
        e, m, t, s = obj["barrier"][:4]
        extra = obj["barrier"][4:]
        barrier = CheckpointBarrier(
            e, m, t, s,
            trace_id=extra[0] if extra else "",
            span_id=extra[1] if len(extra) > 1 else "",
        )
    if "marker" in obj:
        marker = LatencyMarker(*obj["marker"][:3])
    return SignalMessage(kind, wm, barrier, marker)


def encode_batch(batch: pa.RecordBatch) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue()


def decode_batch(data: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(pa.py_buffer(data)) as r:
        batches = list(r)
    if len(batches) == 1:
        return batches[0]
    return pa.Table.from_batches(batches).combine_chunks().to_batches()[0]


def write_frame(writer: asyncio.StreamWriter, quad: Quad, item,
                trace: Optional[dict] = None) -> None:
    if isinstance(item, SignalMessage):
        kind, payload = 1, encode_signal(item)
    else:
        kind, payload = 0, encode_batch(item)
    tbytes = msgpack.packb(trace) if trace else b""
    writer.write(
        _HEADER.pack(MAGIC, kind, *quad, len(payload), time.time_ns(),
                     len(tbytes))
    )
    if tbytes:
        writer.write(tbytes)
    writer.write(payload)


def write_hello(writer: asyncio.StreamWriter, ns: str) -> None:
    """Bind this connection to a job route namespace (first frame)."""
    payload = msgpack.packb({"ns": ns})
    writer.write(
        _HEADER.pack(MAGIC, 2, 0, 0, 0, 0, len(payload), time.time_ns(), 0)
    )
    writer.write(payload)


async def read_frame(reader: asyncio.StreamReader):
    """Returns (quad, item, sent_ns, trace-dict-or-None)."""
    header = await reader.readexactly(_HEADER.size)
    magic, kind, sn, ss, dn, ds, plen, sent_ns, tlen = _HEADER.unpack(header)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic:#x}")
    trace = None
    if tlen:
        trace = msgpack.unpackb(await reader.readexactly(tlen), raw=False)
    payload = await reader.readexactly(plen)
    if kind == 2:
        item = msgpack.unpackb(payload, raw=False)  # hello dict
    elif kind == 1:
        item = decode_signal(payload)
    else:
        item = decode_batch(payload)
    return (sn, ss, dn, ds), kind, item, sent_ns, trace


def _set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on data-plane sockets: frames are latency-sensitive
    and often tiny (watermarks, per-window join batches) — Nagle plus
    delayed ACK costs 40-200 ms PER HOP, which stacks across the
    multi-edge paths of a split pipeline. Throughput is unaffected: the
    pump already writes whole frames and drains."""
    sock = writer.get_extra_info("socket")
    if sock is not None:
        import socket as _socket

        try:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except OSError:
            pass  # e.g. TLS-wrapped transport without raw socket access


class DataPlaneServer:
    """Accepts peer connections and routes frames into local input queues
    (reference `Senders`)."""

    def __init__(self, bind: str = "127.0.0.1", port: int = 0):
        self.bind = bind
        self.port = port
        # (ns, (src_node, src_sub, dst_node, dst_sub)) -> local queue;
        # ns is the sender job's "<job_id>@<incarnation>" namespace
        # (quads collide across multiplexed jobs)
        self.routes: Dict[tuple, BatchQueue] = {}
        self._server: Optional[asyncio.AbstractServer] = None

    def register(self, quad: Quad, queue: BatchQueue, ns: str = ""):
        self.routes[(ns, quad)] = queue

    def unregister_ns(self, ns: str):
        """Per-job teardown: drop every route of one job namespace so a
        co-resident job's routes stay live (and a straggler connection of
        the torn-down job routes nowhere instead of into fresh queues)."""
        for key in [k for k in self.routes if k[0] == ns]:
            del self.routes[key]

    async def start(self) -> int:
        from ..utils.tls import data_server_context

        self._server = await asyncio.start_server(
            self._handle, self.bind, self.port, ssl=data_server_context()
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter):
        _set_nodelay(writer)
        peer = writer.get_extra_info("peername")
        lat_handles: Dict[Quad, object] = {}
        ns = ""  # bound by the connection's hello frame
        try:
            while True:
                quad, kind, item, sent_ns, trace = await read_frame(reader)
                if kind == 2:
                    ns = item.get("ns", "")
                    continue
                latency = max(0, time.time_ns() - sent_ns) / 1e9
                h = lat_handles.get(quad)
                if h is None:
                    # job label: the cardinality GC drops a stopped job's
                    # exchange series with the rest of its families
                    h = lat_handles[quad] = EXCHANGE_FRAME_SECONDS.labels(
                        task=f"{quad[2]}-{quad[3]}",
                        job=ns.split("@", 1)[0],
                    )
                h.observe(latency)
                if trace and "t" in trace and obs.enabled():
                    # sampled frame span: spans the wire time, parented to
                    # the sender's span so hops line up in trace dumps
                    import os as _os

                    obs.recorder().record({
                        "trace_id": trace["t"], "span_id": obs.new_span_id(),
                        "parent_id": trace.get("s"), "name": "exchange.frame",
                        "cat": "network", "ts": sent_ns / 1e3,
                        "dur": latency * 1e6,
                        "attrs": {
                            "edge": f"{quad[0]}-{quad[1]}->"
                                    f"{quad[2]}-{quad[3]}",
                        },
                        "events": [], "pid": _os.getpid(), "tid": 0,
                    })
                queue = self.routes.get((ns, quad))
                if queue is None:
                    logger.warning("no route for %s/%s from %s", ns, quad,
                                   peer)
                    continue
                if kind == 0 and chaos.fire(
                    "audit.dup_frame",
                    edge=getattr(queue, "audit_edge", None)
                    or f"{quad[0]}:{quad[1]}->{quad[2]}:{quad[3]}",
                ):
                    # duplicated data-frame delivery past the TCP layer:
                    # the receiver tap attests the rows twice while the
                    # sender attested them once — the conservation
                    # reconciler must name this edge+epoch
                    await queue.send(item)
                await queue.send(item)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class RemoteEdgeSender:
    """Pumps a local queue over TCP to a remote worker: the sender side of
    one (edge, dst_subtask) pair. Each edge pair gets its OWN connection —
    sharing one socket across edges would couple their backpressure: a
    blocked input (e.g. awaiting checkpoint barrier alignment) must never
    stall delivery of another edge's frames (the reference keeps one
    connection per (worker, edge) for the same reason,
    network_manager.rs:41-106). The bounded local queue provides
    backpressure; the pump blocks on socket drain."""

    def __init__(self, address: str, quad: Quad, queue: BatchQueue,
                 on_error=None, ns: str = ""):
        self.address = address
        self.quad = quad
        self.queue = queue
        self.on_error = on_error
        self.ns = ns  # sender job's route namespace (hello frame)
        self.task: Optional[asyncio.Task] = None
        self.writer: Optional[asyncio.StreamWriter] = None

    async def start(self):
        from ..utils.tls import data_client_context

        spec = chaos.fire("network.connect_delay", quad=self.quad,
                          address=self.address)
        if spec is not None:
            await asyncio.sleep(float(spec.param("delay", 0.2)))
        host, port = self.address.rsplit(":", 1)
        ctx, server_name = data_client_context()
        _, self.writer = await asyncio.open_connection(
            host, int(port), ssl=ctx,
            server_hostname=server_name if ctx is not None else None,
        )
        _set_nodelay(self.writer)
        write_hello(self.writer, self.ns)
        await self.writer.drain()
        self.task = asyncio.ensure_future(self._pump())

    async def _pump(self):
        from ..operators.queues import QueueClosed

        sample_every = obs.frame_sample_every()
        n_frames = 0
        # exchange attribution: the pump task belongs to one job (the ns
        # is "<job_id>@<incarnation>"), so frame serialization + socket
        # drain time lands on that tenant's exchange phase
        job_id = self.ns.split("@", 1)[0] if self.ns else ""
        obs.attribution.set_job(job_id)
        try:
            while True:
                try:
                    item = await self.queue.recv()
                except QueueClosed:
                    return
                if chaos.fire("network.drop_connection", quad=self.quad):
                    self.writer.close()
                    raise ConnectionResetError(
                        "chaos[network.drop_connection]: injected "
                        f"data-plane drop on edge {self.quad}"
                    )
                spec = chaos.fire("network.partial_frame", quad=self.quad)
                if spec is not None:
                    # emit a torn frame: full header, half the payload. The
                    # receiver's readexactly must fail (never deliver it).
                    if isinstance(item, SignalMessage):
                        kind, payload = 1, encode_signal(item)
                    else:
                        kind, payload = 0, encode_batch(item)
                    self.writer.write(
                        _HEADER.pack(MAGIC, kind, *self.quad, len(payload),
                                     time.time_ns(), 0)
                    )
                    self.writer.write(payload[: max(1, len(payload) // 2)])
                    await self.writer.drain()
                    self.writer.close()
                    raise ConnectionResetError(
                        "chaos[network.partial_frame]: injected torn frame "
                        f"on edge {self.quad}"
                    )
                trace = None
                n_frames += 1
                if (sample_every and not isinstance(item, SignalMessage)
                        and n_frames % sample_every == 1 and obs.enabled()):
                    # sampled data-frame trace header: one exchange span
                    # per edge track in the dump, grouped by edge
                    sn, ss, dn, ds = self.quad
                    trace = {"t": f"exchange/{sn}-{ss}_{dn}-{ds}"}
                t0 = time.perf_counter()
                write_frame(self.writer, self.quad, item, trace)
                await self.writer.drain()
                if not isinstance(item, SignalMessage):
                    obs.timeline.note(
                        "exchange", time.perf_counter() - t0,
                        task=f"{self.quad[0]}-{self.quad[1]}",
                    )
                if isinstance(item, SignalMessage) and item.kind in (
                    SignalKind.END_OF_DATA, SignalKind.STOP
                ):
                    return
        except Exception as e:  # noqa: BLE001 - network boundary
            logger.exception("remote edge pump %s -> %s failed",
                             self.quad, self.address)
            if self.on_error is not None:
                self.on_error(self.quad, e)
        finally:
            if self.writer is not None:
                self.writer.close()
