CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT,
  WATERMARK FOR timestamp AS (timestamp - INTERVAL '1 minute')
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source'
);
CREATE TABLE group_by_aggregate (
  month TIMESTAMP,
  count BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO group_by_aggregate
SELECT window.start as month, count
FROM (
  SELECT tumble(interval '30 day') as window, count(*) as count
  FROM cars
  GROUP BY 1
);
