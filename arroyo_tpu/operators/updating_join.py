"""Updating (non-windowed) joins with retractions.

Capability parity with the reference's updating join support
(/root/reference/crates/arroyo-sql-testing/src/test/queries/
updating_{inner,left,right,full}_join.sql + planner plan/join.rs updating
path): both sides materialize per join key; every arriving append/retract
incrementally emits the delta of the join result as append/retract rows
tagged with __updating_meta, including the null-padded transitions of
outer joins (a side's first match retracts its null-padded row; losing the
last match re-emits it).

Streams reaching this operator are post-shuffle (keyed on the equi keys),
so each subtask owns its key range. Rates here are typically
post-aggregation, so the per-row host loop favors correctness; state
checkpoints as msgpack'd row lists per key.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from ..schema import StreamSchema, TIMESTAMP_FIELD, UPDATING_META_FIELD
from .base import Operator


class UpdatingJoinOperator(Operator):
    def __init__(self, config: dict):
        super().__init__("updating_join")
        self.n_keys = int(config["n_keys"])
        self.join_type = config["join_type"]  # inner | left | right | full
        self.out_schema: StreamSchema = config["schema"]
        key_names = {f"__key{i}" for i in range(self.n_keys)}
        skip = key_names | {TIMESTAMP_FIELD, UPDATING_META_FIELD}
        # SOURCE payload column names per side (input batch names) and the
        # OUTPUT names they map to (right side may be _right-renamed,
        # positionally aligned with the source order)
        self.left_src: List[str] = [
            f.name for f in config["left_schema"].schema
            if f.name not in skip
        ]
        self.left_out: List[str] = self.left_src
        self.right_src: List[str] = [
            f.name for f in config["right_schema"].schema
            if f.name not in skip
        ]
        self.right_out: List[str] = config["right_fields"]
        self.residual = config.get("residual_py")
        from ..config import config as get_config

        ttl = config.get(
            "ttl_nanos", int(get_config().pipeline.update_aggregate_ttl * 1e9)
        )
        self.ttl_nanos: Optional[int] = int(ttl) if ttl else None
        # key -> list of payload tuples (may contain duplicates)
        self.state: List[Dict[tuple, List[tuple]]] = [{}, {}]
        self.last_seen: Dict[tuple, int] = {}
        self._lmap = {f: i for i, f in enumerate(self.left_out)}
        self._rmap = {f: i for i, f in enumerate(self.right_out)}
        self._kmap = {f"__key{i}": i for i in range(self.n_keys)}

    def tables(self):
        from ..state.table_config import global_table

        return {"uj": global_table("uj")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("uj")
            for snap in table.all_values():
                for side in (0, 1):
                    for key_vals, rows in snap[str(side)]:
                        key = tuple(key_vals)
                        if self._owns(key, ctx):
                            self.state[side].setdefault(key, []).extend(
                                tuple(r) for r in rows
                            )

    def _owns(self, key: tuple, ctx) -> bool:
        p = ctx.task_info.parallelism
        if p <= 1:
            return True
        from ..types import hash_arrays, hash_column, server_for_hash_array

        cols = [
            hash_column(np.asarray([k])) for k in key
        ]
        owner = server_for_hash_array(hash_arrays(cols), p)[0]
        return owner == ctx.task_info.task_index

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("uj")
            table.put(
                ctx.task_info.task_index,
                {
                    "subtask": ctx.task_info.task_index,
                    "0": [
                        [list(k), [list(r) for r in rows]]
                        for k, rows in self.state[0].items()
                    ],
                    "1": [
                        [list(k), [list(r) for r in rows]]
                        for k, rows in self.state[1].items()
                    ],
                },
            )

    # -- processing ---------------------------------------------------------

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        side = input_index
        schema_names = batch.schema.names
        src_fields = self.left_src if side == 0 else self.right_src
        rows = batch.to_pylist()
        ts = int(
            np.asarray(
                batch.column(schema_names.index(TIMESTAMP_FIELD)).cast(
                    pa.int64()
                )
            ).max()
        )
        # deltas accumulate IN INPUT ORDER as (is_retract, row) so a
        # retract never overtakes the append it cancels within a batch
        deltas: List[Tuple[bool, tuple]] = []
        for row in rows:
            key = tuple(
                _norm(row[f"__key{i}"]) for i in range(self.n_keys)
            )
            payload = tuple(_norm(row[f]) for f in src_fields)
            meta = row.get(UPDATING_META_FIELD)
            self.last_seen[key] = ts
            if meta and meta.get("is_retract"):
                self._retract_row(side, key, payload, deltas)
            else:
                self._append_row(side, key, payload, deltas)
        # emit maximal same-kind runs as batches, preserving order
        i = 0
        while i < len(deltas):
            j = i
            while j < len(deltas) and deltas[j][0] == deltas[i][0]:
                j += 1
            batch_out = self._build(
                [d[1] for d in deltas[i:j]], deltas[i][0], ts
            )
            if batch_out is not None and batch_out.num_rows:
                await collector.collect(batch_out)
            i = j

    # join-delta helpers: rows are (key, left_payload|None, right_payload|None)

    def _null_padded(self, side: int, key: tuple, payload: tuple) -> tuple:
        return (key, payload, None) if side == 0 else (key, None, payload)

    def _joined(self, key: tuple, l: tuple, r: tuple) -> tuple:
        return (key, l, r)

    def _append_row(self, side, key, payload, deltas):
        out_append = _DeltaSink(deltas, False)
        out_retract = _DeltaSink(deltas, True)
        mine = self.state[side].setdefault(key, [])
        other = self.state[1 - side].get(key, [])
        other_outer = (
            self.join_type in ("left", "full") if side == 1
            else self.join_type in ("right", "full")
        )
        my_outer = (
            self.join_type in ("left", "full") if side == 0
            else self.join_type in ("right", "full")
        )
        if other:
            for o in other:
                l, r = (payload, o) if side == 0 else (o, payload)
                out_append.append(self._joined(key, l, r))
            # first row on MY side: the other side's null-padded rows retract
            if not mine and other_outer:
                for o in other:
                    out_retract.append(self._null_padded(1 - side, key, o))
        elif my_outer:
            out_append.append(self._null_padded(side, key, payload))
        mine.append(payload)

    def _retract_row(self, side, key, payload, deltas):
        out_append = _DeltaSink(deltas, False)
        out_retract = _DeltaSink(deltas, True)
        mine = self.state[side].get(key, [])
        try:
            mine.remove(payload)
        except ValueError:
            return  # retraction for an unknown row: drop
        other = self.state[1 - side].get(key, [])
        other_outer = (
            self.join_type in ("left", "full") if side == 1
            else self.join_type in ("right", "full")
        )
        my_outer = (
            self.join_type in ("left", "full") if side == 0
            else self.join_type in ("right", "full")
        )
        if other:
            for o in other:
                l, r = (payload, o) if side == 0 else (o, payload)
                out_retract.append(self._joined(key, l, r))
            # last row on MY side gone: other side's rows become null-padded
            if not mine and other_outer:
                for o in other:
                    out_append.append(self._null_padded(1 - side, key, o))
        elif my_outer:
            out_retract.append(self._null_padded(side, key, payload))
        if not mine:
            self.state[side].pop(key, None)

    async def handle_watermark(self, watermark, ctx, collector):
        """TTL eviction of idle keys (the reference bounds updating state
        with updating_cache.rs the same way). Evicted keys silently drop
        their materialized rows — late retractions for them are ignored."""
        from ..types import WATERMARK_END, WatermarkKind

        if (
            watermark.kind == WatermarkKind.EVENT_TIME
            and self.ttl_nanos
            and watermark.timestamp < WATERMARK_END
        ):
            cutoff = watermark.timestamp - self.ttl_nanos
            stale = [k for k, seen in self.last_seen.items() if seen < cutoff]
            for k in stale:
                self.state[0].pop(k, None)
                self.state[1].pop(k, None)
                self.last_seen.pop(k, None)
        return watermark

    # -- output -------------------------------------------------------------

    def _build(self, rows: List[tuple], is_retract: bool, ts: int):
        n = len(rows)
        lmap, rmap, kmap = self._lmap, self._rmap, self._kmap
        arrays = []
        for f in self.out_schema.schema:
            if f.name in kmap:
                ki = kmap[f.name]
                arrays.append(
                    pa.array([r[0][ki] for r in rows], type=f.type)
                )
            elif f.name == TIMESTAMP_FIELD:
                arrays.append(
                    pa.array(np.full(n, ts, dtype=np.int64)).cast(f.type)
                )
            elif f.name == UPDATING_META_FIELD:
                from ..schema import updating_meta_array

                arrays.append(updating_meta_array(n, is_retract))
            elif f.name in lmap:
                li = lmap[f.name]
                arrays.append(_col(
                    [r[1][li] if r[1] is not None else None for r in rows],
                    f.type,
                ))
            elif f.name in rmap:
                ri = rmap[f.name]
                arrays.append(_col(
                    [r[2][ri] if r[2] is not None else None for r in rows],
                    f.type,
                ))
            else:
                raise KeyError(f"updating join output missing {f.name}")
        batch = pa.RecordBatch.from_arrays(
            arrays, schema=self.out_schema.schema
        )
        if self.residual is not None:
            mask = self.residual(batch)
            batch = batch.filter(mask)
        return batch


def _norm(v):
    """State values must be msgpack-serializable and hashable; pandas
    Timestamps become int nanos."""
    if isinstance(v, pd.Timestamp):
        return v.value
    return v


class _DeltaSink:
    """Appends (is_retract, row) onto the shared in-order delta list."""

    __slots__ = ("deltas", "is_retract")

    def __init__(self, deltas, is_retract):
        self.deltas = deltas
        self.is_retract = is_retract

    def append(self, row):
        self.deltas.append((self.is_retract, row))


def _col(vals, t: pa.DataType) -> pa.Array:
    if pa.types.is_timestamp(t):
        return pa.array(vals, type=pa.int64()).cast(t)
    return pa.array(vals, type=t)


def make_updating_join(config: dict) -> Operator:
    return UpdatingJoinOperator(config)
