"""Delta Lake sink: rolling parquet files + a hand-maintained transaction
log.

Capability parity with the reference's delta support inside the filesystem
connector (/root/reference/crates/arroyo-connectors/src/filesystem/sink/
delta.rs): data lands as parquet through the filesystem sink's two-phase
commit, and every durable commit appends a `_delta_log/<version>.json`
entry with `add` actions, so any Delta reader (Spark, DuckDB, deltalake)
sees an atomic, exactly-once table. The log protocol is written directly
(protocol 1/2, metaData on version 0, add actions with stats) — no
deltalake library dependency.

Crash safety: file visibility is governed by the parent's 2PC (rename on
commit, re-finalized from checkpointed state after a crash). The log append
happens after the rename; if a crash lands between them, `on_start`
reconciles by appending a recovery version for visible parquet files the
log doesn't know yet (re-adding the same path is idempotent in Delta —
it replaces the file's metadata, no data duplication).
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional
import uuid

import pyarrow as pa

from .base import ConnectionSchema, Connector, register_connector
from .filesystem import FileSystemSink

LOG_DIR = "_delta_log"


def _delta_type(t: pa.DataType):
    """Arrow -> Delta (Spark SQL) type mapping for schemaString."""
    if pa.types.is_boolean(t):
        return "boolean"
    if pa.types.is_int8(t):
        return "byte"
    if pa.types.is_int16(t):
        return "short"
    if pa.types.is_int32(t):
        return "integer"
    if pa.types.is_integer(t):  # int64 + unsigned widths
        return "long"
    if pa.types.is_float32(t):
        return "float"
    if pa.types.is_floating(t):
        return "double"
    if pa.types.is_timestamp(t):
        return "timestamp"
    if pa.types.is_date(t):
        return "date"
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return "binary"
    if pa.types.is_decimal(t):
        return f"decimal({t.precision},{t.scale})"
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return {
            "type": "array",
            "elementType": _delta_type(t.value_type),
            "containsNull": True,
        }
    if pa.types.is_struct(t):
        return _delta_struct(t)
    return "string"


def _delta_struct(t) -> dict:
    return {
        "type": "struct",
        "fields": [
            {
                "name": f.name,
                "type": _delta_type(f.type),
                "nullable": bool(f.nullable),
                "metadata": {},
            }
            for f in t
        ],
    }


def schema_string(schema: pa.Schema) -> str:
    """Delta metaData.schemaString for an arrow schema."""
    return json.dumps(_delta_struct(schema))


class DeltaSink(FileSystemSink):
    """Filesystem parquet sink that also maintains the Delta log."""

    def __init__(self, path: str, rollover_rows: int = 100_000):
        super().__init__(path, "parquet", rollover_rows)
        self._arrow_schema: Optional[pa.Schema] = None
        self._table_id = str(uuid.uuid4())

    # -- log plumbing -------------------------------------------------------

    def _log_dir(self) -> str:
        return os.path.join(self.path, LOG_DIR)

    def _log_versions(self) -> List[int]:
        d = self._log_dir()
        if not os.path.isdir(d):
            return []
        out = []
        for n in os.listdir(d):
            if n.endswith(".json"):
                try:
                    out.append(int(n[: -len(".json")]))
                except ValueError:
                    pass
        return sorted(out)

    def _logged_paths(self) -> set:
        """File names already recorded by an add action in any version."""
        seen = set()
        d = self._log_dir()
        for v in self._log_versions():
            with open(os.path.join(d, f"{v:020d}.json")) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    action = json.loads(line)
                    if "add" in action:
                        seen.add(action["add"]["path"])
        return seen

    def _append_log(self, adds: List[dict]):
        """CAS-append the next log version (O_EXCL create). Retries the
        version number on a concurrent writer; actions carry only this
        subtask's files so retried versions stay disjoint."""
        if not adds:
            return
        os.makedirs(self._log_dir(), exist_ok=True)
        versions = self._log_versions()
        next_v = (versions[-1] + 1) if versions else 0
        while True:
            actions = []
            if next_v == 0:
                # version 0 carries the table's protocol + metadata; a CAS
                # retry at a later version must NOT repeat them
                actions.append({
                    "protocol": {"minReaderVersion": 1,
                                 "minWriterVersion": 2}
                })
                actions.append({
                    "metaData": {
                        "id": self._table_id,
                        "format": {"provider": "parquet", "options": {}},
                        "schemaString": schema_string(self._arrow_schema),
                        "partitionColumns": [],
                        "configuration": {},
                        "createdTime": int(time.time() * 1000),
                    },
                })
            actions.extend({"add": a} for a in adds)
            payload = "\n".join(json.dumps(a) for a in actions) + "\n"
            target = os.path.join(self._log_dir(), f"{next_v:020d}.json")
            try:
                with open(target, "x") as f:
                    f.write(payload)
                return
            except FileExistsError:
                next_v += 1

    def _add_action(self, fpath: str) -> dict:
        st = os.stat(fpath)
        action = {
            "path": os.path.relpath(fpath, self.path),
            "size": st.st_size,
            "modificationTime": int(st.st_mtime * 1000),
            "dataChange": True,
            "partitionValues": {},
        }
        try:
            import pyarrow.parquet as pq

            action["stats"] = json.dumps(
                {"numRecords": pq.read_metadata(fpath).num_rows}
            )
        except Exception:  # noqa: BLE001
            pass
        return action

    # -- sink hooks ---------------------------------------------------------

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        if self._arrow_schema is None:
            self._arrow_schema = batch.schema
        await super().process_batch(batch, ctx, collector, input_index)

    async def on_start(self, ctx):
        await super().on_start(ctx)  # re-finalizes committed .tmp files
        # crash between rename and log append: visible parquet files not in
        # the log get a recovery version (idempotent re-add by path)
        if not os.path.isdir(self.path):
            return
        logged = self._logged_paths()
        orphans = [
            os.path.join(self.path, n)
            for n in sorted(os.listdir(self.path))
            if n.endswith(".parquet") and n not in logged
        ]
        if orphans:
            if self._arrow_schema is None:
                import pyarrow.parquet as pq

                self._arrow_schema = pq.read_schema(orphans[0])
            self._append_log([self._add_action(f) for f in orphans])

    async def _committed(self, files: List[str], ctx, epoch=None):
        self._append_log(
            [self._add_action(f) for f in files if os.path.exists(f)]
        )


@register_connector
class DeltaConnector(Connector):
    name = "delta"
    description = "Delta Lake table sink (parquet + transaction log)"
    source = False
    sink = True
    config_schema = {
        "path": {"type": "string", "required": True},
        "rollover_rows": {"type": "integer"},
    }

    def validate_options(self, options, schema):
        if "path" not in options:
            raise ValueError("delta requires a path option")
        out = {"path": options["path"]}
        if "rollover_rows" in options:
            out["rollover_rows"] = int(options["rollover_rows"])
        return out

    def make_sink(self, config, schema: ConnectionSchema):
        return DeltaSink(
            config["path"], config.get("rollover_rows", 100_000)
        )

    def make_source(self, config, schema: ConnectionSchema):
        raise ValueError("delta is sink-only; use the filesystem source")
