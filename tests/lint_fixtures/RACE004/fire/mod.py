"""MUST fire RACE004: `hold` awaits while holding `_lock`, and a
concurrent task root (`mutate`) writes a field that lock guards — the
await window invites lock-ordering stalls and convoying on state the
holder believes is frozen."""
import asyncio

from arroyo_tpu.analysis.races import guarded_by


@guarded_by("_lock", "fired")
class Plan:
    def __init__(self):
        self.fired = []
        self._lock = None


class Driver:
    async def hold(self, plan):
        with plan._lock:
            await asyncio.sleep(0)

    async def mutate(self, plan):
        with plan._lock:
            plan.fired.append(1)

    def start(self, plan):
        asyncio.ensure_future(self.hold(plan))
        asyncio.ensure_future(self.mutate(plan))
