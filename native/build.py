"""Build the native extension: g++ -> arroyo_native.so next to this file.

Invoked automatically on first import attempt (ops/native.py) and cached;
run manually with `python native/build.py` to rebuild.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "slotdir.cpp")
OUT = os.path.join(
    HERE, f"arroyo_native{sysconfig.get_config_var('EXT_SUFFIX') or '.so'}"
)


def build(force: bool = False) -> str:
    if (
        not force
        and os.path.exists(OUT)
        and os.path.getmtime(OUT) >= os.path.getmtime(SRC)
    ):
        return OUT
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        f"-I{include}", SRC, "-o", OUT,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return OUT


if __name__ == "__main__":
    print(build(force="--force" in sys.argv))
