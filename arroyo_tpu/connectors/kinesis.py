"""Placeholder: kinesis connector lands with the connector milestone."""
