"""The RACE00x rule family: lockset + atomicity checks over the fields
declared with the ``shared_state``/``guarded_by`` DSL, on top of the
interprocedural call graph (``callgraph.build`` — cached per Project, so
four rules cost one graph).

All four rules are project-scope: the read, the yield point, and the
conflicting writer typically live in different files. All four analyze
ONLY declared fields — the DSL is the precision contract that keeps a
name-heuristic analysis quiet on the real tree.

RACE001  a declared field is written from >= 2 task-spawn roots, no lock
         is common to all write sites, and the field is not declared
         ``multi_writer``. In a lock-free asyncio program every lockset
         is empty, so the teeth are in the root count: the fix is either
         a ``multi_writer`` declaration (making last-writer-wins an
         explicit, reviewable policy) or serializing the writers.

RACE002  the asyncio TOCTOU: shared state is read, the coroutine crosses
         an ``await`` (any interleaving may run), and a dependent write
         lands without revalidation. Detected by a flow-sensitive
         abstract interpretation of each async function: branch states
         split and merge (a branch-local await does not poison the
         fallthrough path), loop bodies run twice (read-in-iteration-1 /
         write-in-iteration-2 is caught), and staleness tracks both the
         field itself and locals tainted by it (``m = job.f`` ... await
         ... ``job.f = m``). A fresh read after the last await — even in
         the writing statement itself (``job.f = job.f or m``) —
         revalidates and silences the rule. ``multi_writer`` does NOT
         waive RACE002: lost updates are never the design.

RACE003  a ``guarded_by`` field is accessed at a site whose lockset
         (interprocedural entry lockset | locks held at the site) lacks
         the declared lock.

RACE004  an ``await`` (or ``async with`` / ``async for``) is reached
         while holding a ``guarded_by`` lock whose fields some
         *concurrent* root also mutates — the classic
         lock-held-across-yield convoy/starvation hazard.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Project, Rule, dotted_name, register
from . import callgraph
from .callgraph import _MUTATORS, Access, CallGraph, FieldDecl, FuncInfo


def _short(qualname: str) -> str:
    return qualname.split("::", 1)[-1]


def _relevant(func: FuncInfo, access: Access, decl: FieldDecl) -> bool:
    """Receiver-based precision filter: a ``self.field`` access inside a
    class that is not the declaring class is a different attribute that
    happens to share the name. Non-self receivers can't be type-resolved
    and stay in (that's how ``job.stop_requested`` writes in the REST
    layer are seen)."""
    if access.receiver == "self" and func.cls is not None:
        return func.cls == decl.cls
    return True


def _site_lockset(graph: CallGraph, func: FuncInfo,
                  locks: FrozenSet[str]) -> FrozenSet[str]:
    return graph.entry_lockset(func.qualname) | locks


@register
class RaceMultiRootWrite(Rule):
    id = "RACE001"
    name = "race-multi-root-write"
    description = (
        "Shared field written from >= 2 task-spawn roots with no common "
        "lock and no multi_writer declaration; declare the policy or "
        "serialize the writers"
    )
    scope = "project"

    def check_project(self, project: Project) -> Iterable[Finding]:
        graph = callgraph.build(project)
        for field, decl in sorted(graph.decls.items()):
            if decl.multi_writer:
                continue
            writes = [
                (f, a) for f, a in graph.field_writes(field)
                if _relevant(f, a, decl)
            ]
            if not writes:
                continue
            roots: Set[str] = set()
            common: Optional[FrozenSet[str]] = None
            for f, a in writes:
                roots |= graph.roots(f.qualname)
                ls = _site_lockset(graph, f, a.lockset)
                common = ls if common is None else (common & ls)
            if len(roots) < 2 or common:
                continue
            root_names = ", ".join(sorted(_short(r) for r in roots))
            for f, a in writes:
                yield Finding(
                    rule=self.id, path=a.path, line=a.line, col=a.col,
                    message=(
                        f"shared field '{decl.cls}.{field}' is written "
                        f"from {len(roots)} task roots ({root_names}) "
                        f"with no common lock; declare it "
                        f"multi_writer or serialize the writers"
                    ),
                )


# -- RACE002: flow-sensitive atomicity interpretation ------------------------


class _State:
    """Abstract state at a program point. `pending[key]` is the last
    un-overwritten read of a shared access path ("job.stop_requested");
    `taints[name][key]` means local `name` holds a value derived from
    `key`. The bool is 'crossed an await since'."""

    __slots__ = ("pending", "taints")

    def __init__(self):
        self.pending: Dict[str, Tuple[int, bool]] = {}
        self.taints: Dict[str, Dict[str, Tuple[int, bool]]] = {}

    def copy(self) -> "_State":
        st = _State()
        st.pending = dict(self.pending)
        st.taints = {k: dict(v) for k, v in self.taints.items()}
        return st

    def cross(self) -> None:
        for k, (line, _) in self.pending.items():
            self.pending[k] = (line, True)
        for name, per in self.taints.items():
            for k, (line, _) in per.items():
                per[k] = (line, True)


def _merge(a: Optional[_State], b: Optional[_State]) -> Optional[_State]:
    if a is None:
        return b
    if b is None:
        return a
    out = a.copy()
    for k, (line, crossed) in b.pending.items():
        if k in out.pending:
            l0, c0 = out.pending[k]
            out.pending[k] = (min(l0, line), c0 or crossed)
        else:
            out.pending[k] = (line, crossed)
    for name, per in b.taints.items():
        dst = out.taints.setdefault(name, {})
        for k, (line, crossed) in per.items():
            if k in dst:
                l0, c0 = dst[k]
                dst[k] = (min(l0, line), c0 or crossed)
            else:
                dst[k] = (line, crossed)
    return out


def _has_yield_point(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
    return False


class _AtomicityScan:
    """Interpret one async function; findings accumulate in `fired`."""

    def __init__(self, rule: Rule, func: FuncInfo, keys_of):
        self.rule = rule
        self.func = func
        self.keys_of = keys_of  # Attribute node -> access key, or None
        self.fired: Dict[Tuple[str, int], Finding] = {}

    # -- events --------------------------------------------------------------

    def read(self, st: _State, key: str, line: int) -> None:
        st.pending[key] = (line, False)

    def write(self, st: _State, key: str, line: int, col: int,
              value_names: Iterable[str],
              rhs_reads: Iterable[str] = ()) -> None:
        p = st.pending.get(key)
        why = None
        if p and p[1]:
            why = (f"'{key}' read at line {p[0]} crossed an await before "
                   f"this write")
        elif key in rhs_reads and p is not None:
            # the RHS itself re-read the key after the last await
            # (`job.f = job.f or mode`): the write is revalidated
            pass
        else:
            for name in value_names:
                t = st.taints.get(name, {}).get(key)
                if t and t[1]:
                    why = (f"'{key}' was read into '{name}' at line "
                           f"{t[0]} and crossed an await before being "
                           f"written back")
                    break
        if why is not None and (key, line) not in self.fired:
            self.fired[(key, line)] = Finding(
                rule=self.rule.id, path=self.func.path, line=line, col=col,
                message=(
                    f"atomicity violation in {_short(self.func.qualname)}: "
                    f"{why}; another task may have changed it in between — "
                    f"re-read and revalidate after the await"
                ),
            )
        st.pending.pop(key, None)

    # -- expression walk (in evaluation order) -------------------------------

    def eval_expr(self, node: Optional[ast.AST], st: _State) -> None:
        if node is None:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # deferred execution; not this coroutine's timeline
        if isinstance(node, ast.Await):
            self.eval_expr(node.value, st)
            st.cross()
            return
        if isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _MUTATORS
                    and isinstance(func.value, ast.Attribute)):
                key = self.keys_of(func.value)
                if key is not None:
                    self.read(st, key, func.value.lineno)
                    for a in node.args:
                        self.eval_expr(a, st)
                    for kw in node.keywords:
                        self.eval_expr(kw.value, st)
                    # the mutation commits only now: if an argument
                    # awaited, the receiver read above is stale
                    self.write(st, key, node.lineno, node.col_offset, ())
                    return
            for child in ast.iter_child_nodes(node):
                self.eval_expr(child, st)
            return
        if isinstance(node, ast.Attribute):
            key = self.keys_of(node)
            if key is not None and isinstance(node.ctx, ast.Load):
                self.eval_expr(node.value, st)
                self.read(st, key, node.lineno)
                return
            for child in ast.iter_child_nodes(node):
                self.eval_expr(child, st)
            return
        for child in ast.iter_child_nodes(node):
            self.eval_expr(child, st)

    def _value_names(self, node: Optional[ast.AST]) -> List[str]:
        if node is None:
            return []
        return [
            n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        ]

    def _reads_in(self, node: Optional[ast.AST], st: _State) -> List[str]:
        """Access keys read within `node` that are still pending."""
        if node is None:
            return []
        keys = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                key = self.keys_of(sub)
                if key is not None and key in st.pending:
                    keys.append(key)
        return keys

    def assign_target(self, st: _State, target: ast.AST, value_names,
                      read_keys) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self.assign_target(st, el, value_names, read_keys)
            return
        if isinstance(target, ast.Starred):
            self.assign_target(st, target.value, value_names, read_keys)
            return
        if isinstance(target, ast.Name):
            # local now derives from whatever shared keys the RHS read
            per: Dict[str, Tuple[int, bool]] = {}
            for key in read_keys:
                if key in st.pending:
                    per[key] = st.pending[key]
            # and inherits taints of the RHS's locals (m2 = m)
            for name in value_names:
                for key, info in st.taints.get(name, {}).items():
                    if key not in per or info[1]:
                        per[key] = info
            if per:
                st.taints[target.id] = per
            else:
                st.taints.pop(target.id, None)
            return
        if isinstance(target, ast.Attribute):
            key = self.keys_of(target)
            if key is not None:
                self.eval_expr(target.value, st)
                self.write(st, key, target.lineno, target.col_offset,
                           value_names, read_keys)
                return
        if isinstance(target, ast.Subscript):
            inner = target.value
            if isinstance(inner, ast.Attribute):
                key = self.keys_of(inner)
                if key is not None:
                    self.eval_expr(inner.value, st)
                    self.eval_expr(target.slice, st)
                    self.write(st, key, target.lineno, target.col_offset,
                               value_names, read_keys)
                    return
        self.eval_expr(target, st)

    # -- statement walk ------------------------------------------------------

    def exec_block(self, stmts, st: Optional[_State]) -> Optional[_State]:
        for stmt in stmts:
            if st is None:
                return None
            st = self.exec_stmt(stmt, st)
        return st

    def exec_stmt(self, node: ast.AST, st: _State) -> Optional[_State]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return st
        if isinstance(node, ast.Assign):
            self.eval_expr(node.value, st)
            read_keys = self._reads_in(node.value, st)  # pending post-eval
            names = self._value_names(node.value)
            for target in node.targets:
                self.assign_target(st, target, names, read_keys)
            return st
        if isinstance(node, ast.AnnAssign):
            self.eval_expr(node.value, st)
            read_keys = self._reads_in(node.value, st)
            names = self._value_names(node.value)
            self.assign_target(st, node.target, names, read_keys)
            return st
        if isinstance(node, ast.AugAssign):
            # x.f += v re-reads f right here: the RMW is await-free iff
            # the value expression is
            if isinstance(node.target, ast.Attribute):
                key = self.keys_of(node.target)
                if key is not None:
                    self.eval_expr(node.target.value, st)
                    self.read(st, key, node.lineno)
                    self.eval_expr(node.value, st)
                    self.write(st, key, node.lineno, node.col_offset,
                               self._value_names(node.value), (key,))
                    return st
            self.eval_expr(node.value, st)
            read_keys = self._reads_in(node.value, st)
            if isinstance(node.target, ast.Name):
                self.assign_target(st, node.target,
                                   self._value_names(node.value) +
                                   [node.target.id],
                                   read_keys)
            else:
                self.assign_target(st, node.target,
                                   self._value_names(node.value), read_keys)
            return st
        if isinstance(node, (ast.Return, ast.Raise)):
            self.eval_expr(getattr(node, "value", None) or
                           getattr(node, "exc", None), st)
            return None
        if isinstance(node, (ast.Break, ast.Continue)):
            return None
        if isinstance(node, ast.If):
            self.eval_expr(node.test, st)
            a = self.exec_block(node.body, st.copy())
            b = self.exec_block(node.orelse, st.copy())
            return _merge(a, b)
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            return self._exec_loop(node, st)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self.eval_expr(item.context_expr, st)
            if isinstance(node, ast.AsyncWith):
                st.cross()
            return self.exec_block(node.body, st)
        if isinstance(node, ast.Try):
            body_st = self.exec_block(node.body, st.copy())
            h_entry = st.copy()
            if any(_has_yield_point(s) for s in node.body):
                h_entry.cross()  # the body may yield before raising
            h_entry = _merge(h_entry, body_st)
            outs: List[Optional[_State]] = []
            for handler in node.handlers:
                hs = h_entry.copy()
                if handler.type is not None:
                    self.eval_expr(handler.type, hs)
                if handler.name:
                    hs.taints.pop(handler.name, None)
                outs.append(self.exec_block(handler.body, hs))
            if node.orelse and body_st is not None:
                body_st = self.exec_block(node.orelse, body_st)
            outs.append(body_st)
            merged = None
            for o in outs:
                merged = _merge(merged, o)
            if node.finalbody:
                fin_in = merged if merged is not None else h_entry
                return self.exec_block(node.finalbody, fin_in)
            return merged
        if isinstance(node, (ast.Expr, ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(node):
                self.eval_expr(child, st)
            return st
        # anything else (Global, Import, Pass...): walk exprs generically
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.stmt):
                self.eval_expr(child, st)
        return st

    def _exec_loop(self, node, st: _State) -> Optional[_State]:
        if isinstance(node, ast.While):
            pre = lambda s: self.eval_expr(node.test, s)  # noqa: E731
        else:
            self.eval_expr(node.iter, st)
            if isinstance(node, ast.AsyncFor):
                def pre(s):
                    s.cross()  # each iteration awaits the iterator
                    self.assign_target(s, node.target, [], [])
            else:
                def pre(s):
                    self.assign_target(s, node.target, [], [])
        s_in: Optional[_State] = st
        # two symbolic iterations: the second sees iteration-1 state, so
        # read->await->write-next-iteration patterns fire; merging with
        # the pre-loop state keeps the zero-iteration path sound
        for _ in range(2):
            if s_in is None:
                break
            pre(s_in)
            s_out = self.exec_block(node.body, s_in.copy())
            s_in = _merge(s_in, s_out)
        if s_in is not None and node.orelse:
            s_in = self.exec_block(node.orelse, s_in)
        return s_in


@register
class RaceAwaitSpanningRMW(Rule):
    id = "RACE002"
    name = "race-atomicity-await"
    description = (
        "Read-modify-write on shared state spans an await with no "
        "revalidation (asyncio TOCTOU); re-read the field after the "
        "last await before writing"
    )
    scope = "project"

    def check_project(self, project: Project) -> Iterable[Finding]:
        graph = callgraph.build(project)
        if not graph.decls:
            return
        out: List[Finding] = []
        for func in graph.funcs.values():
            if not func.is_async or not func.awaits:
                continue
            if func.name in callgraph._CONSTRUCTORS:
                continue
            if not any(
                _relevant(func, a, graph.decls[a.field])
                for a in func.accesses if a.field in graph.decls
            ):
                continue
            out.extend(self._scan(graph, func))
        return out

    def _scan(self, graph: CallGraph, func: FuncInfo) -> List[Finding]:
        decls = graph.decls

        def keys_of(node: ast.Attribute) -> Optional[str]:
            decl = decls.get(node.attr)
            if decl is None:
                return None
            recv = dotted_name(node.value) or "?"
            if recv == "self" and func.cls is not None \
                    and func.cls != decl.cls:
                return None
            return f"{recv}.{node.attr}"

        scan = _AtomicityScan(self, func, keys_of)
        scan.exec_block(func.node.body, _State())
        return list(scan.fired.values())


@register
class RaceGuardedFieldUnlocked(Rule):
    id = "RACE003"
    name = "race-guarded-by-unlocked"
    description = (
        "guarded_by field accessed at a site whose lockset does not "
        "include the declared lock"
    )
    scope = "project"

    def check_project(self, project: Project) -> Iterable[Finding]:
        graph = callgraph.build(project)
        for field, decl in sorted(graph.decls.items()):
            if decl.guard is None:
                continue
            for func, a in graph.field_accesses(field):
                if not _relevant(func, a, decl):
                    continue
                if func.name in callgraph._CONSTRUCTORS:
                    continue
                if decl.guard in _site_lockset(graph, func, a.lockset):
                    continue
                yield Finding(
                    rule=self.id, path=a.path, line=a.line, col=a.col,
                    message=(
                        f"'{decl.cls}.{field}' is guarded by "
                        f"'{decl.guard}' but this {a.kind} in "
                        f"{_short(func.qualname)} does not hold it"
                    ),
                )


@register
class RaceAwaitUnderLock(Rule):
    id = "RACE004"
    name = "race-await-holding-lock"
    description = (
        "await reached while holding a guarded_by lock whose fields a "
        "concurrent task root mutates; yielding under the lock invites "
        "convoy/starvation"
    )
    scope = "project"

    def check_project(self, project: Project) -> Iterable[Finding]:
        graph = callgraph.build(project)
        guards: Dict[str, List[FieldDecl]] = {}
        for decl in graph.decls.values():
            if decl.guard is not None:
                guards.setdefault(decl.guard, []).append(decl)
        if not guards:
            return
        writer_roots: Dict[str, Set[str]] = {}
        for lock, decls in guards.items():
            roots: Set[str] = set()
            for decl in decls:
                for f, a in graph.field_writes(decl.field):
                    if _relevant(f, a, decl):
                        roots |= graph.roots(f.qualname)
            writer_roots[lock] = roots
        for func in graph.funcs.values():
            entry = graph.entry_lockset(func.qualname)
            for aw in func.awaits:
                held = entry | aw.lockset
                for lock in sorted(held & set(guards)):
                    others = writer_roots[lock] - graph.roots(func.qualname)
                    if not others:
                        continue
                    fields = ", ".join(
                        sorted(d.field for d in guards[lock])
                    )
                    yield Finding(
                        rule=self.id, path=func.path, line=aw.line,
                        col=aw.col,
                        message=(
                            f"{_short(func.qualname)} awaits while "
                            f"holding '{lock}' (guarding {fields}), "
                            f"which concurrent roots also need"
                        ),
                    )
