"""The mutant regression corpus: reintroduced protocol bugs.

Each mutant is a named flag `spec.Model` consults to re-create a bug in
the MODEL (the code stays fixed); the corpus asserts the checker finds a
counterexample for every one. Three are the historical 2PC/recovery bugs
the PR 2 chaos drills originally exposed and fixed — the checker must
never regress below what sampling already caught. The rest guard the
pipelined-checkpoint (PR 8) and fencing invariants that no drill
enumerates exhaustively.

Every entry pins the expected violation KIND and the smallest
configuration that exposes it, so the corpus stays fast enough for CI.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

from .spec import FAULT_KINDS, ModelConfig, VIOLATIONS


class Mutant(NamedTuple):
    name: str
    description: str
    expect_violation: str     # violation-label prefix the corpus asserts
    config: ModelConfig
    historical: bool = False  # one of the PR 2 chaos-found bugs


def _cfg(**kw) -> ModelConfig:
    base = dict(workers=2, epochs=2, inflight=2, faults=0, restarts=2,
                rescales=0, reads=0, fault_kinds=FAULT_KINDS)
    base.update(kw)
    return ModelConfig(**base)


MUTANTS: Dict[str, Mutant] = {
    m.name: m
    for m in [
        Mutant(
            name="stop_strands_commit",
            description=(
                "PR 2 bug #1: the sink does not hold a committing state "
                "at stop — it closes after its stop-epoch flush without "
                "awaiting the phase-2 CommitMsg, and the commit fan-out "
                "silently drops the message to the closed worker. The "
                "sealed sink transaction is stranded uncommitted at "
                "STOPPED."
            ),
            expect_violation=VIOLATIONS.STRANDED,
            config=_cfg(epochs=1, mutant="stop_strands_commit"),
            historical=True,
        ),
        Mutant(
            name="commit_fanout_all_workers",
            description=(
                "PR 2 bug #2: the phase-2 commit fans out to EVERY "
                "worker instead of only those hosting committing "
                "subtasks. A source-only worker legitimately finishes "
                "and closes its rpc server right after the then_stop "
                "barrier, so the commit rpc to it fails, the stop "
                "recovers, retries, and loops to FAILED without any "
                "injected fault."
            ),
            expect_violation=VIOLATIONS.FAILED_NO_FAULT,
            config=_cfg(epochs=1, restarts=1,
                        mutant="commit_fanout_all_workers"),
            historical=True,
        ),
        Mutant(
            name="no_liveness_in_stop_wait",
            description=(
                "PR 2 bug #3: the stop-checkpoint wait does not check "
                "worker liveness, so a worker death mid-barrier leaves "
                "only the 60s deadline to unstick the wait — a stall "
                "the liveness check was added to kill."
            ),
            expect_violation=VIOLATIONS.STALL,
            config=_cfg(epochs=1, faults=1,
                        fault_kinds=("fault.kill",),
                        mutant="no_liveness_in_stop_wait"),
            historical=True,
        ),
        Mutant(
            name="unordered_flush",
            description=(
                "PR 8 invariant: per-subtask checkpoint flushes must be "
                "strictly epoch-ordered — a report for epoch N+1 implies "
                "N's blob is durable, which is what makes abandoning an "
                "overdue epoch and publishing a later one sound. LIFO "
                "flushes break the chain: a manifest can publish "
                "referencing an unflushed blob."
            ),
            expect_violation=VIOLATIONS.ATOMIC,
            config=_cfg(epochs=2, inflight=2, faults=1,
                        fault_kinds=("fault.kill",),
                        mutant="unordered_flush"),
        ),
        Mutant(
            name="unstamped_data_paths",
            description=(
                "PR 8 invariant: checkpoint data paths are generation-"
                "stamped so a fenced zombie's late upload cannot "
                "overwrite a live incarnation's blob for the same "
                "(epoch, table, subtask). Unstamped paths let a "
                "presumed-dead worker clobber live state."
            ),
            expect_violation=VIOLATIONS.OVERWRITE,
            config=_cfg(epochs=2, faults=1,
                        fault_kinds=("fault.blackout",),
                        mutant="unstamped_data_paths"),
        ),
        Mutant(
            name="publish_any_complete",
            description=(
                "pipelined-reap invariant: manifests must publish in "
                "strict epoch order (manifest N+1 references chain "
                "blobs first recorded in N). Publishing whichever "
                "pending epoch completes first breaks the order."
            ),
            expect_violation=VIOLATIONS.ORDER,
            config=_cfg(epochs=2, inflight=2,
                        mutant="publish_any_complete"),
        ),
        Mutant(
            name="publish_without_reports",
            description=(
                "reap-guard invariant: an epoch publishes only once its "
                "full report set arrived. Publishing early half-commits "
                "the epoch — the manifest references blobs nobody "
                "flushed."
            ),
            expect_violation=VIOLATIONS.ATOMIC,
            config=_cfg(epochs=1, mutant="publish_without_reports"),
        ),
        Mutant(
            name="no_fence_check",
            description=(
                "generation-fencing invariant: a superseded generation "
                "must be fenced at publish (protocol.check_current). "
                "Without the check a zombie controller publishes "
                "manifests under a stale generation."
            ),
            expect_violation=VIOLATIONS.FENCE,
            config=_cfg(epochs=2, faults=1,
                        fault_kinds=("fault.fence",),
                        mutant="no_fence_check"),
        ),
        Mutant(
            name="overlap_double_emission",
            description=(
                "generation-overlap rescale invariant (ISSUE 15): the "
                "new incarnation is prepared against the last PUBLISHED "
                "manifest while the old incarnation drains its final "
                "epoch, and activation must advance the restore to the "
                "durable rescale checkpoint (the stop epoch) before "
                "releasing sources. The mutant activates at the PREPARED "
                "epoch instead — sources rewind behind the stop epoch "
                "and the new generation re-seals output the old "
                "generation already committed: the same epoch becomes "
                "visible under two generations."
            ),
            expect_violation=VIOLATIONS.OVERLAP_EMIT,
            config=_cfg(epochs=1, inflight=2, rescales=1, overlap=1,
                        mutant="overlap_double_emission"),
        ),
        Mutant(
            name="promote_while_primary_alive",
            description=(
                "hot-standby failover invariant (ISSUE 17): promotion "
                "must re-resolve the LATEST published manifest when it "
                "claims the fresh generation — the standby's tailed "
                "restore may be an epoch behind a primary that is "
                "merely slow (heartbeat blackout), not dead. The mutant "
                "promotes at the standby's tailed epoch instead: the "
                "still-running primary already published and committed "
                "a later epoch, so the promoted generation rewinds "
                "behind visible output and re-emits it — the "
                "overlap_double_emission invariant generalized to "
                "failover."
            ),
            expect_violation=VIOLATIONS.OVERLAP_EMIT,
            config=_cfg(epochs=1, inflight=2, faults=1,
                        fault_kinds=("fault.blackout",), standby=1,
                        mutant="promote_while_primary_alive"),
        ),
        Mutant(
            name="serve_reads_unpublished_epoch",
            description=(
                "StateServe invariant (ISSUE 12): queryable-state reads "
                "serve at the last PUBLISHED epoch — the worker-side "
                "view folds sealed epochs only up to the published "
                "epoch the gateway resolved. The mutant reads at the "
                "controller's last ISSUED epoch instead: a fanned-out-"
                "but-unpublished checkpoint, i.e. a half-captured view "
                "no manifest has made durable (and, post-recovery, one "
                "a fenced generation may be superseding)."
            ),
            expect_violation=VIOLATIONS.SERVE,
            config=_cfg(epochs=2, inflight=2, reads=1, faults=1,
                        fault_kinds=("fault.kill",),
                        mutant="serve_reads_unpublished_epoch"),
        ),
        Mutant(
            name="follower_serves_unpublished_epoch",
            description=(
                "follower read-replica invariant (ISSUE 20): a follower "
                "may LAG the published epoch, never lead it — every "
                "(re)attach must re-resolve latest.json from storage "
                "and every tail advances only to a published manifest. "
                "The mutant reattaches a died follower from the "
                "controller's in-memory issued-epoch counter instead of "
                "re-resolving latest.json: the counter is ahead of "
                "publication whenever a checkpoint is in flight, so the "
                "reattached follower serves a fanned-out-but-"
                "unpublished epoch no manifest has made durable."
            ),
            expect_violation=VIOLATIONS.REPLICA,
            config=_cfg(epochs=1, inflight=2, reads=1, faults=1,
                        followers=1,
                        fault_kinds=("fault.follower_die",),
                        mutant="follower_serves_unpublished_epoch"),
        ),
        Mutant(
            name="transitions_missing_recovering",
            description=(
                "state-machine mutant: the CHECKPOINT_STOPPING -> "
                "RECOVERING edge is deleted from TRANSITIONS. A stop "
                "checkpoint failure then has no legal move — the "
                "extracted-table conformance catches the illegal "
                "transition."
            ),
            expect_violation=VIOLATIONS.ILLEGAL_MOVE,
            config=_cfg(epochs=1, faults=1,
                        fault_kinds=("fault.cas_race",),
                        mutant="transitions_missing_recovering"),
        ),
    ]
}


def get_mutant(name: str) -> Mutant:
    if name not in MUTANTS:
        raise KeyError(
            f"unknown mutant {name!r}; known: {sorted(MUTANTS)}"
        )
    return MUTANTS[name]


def historical_mutants() -> Tuple[Mutant, ...]:
    return tuple(m for m in MUTANTS.values() if m.historical)
