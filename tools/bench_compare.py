#!/usr/bin/env python3
"""Noise-aware bench regression gate (ISSUE 6).

Compare a bench.py JSON line against a pinned baseline and decide, per
metric, whether the delta is a real regression or in-spread wobble. The
threshold is derived from the MEASURED run spread, not a fixed
percentage: BENCH_r01-r05 show ±15%+ run-to-run variance on the shared
bench host, so any fixed gate either cries wolf or sleeps through 2x
losses.

Per metric the allowed relative delta is

    allowed = max(baseline_spread, current_spread, floor) * margin

where spread comes from the metric's own `*_runs` array (max-min over
median, the same dispersion bench.py publishes as `*_spread_pct`) when
present, and `floor` is the class floor otherwise (throughput metrics
default 10%, latency metrics 25% — latency percentiles rest on tens of
samples). Throughput metrics (`value`, `*_eps`) regress downward;
latency metrics (`*_ms`) regress upward. Count/diagnostic fields
(rows, events, spreads, compile seconds, calibration) are reported but
never gated.

A baseline or current measured on a CONTENDED host (bench.py's
calibration probe) widens every floor by the contention factor — the
numbers were taken under interference and say less.

Both documents carry a `pin_era` stamp (bench.py PIN_ERA): the bench
era the numbers were measured under. A baseline pinned under a
different era than the current run is rejected OUTRIGHT (exit 2) —
cross-era eps comparisons silently trend instead of gating (ISSUE 17).

Exit status: 0 = no regression, 1 = at least one metric regressed,
2 = usage/IO error or pin_era mismatch. `--json` writes the full
comparison for CI upload.

Usage:
  python tools/bench_compare.py BENCH_BASELINE.json current.json \
      [--json comparison.json] [--margin 1.5] [--floor-pct 10] \
      [--latency-floor-pct 25]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, Optional


def baseline_provenance(path: str) -> dict:
    """Which baseline the gate compared against: the file path plus the
    commit that last touched it (BENCH_r05 kept pre-PR-1 mesh numbers
    next to post-PR-1 prose for five rounds because nothing ever printed
    what was actually pinned — the report now names it)."""
    prov = {"file": os.path.abspath(path)}
    try:
        out = subprocess.run(
            ["git", "log", "-1", "--format=%h %cs %s", "--", path],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(path)) or ".",
        ).stdout.strip()
        if out:
            prov["commit"] = out.split()[0]
            prov["committed"] = out.split()[1]
            prov["subject"] = out.split(" ", 2)[2] if len(
                out.split(" ", 2)) > 2 else ""
    except Exception:  # noqa: BLE001 - provenance is best-effort
        pass
    return prov


def check_pin_era(baseline: dict, current: dict) -> Optional[str]:
    """Cross-era guard (ISSUE 17): a baseline pinned under one bench era
    (host class, event counts, harness methodology) must never gate a run
    measured under another — the eps deltas would silently trend instead
    of measuring anything. Returns an error string on mismatch, None when
    the comparison is era-valid. Era-less documents on BOTH sides are
    pre-era legacy and pass with a warning from the caller; an era on
    exactly one side is itself a mismatch (somebody re-pinned or forgot
    to)."""
    b, c = baseline.get("pin_era"), current.get("pin_era")
    if b is None and c is None:
        return None
    if b != c:
        return (f"pin_era mismatch: baseline pinned under era {b!r}, "
                f"current measured under era {c!r} — cross-era eps "
                "comparisons are meaningless; re-pin BENCH_BASELINE.json "
                "from a run of the current harness (bench.py PIN_ERA)")
    return None


def _spread_pct(doc: dict, metric: str) -> Optional[float]:
    """The metric's own measured dispersion: (max - min) / median over
    its published runs array, in percent."""
    runs_key = {
        "value": "value_runs",
    }.get(metric, f"{metric}_runs")
    runs = doc.get(runs_key)
    if not isinstance(runs, list) or len(runs) < 2:
        if metric == "value":
            v = doc.get("value_spread_pct")
            return float(v) if isinstance(v, (int, float)) else None
        return None
    rs = sorted(float(r) for r in runs)
    med = rs[(len(rs) - 1) // 2]
    if med <= 0:
        return None
    return 100.0 * (rs[-1] - rs[0]) / med


def classify(metric: str) -> Optional[str]:
    """'higher' (throughput), 'lower' (latency/cost), or None (not
    gated)."""
    if metric == "value" or metric.endswith("_eps"):
        return "higher"
    if metric.endswith("_ms"):
        return "lower"
    # state-at-scale costs (ISSUE 8): checkpoint capture latency and
    # amortized upload volume both regress UPWARD
    if metric.endswith("_ms_p99") or metric.endswith("_bytes_per_epoch"):
        return "lower"
    # multi-tenant control plane (ISSUE 10): concurrent jobs one
    # controller holds regresses DOWNWARD; idle CPU per parked job and
    # API p99 (both *_ms) already classify as lower-is-better above
    if metric.endswith("_jobs_per_controller"):
        return "higher"
    # fleet observatory (ISSUE 11): attribution overhead (instrumented
    # vs uninstrumented q5 eps, in percentage points) regresses upward —
    # gated in ABSOLUTE points (see compare), because a relative delta
    # on a near-zero overhead is pure noise. loop_lag_ms_p99 already
    # classifies as lower-is-better via the *_ms_p99 suffix above.
    if metric.endswith("_overhead_pct"):
        return "lower_abs"
    # StateServe (ISSUE 12): cache hit ratio regresses DOWNWARD; the
    # read-path latency (serve_read_*_ms) and throughput keys
    # (serve_lookup_eps, serve_pipeline_eps) classify via the suffix
    # rules above
    if metric.endswith("_hit_pct"):
        return "higher"
    # Watchtower (ISSUE 13): correctness counts that must be EXACTLY
    # zero — no spread, no margin. One false-positive page or one wrong
    # served value is a red gate, full stop.
    if (metric.endswith("_false_positive_count")
            or metric.endswith("_wrong_values")):
        return "zero"
    # Follower replicas (ISSUE 20): worker QueryState RPCs issued while
    # followers are mounted must be EXACTLY zero — the whole point of
    # the tier is that durable-job reads never touch workers. Staleness
    # percentiles (serve_staleness_*) are deliberately suffix-less here:
    # the harness itself hard-fails any read beyond one checkpoint
    # interval, so the comparison only reports them.
    if metric.endswith("_worker_rpcs"):
        return "zero"
    # fused segment runtime (ISSUE 14): stateless-chain dispatches per
    # batch regress UPWARD — a segment silently splitting back into
    # per-operator dispatches (or a new operator joining the chain
    # unfused) shows up here before it shows up as an eps loss
    if metric.endswith("_per_batch"):
        return "lower"
    return None


def compare(baseline: dict, current: dict, margin: float = 1.5,
            floor_pct: float = 10.0,
            latency_floor_pct: float = 25.0) -> dict:
    """Full comparison document: per-metric verdicts + overall status."""
    contended = bool(baseline.get("contended")) or bool(
        current.get("contended"))
    results: Dict[str, dict] = {}
    regressions = []
    for metric in sorted(set(baseline) & set(current)):
        direction = classify(metric)
        if direction is None:
            continue
        b, c = baseline[metric], current[metric]
        if not isinstance(b, (int, float)) or not isinstance(c, (int, float)):
            continue
        if direction == "zero":
            status = "ok" if c == 0 else "regression"
            if status == "regression":
                regressions.append(metric)
            results[metric] = {
                "baseline": b, "current": c,
                "delta_pct": float(c), "allowed_pct": 0.0,
                "spread_pcts": [], "direction": direction,
                "status": status,
            }
            continue
        if direction == "lower_abs":
            # absolute-points gate (attribution overhead): the value IS
            # already a percentage, and its baseline is legitimately ~0,
            # so relative deltas are meaningless. Allowed drift: 2 points
            # (the <2% acceptance bar), widened with the latency floor
            # (CI runners pass a bigger one) and under contention.
            allowed_pts = max(2.0, latency_floor_pct / 12.5)
            if contended:
                allowed_pts *= 1.5
            delta = c - b
            status = ("regression" if delta > allowed_pts
                      else "improved" if delta < -allowed_pts else "ok")
            if status == "regression":
                regressions.append(metric)
            results[metric] = {
                "baseline": b, "current": c,
                "delta_pct": round(delta, 2),
                "allowed_pct": round(allowed_pts, 2),
                "spread_pcts": [],
                "direction": direction,
                "status": status,
            }
            continue
        if not b or not c:
            # 0 means "that query failed that round" — a wedge, not a
            # perf number; flag a current-side 0 against a real baseline
            status = "regression" if b and not c else "missing"
            results[metric] = {"baseline": b, "current": c,
                               "status": status}
            if status == "regression":
                regressions.append(metric)
            continue
        floor = latency_floor_pct if direction == "lower" else floor_pct
        spreads = [s for s in (_spread_pct(baseline, metric),
                               _spread_pct(current, metric)) if s]
        allowed = max(spreads + [floor]) * margin
        if contended:
            allowed *= 1.5
        delta_pct = 100.0 * (c - b) / b
        bad = (-delta_pct if direction == "higher" else delta_pct)
        if bad > allowed:
            status = "regression"
            regressions.append(metric)
        elif bad < -allowed:
            status = "improved"
        else:
            status = "ok"
        results[metric] = {
            "baseline": b, "current": c,
            "delta_pct": round(delta_pct, 1),
            "allowed_pct": round(allowed, 1),
            "spread_pcts": [round(s, 1) for s in spreads],
            "direction": direction,
            "status": status,
        }
    return {
        "status": "regression" if regressions else "ok",
        "regressions": regressions,
        "contended": contended,
        "margin": margin,
        # the era both documents were measured under (check_pin_era has
        # already rejected a mismatch by the time compare() runs)
        "pin_era": current.get("pin_era") or baseline.get("pin_era"),
        "metrics": results,
    }


def render(doc: dict, out=sys.stdout) -> None:
    prov = doc.get("baseline_provenance")
    if prov:
        line = f"gating against baseline {prov['file']}"
        if prov.get("commit"):
            line += (f" (pinned at commit {prov['commit']}"
                     + (f", {prov['committed']}" if prov.get("committed")
                        else "")
                     + ")")
        print(line, file=out)
    width = max([len(m) for m in doc["metrics"]] + [6])
    for metric, r in doc["metrics"].items():
        if r["status"] == "missing":
            print(f"  {metric:<{width}}  MISSING "
                  f"(baseline={r['baseline']} current={r['current']})",
                  file=out)
            continue
        flag = {"ok": " ", "improved": "+", "regression": "!"}[r["status"]]
        print(f"{flag} {metric:<{width}}  {r['baseline']:>12} -> "
              f"{r['current']:>12}  {r['delta_pct']:+6.1f}% "
              f"(allowed ±{r['allowed_pct']}%)", file=out)
    print(f"\nverdict: {doc['status'].upper()}"
          + (f" — {', '.join(doc['regressions'])}"
             if doc["regressions"] else "")
          + (" [contended host: thresholds widened]"
             if doc["contended"] else ""), file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("baseline", help="pinned baseline bench JSON")
    ap.add_argument("current", help="fresh bench JSON to gate")
    ap.add_argument("--json", help="write the comparison document here")
    ap.add_argument("--margin", type=float, default=1.5,
                    help="multiplier over the measured spread")
    ap.add_argument("--floor-pct", type=float, default=10.0,
                    help="minimum allowed delta for throughput metrics")
    ap.add_argument("--latency-floor-pct", type=float, default=25.0,
                    help="minimum allowed delta for latency metrics")
    args = ap.parse_args(argv)
    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
        with open(args.current) as f:
            current = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    era_error = check_pin_era(baseline, current)
    if era_error:
        print(f"bench_compare: {era_error}", file=sys.stderr)
        return 2
    if "pin_era" not in baseline:
        print("bench_compare: warning: baseline carries no pin_era stamp "
              "(pre-era pin) — cannot verify the current run is "
              "era-comparable", file=sys.stderr)
    doc = compare(baseline, current, margin=args.margin,
                  floor_pct=args.floor_pct,
                  latency_floor_pct=args.latency_floor_pct)
    doc["baseline_provenance"] = baseline_provenance(args.baseline)
    render(doc)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2)
    return 1 if doc["status"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main())
